"""Prometheus-style metrics (weed/stats/metrics.go — the reference
defines vectors per role and serves them on -metricsPort; ours is a
minimal in-process registry rendered in the Prometheus text format on
each server's /metrics endpoint), plus the push-gateway loop
(metrics.go:534 LoopPushingMetric)."""

from __future__ import annotations

import threading
import urllib.parse
from collections import defaultdict


# latency buckets in SECONDS, 5ms through 10s: loopback slice
# fetches sit in the low buckets, WAN shard pulls in the high ones —
# the EC rebuild observation range
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)

# group-commit observability (util/group_commit.py): batch sizes are
# small integers (mean batch = sum/count is the headline number), and
# barrier waits live in the 100us..100ms band between "rode a batch
# for free" and "waited out an fsync" — DEFAULT_BUCKETS can't resolve
# either
GROUP_COMMIT_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0,
                              128.0)
GROUP_COMMIT_WAIT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                             0.005, 0.01, 0.025, 0.05, 0.1, 0.25)

# filer meta-plane sub-stages (filer/meta_plane.py): serialize and
# barrier live in the 50us..25ms band; the async apply's per-event
# share sits near the bottom of it.  Mean = sum/count is the number
# bench.py's meta sub-stage split reports per arm.
META_SUB_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                    0.005, 0.01, 0.025, 0.05, 0.1, 0.25)


def escape_label_value(v) -> str:
    """Prometheus text-format label escaping (exposition format §text
    "label_value can be any sequence of UTF-8 characters, but the
    backslash, double-quote, and line-feed characters have to be
    escaped as \\\\, \\", and \\n"): an unescaped source url or error
    string must not tear the exposition line."""
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


class Metrics:
    def __init__(self, namespace: str):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = defaultdict(float)
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._hists: dict[tuple[str, tuple], dict] = {}
        self._help: dict[str, str] = {}
        # shared observer memo for hot call sites whose OWNER object
        # is transient (per-request StageTracks, module functions):
        # caller-chosen hashable key -> observer closure.  Call sites
        # with a long-lived owner (HttpServer) keep their own dict.
        self.obs_memo: dict = {}

    def counter_add(self, name: str, value: float = 1.0,
                    help_text: str = "", **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value
            if help_text:
                self._help.setdefault(name, help_text)

    def gauge_set(self, name: str, value: float, help_text: str = "",
                  **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value
            if help_text:
                self._help.setdefault(name, help_text)

    def histogram_observe(self, name: str, value: float,
                          buckets: "tuple[float, ...]" = DEFAULT_BUCKETS,
                          help_text: str = "", **labels) -> None:
        """Prometheus histogram (metrics.go uses prometheus.Histogram
        for the same surfaces — request/operation latencies)."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = {
                    "buckets": tuple(buckets),
                    "counts": [0] * (len(buckets) + 1),  # +Inf last
                    "sum": 0.0, "count": 0}
            for i, le in enumerate(h["buckets"]):
                if value <= le:
                    h["counts"][i] += 1
                    break
            else:
                h["counts"][-1] += 1
            h["sum"] += value
            h["count"] += 1
            if help_text:
                self._help.setdefault(name, help_text)

    def observer(self, name: str,
                 buckets: "tuple[float, ...]" = DEFAULT_BUCKETS,
                 help_text: str = "", **labels):
        """Pre-resolved histogram observe (ROADMAP 1d): the per-call
        overhead of `histogram_observe` — building
        `tuple(sorted(labels.items()))`, probing the registry dict,
        re-interning the help text — was bisected at ~10-15% of a
        saturated filer, paid again for every observation of a label
        set that never changes.  This resolves the (metric, labelset)
        cell ONCE and returns a closure over its mutable dict; the
        closure does only the bucket scan under the registry lock, and
        is freely shareable across threads.  Hot call sites cache one
        observer per label set (first observe) instead of calling
        histogram_observe per request."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = {
                    "buckets": tuple(buckets),
                    "counts": [0] * (len(buckets) + 1),  # +Inf last
                    "sum": 0.0, "count": 0}
            if help_text:
                self._help.setdefault(name, help_text)
        lock = self._lock
        bkts = h["buckets"]
        counts = h["counts"]

        def observe(value: float) -> None:
            with lock:
                for i, le in enumerate(bkts):
                    if value <= le:
                        counts[i] += 1
                        break
                else:
                    counts[-1] += 1
                h["sum"] += value
                h["count"] += 1

        return observe

    def batch_observer(self, name: str,
                       buckets: "tuple[float, ...]" = DEFAULT_BUCKETS,
                       help_text: str = "", **labels):
        """Bulk sibling of `observer`: consumes a whole numpy array of
        values in one lock round, bucketing with np.searchsorted —
        the native-plane flight-record drain observes thousands of
        stage samples per tick, where even the pre-resolved
        per-value closure was a measurable share of one core."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = {
                    "buckets": tuple(buckets),
                    "counts": [0] * (len(buckets) + 1),  # +Inf last
                    "sum": 0.0, "count": 0}
            if help_text:
                self._help.setdefault(name, help_text)
        lock = self._lock
        bkts = h["buckets"]
        counts = h["counts"]

        def observe_batch(values) -> None:
            n = len(values)
            if not n:
                return
            import numpy as np
            vals = np.asarray(values, dtype=np.float64)
            # side="left": first bucket with le >= value, matching
            # the scalar closure's `value <= le` scan
            idx = np.searchsorted(np.asarray(bkts), vals, side="left")
            per = np.bincount(idx, minlength=len(counts))
            total = float(vals.sum())
            with lock:
                for i, c in enumerate(per.tolist()):
                    if c:
                        counts[i] += c
                h["sum"] += total
                h["count"] += n

        return observe_batch

    def counter_value(self, name: str, **labels) -> "float | None":
        """Read one counter cell (exact label set), or None if that
        cell has never been incremented — the autopilot's sensors
        need the distinction: an absent counter is a sensor gap (hold
        the knob), a zero delta is evidence."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key)

    def counter_sum(self, name: str, **labels) -> float:
        """Sum a counter across every label set that carries at least
        the given labels (the programmatic twin of the shell's
        `_counter_sum` over rendered text)."""
        want = set(labels.items())
        with self._lock:
            return sum(v for (n, ls), v in self._counters.items()
                       if n == name and want.issubset(ls))

    def gauge_value(self, name: str, **labels) -> "float | None":
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key)

    def histogram_merged(self, name: str) -> "dict | None":
        """Snapshot of histogram `name` merged across every label set
        (the QoS feedback throttle's foreground-latency source: it
        wants 'this role's request_seconds', not one method+code
        cell).  Returns {"buckets", "counts", "sum", "count"} or None
        when the histogram has never been observed."""
        merged: "dict | None" = None
        with self._lock:
            for (n, _labels), h in self._hists.items():
                if n != name:
                    continue
                if merged is None:
                    merged = {"buckets": h["buckets"],
                              "counts": list(h["counts"]),
                              "sum": h["sum"], "count": h["count"]}
                elif merged["buckets"] == h["buckets"]:
                    merged["counts"] = [
                        a + b for a, b in zip(merged["counts"],
                                              h["counts"])]
                    merged["sum"] += h["sum"]
                    merged["count"] += h["count"]
        return merged

    def render(self) -> str:
        """Prometheus text exposition format."""
        out = []
        with self._lock:
            seen_types: set[str] = set()
            for store, mtype in ((self._counters, "counter"),
                                 (self._gauges, "gauge")):
                for (name, labels), value in sorted(store.items()):
                    full = f"{self.namespace}_{name}"
                    if full not in seen_types:
                        if name in self._help:
                            out.append(f"# HELP {full} "
                                       f"{self._help[name]}")
                        out.append(f"# TYPE {full} {mtype}")
                        seen_types.add(full)
                    if labels:
                        lbl = ",".join(
                            f'{k}="{escape_label_value(v)}"'
                            for k, v in labels)
                        out.append(f"{full}{{{lbl}}} {value}")
                    else:
                        out.append(f"{full} {value}")
            for (name, labels), h in sorted(self._hists.items()):
                full = f"{self.namespace}_{name}"
                if full not in seen_types:
                    if name in self._help:
                        out.append(f"# HELP {full} {self._help[name]}")
                    out.append(f"# TYPE {full} histogram")
                    seen_types.add(full)
                base = [f'{k}="{escape_label_value(v)}"'
                        for k, v in labels]
                cum = 0
                for le, n in zip(h["buckets"], h["counts"]):
                    cum += n
                    lbl = ",".join(base + [f'le="{le}"'])
                    out.append(f"{full}_bucket{{{lbl}}} {cum}")
                lbl = ",".join(base + ['le="+Inf"'])
                out.append(f"{full}_bucket{{{lbl}}} {h['count']}")
                suffix = f"{{{','.join(base)}}}" if base else ""
                out.append(f"{full}_sum{suffix} {h['sum']}")
                out.append(f"{full}_count{suffix} {h['count']}")
        return "\n".join(out) + "\n"


# process-wide registry for cross-cutting planes that predate any one
# role's registry: unified retry/backoff (util/retry), the per-peer
# circuit breakers, failpoint triggers (faults.py), and EC degraded-
# read/failover counters.  Every role's /metrics appends its
# exposition (render_process) after the role registry's own — the
# namespaces differ, so the two blocks never collide.
PROCESS = Metrics("seaweedfs_tpu")


def _proc_tree_sample() -> "tuple[float, float, int] | None":
    """(cpu_seconds, rss_bytes, process_count) for this process's
    whole /proc subtree — pre-fork SO_REUSEPORT workers and native
    plane children included, transitively.  One /proc pass builds the
    ppid map; the walk is in-memory.  None where /proc is absent
    (non-Linux); self's cutime/cstime ride along so already-reaped
    children (a restarted native plane) stay accounted.

    Root selection: SEAWEEDFS_TPU_TREE_ROOT when set AND alive (the
    filer pre-fork parent exports its own pid before spawning
    SO_REUSEPORT siblings, so a scrape the kernel routed to any ONE
    worker still reports the whole fleet), else this process."""
    import os
    me = os.getpid()
    try:
        me = int(os.environ.get("SEAWEEDFS_TPU_TREE_ROOT", "") or me)
    except ValueError:
        pass
    try:
        clk = os.sysconf("SC_CLK_TCK")
        page = os.sysconf("SC_PAGE_SIZE")
        names = os.listdir("/proc")
    except (OSError, ValueError, AttributeError):
        return None
    info: "dict[int, tuple[int, float, float, float]]" = {}
    for d in names:
        if not d.isdigit():
            continue
        try:
            with open(f"/proc/{d}/stat", "rb") as f:
                raw = f.read(4096)
            # fields after the ")" of comm (proc(5)): [1]=ppid,
            # [11]=utime, [12]=stime, [13]=cutime, [14]=cstime,
            # [21]=rss pages
            parts = raw.rsplit(b") ", 1)[1].split()
            info[int(d)] = (
                int(parts[1]),
                (int(parts[11]) + int(parts[12])) / clk,
                (int(parts[13]) + int(parts[14])) / clk,
                int(parts[21]) * page)
        except (OSError, IndexError, ValueError):
            continue
    if me not in info:
        # stale TREE_ROOT (pre-fork parent died): degrade to self
        me = os.getpid()
        if me not in info:
            return None
    kids: "dict[int, list[int]]" = {}
    for pid, (ppid, _c, _rc, _r) in info.items():
        kids.setdefault(ppid, []).append(pid)
    cpu = rss = 0.0
    count = 0
    stack, seen = [me], set()
    while stack:
        pid = stack.pop()
        if pid in seen or pid not in info:
            continue
        seen.add(pid)
        _ppid, own, reaped, mem = info[pid]
        cpu += own + reaped
        rss += mem
        count += 1
        stack.extend(kids.get(pid, ()))
    return cpu, rss, count


def render_process() -> str:
    # process CPU, refreshed per scrape — operator visibility
    # (cluster.top / any Prometheus scrape can divide its delta by
    # request-rate deltas per node).  os.times() covers every thread
    # and costs ~1us; the TREE gauges below close the gap this
    # per-process number used to leave open: a filer in -workers mode
    # answers each scrape from ONE random SO_REUSEPORT worker, and
    # the native write/read planes are separate child processes — the
    # /proc subtree walk charges all of them to the listener the
    # operator actually scraped.
    import os
    t = os.times()
    PROCESS.gauge_set(
        "process_cpu_seconds", t[0] + t[1],
        help_text="user+system CPU consumed by this process "
                  "(cumulative; exported as a gauge)")
    tree = _proc_tree_sample()
    if tree is not None:
        cpu, rss, count = tree
        PROCESS.gauge_set(
            "process_tree_cpu_seconds", round(cpu, 3),
            help_text="user+system CPU of this process's whole /proc "
                      "subtree (pre-fork workers + native plane "
                      "children; cumulative, refreshed per scrape)")
        PROCESS.gauge_set(
            "process_tree_rss_bytes", rss,
            help_text="resident set of this process's whole /proc "
                      "subtree (shared pages double-counted across "
                      "forked workers)")
        PROCESS.gauge_set(
            "process_tree_procs", float(count),
            help_text="processes in this node's /proc subtree")
    return PROCESS.render()


class MetricsPusher:
    """LoopPushingMetric (metrics.go:534): periodically PUT the
    rendered registry to a Prometheus pushgateway at
    /metrics/job/<job>/instance/<instance>.  Push failures are
    logged-and-retried, never fatal — metrics delivery must not take
    a data server down."""

    def __init__(self, metrics: "Metrics", job: str, instance: str,
                 gateway: str, interval: float = 15.0):
        from .server.httpd import http_bytes
        self._http = http_bytes
        self.metrics = metrics
        self.gateway = gateway
        self.interval = interval
        self.path = (f"/metrics/job/{urllib.parse.quote(job)}"
                     f"/instance/{urllib.parse.quote(instance)}")
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def push_once(self) -> bool:
        try:
            st, _, _ = self._http(
                "PUT", f"{self.gateway}{self.path}",
                self.metrics.render().encode(),
                {"Content-Type": "text/plain; version=0.0.4"})
            return st < 300
        except OSError:
            return False

    def start(self) -> "MetricsPusher":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.push_once()
