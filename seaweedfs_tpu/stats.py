"""Prometheus-style metrics (weed/stats/metrics.go — the reference
defines vectors per role and serves them on -metricsPort; ours is a
minimal in-process registry rendered in the Prometheus text format on
each server's /metrics endpoint)."""

from __future__ import annotations

import threading
from collections import defaultdict


class Metrics:
    def __init__(self, namespace: str):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = defaultdict(float)
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._help: dict[str, str] = {}

    def counter_add(self, name: str, value: float = 1.0,
                    help_text: str = "", **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value
            if help_text:
                self._help.setdefault(name, help_text)

    def gauge_set(self, name: str, value: float, help_text: str = "",
                  **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value
            if help_text:
                self._help.setdefault(name, help_text)

    def render(self) -> str:
        """Prometheus text exposition format."""
        out = []
        with self._lock:
            seen_types: set[str] = set()
            for store, mtype in ((self._counters, "counter"),
                                 (self._gauges, "gauge")):
                for (name, labels), value in sorted(store.items()):
                    full = f"{self.namespace}_{name}"
                    if full not in seen_types:
                        if name in self._help:
                            out.append(f"# HELP {full} "
                                       f"{self._help[name]}")
                        out.append(f"# TYPE {full} {mtype}")
                        seen_types.add(full)
                    if labels:
                        lbl = ",".join(f'{k}="{v}"' for k, v in labels)
                        out.append(f"{full}{{{lbl}}} {value}")
                    else:
                        out.append(f"{full} {value}")
        return "\n".join(out) + "\n"
