"""Balance JobHandlers (plugin/worker/handler_registry.go's
volume_balance and ec_balance handlers; worker/tasks/balance/): detect
volume-count / EC-shard skew across servers and run the same balancing
algorithms the shell commands use — one implementation, two drivers
(operator-invoked shell vs maintenance-plane worker).

Executions take the cluster admin lease first (the shell's lock), so a
worker-driven balance can never interleave with an operator running
volume.move by hand."""

from __future__ import annotations

from ...operation import master_json
from ..worker import JobHandler


def _volume_counts(master: str) -> "dict[str, int]":
    from ...topology import iter_volume_list_volumes
    counts: dict[str, int] = {}
    vl = master_json(master, "GET", "/vol/list")
    for n, _v in iter_volume_list_volumes(vl):
        counts[n["url"]] = counts.get(n["url"], 0) + 1
    for url in master_json(master, "GET",
                           "/cluster/status").get("dataNodes", []):
        counts.setdefault(url, 0)
    return counts


class _LockedShellRun:
    """Context manager: a CommandEnv holding the cluster admin lease
    for the duration of a handler execution."""

    def __init__(self, master: str):
        from ...shell import CommandEnv
        self.env = CommandEnv(master)

    def __enter__(self):
        self.env.lock()
        return self.env

    def __exit__(self, *exc):
        try:
            self.env.unlock()
        except (OSError, RuntimeError):
            pass  # lease expires on its own


class VolumeBalanceHandler(JobHandler):
    job_type = "volume_balance"
    aliases = ["balance"]

    def __init__(self, imbalance_threshold: int = 2):
        self.imbalance_threshold = imbalance_threshold

    def capability(self) -> dict:
        return {"jobType": self.job_type, "canDetect": True,
                "canExecute": True, "weight": 30}

    def descriptor(self) -> dict:
        return {"jobType": self.job_type, "fields": [
            {"name": "imbalanceThreshold", "type": "int",
             "default": self.imbalance_threshold,
             "help": "propose a balance when max-min volume count "
                     "per server exceeds this"},
        ]}

    def detect(self, worker) -> list[dict]:
        counts = _volume_counts(worker.master)
        if len(counts) < 2:
            return []
        spread = max(counts.values()) - min(counts.values())
        if spread <= self.imbalance_threshold:
            return []
        return [{
            "jobType": self.job_type,
            # one cluster-wide job at a time; re-proposed while skewed
            "dedupeKey": "volume_balance",
            "params": {"spread": spread},
        }]

    def execute(self, worker, job_id: str, params: dict) -> str:
        from ...shell.commands import cmd_volume_balance
        worker.report_progress(job_id, 0.1, "acquiring cluster lock")
        with _LockedShellRun(worker.master) as env:
            worker.report_progress(job_id, 0.3, "balancing volumes")
            return cmd_volume_balance(env, [])


class EcBalanceHandler(JobHandler):
    job_type = "ec_balance"

    def __init__(self, collection: str = ""):
        self.collection = collection

    def capability(self) -> dict:
        return {"jobType": self.job_type, "canDetect": True,
                "canExecute": True, "weight": 30}

    def descriptor(self) -> dict:
        return {"jobType": self.job_type, "fields": [
            {"name": "collection", "type": "string",
             "default": self.collection},
        ]}

    def detect(self, worker) -> list[dict]:
        """Propose when any server holds more EC shards of one volume
        than a balanced spread allows (ec_balance.go's skew rule,
        simplified to the per-volume max-shards criterion the shell
        balancer enforces)."""
        from ...topology import iter_volume_list_ec_shards
        vl = master_json(worker.master, "GET", "/vol/list")
        per_vid: dict[int, dict[str, int]] = {}
        for node, e in iter_volume_list_ec_shards(vl):
            n = bin(e.get("shardBits", 0)).count("1")
            per_vid.setdefault(e["volumeId"], {})[node["url"]] = n
        nodes = master_json(worker.master, "GET",
                            "/cluster/status").get("dataNodes", [])
        if not nodes:
            return []
        for vid, holders in per_vid.items():
            total = sum(holders.values())
            fair = -(-total // len(nodes))  # ceil
            if max(holders.values(), default=0) > fair:
                return [{
                    "jobType": self.job_type,
                    "dedupeKey": "ec_balance",
                    "params": {"collection": self.collection},
                }]
        return []

    def execute(self, worker, job_id: str, params: dict) -> str:
        from ...shell.commands import cmd_ec_balance
        worker.report_progress(job_id, 0.1, "acquiring cluster lock")
        args = []
        collection = params.get("collection", self.collection)
        if collection:
            args.append(f"-collection={collection}")
        with _LockedShellRun(worker.master) as env:
            worker.report_progress(job_id, 0.3, "balancing ec shards")
            return cmd_ec_balance(env, args)
