"""The `tpu_ec` worker handler — the north-star TPU entry point.

Mirrors the reference's canonical JobHandler
(plugin/worker/erasure_coding_handler.go: Capability :48, Descriptor
:61, Detect :187, Execute :445 delegating to
worker/tasks/erasure_coding/ec_task.go:59):

    markVolumeReadonly        (:261)
    copyVolumeFilesToWorker   (:300)  <- bulk .dat/.idx pull
    generateEcShardsLocally   (:426)  <- THE TPU HOT PATH: the worker
                                         owns the accelerator; encode
                                         runs on the JAX kernels when a
                                         TPU is present
    distributeEcShards        (:532)  -> ReceiveFile pushes to targets
    mountEcShards             (shard_distribution.go:209)
    deleteOriginalVolume      (:547)
"""

from __future__ import annotations

import os

from ...operation import master_json
from ...server.httpd import http_download, http_json, http_upload
from ...storage.erasure_coding import ECContext
from ...storage.erasure_coding import ec_decoder, ec_encoder
from ...storage.erasure_coding.ec_context import to_ext
from ...topology import iter_volume_list_volumes
from ..worker import JobHandler


from ..worker import must as _must


class EcEncodeHandler(JobHandler):
    job_type = "erasure_coding"
    aliases = ["ec", "erasure-coding"]

    def __init__(self, fullness_ratio: float = 0.9,
                 collection_filter: str | None = None,
                 data_shards: int = 10, parity_shards: int = 4,
                 backend: str | None = None,
                 encode_mode: str = "worker"):
        self.fullness_ratio = fullness_ratio
        self.collection_filter = collection_filter
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.backend = backend  # None -> auto (jax on TPU)
        # "worker": pull the volume here, encode on this worker's
        # accelerator, distribute (the TPU hot path).  "scatter": drive
        # the SOURCE server's scatter-encode — placement-first, shard
        # windows streamed straight to their destinations; the worker
        # only orchestrates (no volume bytes cross the plugin boundary)
        self.encode_mode = encode_mode

    def capability(self) -> dict:
        # weight 80 per erasure_coding_handler.go:48
        return {"jobType": self.job_type, "canDetect": True,
                "canExecute": True, "weight": 80}

    def descriptor(self) -> dict:
        """Declarative admin/worker config forms (handler :61)."""
        return {"jobType": self.job_type, "fields": [
            {"name": "fullnessRatio", "type": "float",
             "default": self.fullness_ratio,
             "help": "encode volumes fuller than this fraction"},
            {"name": "collectionFilter", "type": "string",
             "default": self.collection_filter or "",
             "help": "only encode volumes of this collection"},
            {"name": "dataShards", "type": "int",
             "default": self.data_shards},
            {"name": "parityShards", "type": "int",
             "default": self.parity_shards},
            {"name": "encodeMode", "type": "string",
             "default": self.encode_mode,
             "help": "worker (pull+encode here) or scatter "
                     "(source streams shards to placement targets)"},
        ]}

    # -- Detect (:187) ------------------------------------------------

    def detect(self, worker) -> list[dict]:
        vl = master_json(worker.master, "GET", "/vol/list", timeout=30)
        size_limit = self._volume_size_limit(worker)
        proposals = []
        seen = set()
        for _node, v in iter_volume_list_volumes(vl):
            vid = v["id"]
            if vid in seen:
                continue
            seen.add(vid)
            if self.collection_filter not in (None, "") and \
                    v.get("collection", "") != self.collection_filter:
                continue
            if v.get("size", 0) < self.fullness_ratio * size_limit:
                continue
            proposals.append({
                "jobType": self.job_type,
                "dedupeKey": f"ec:{vid}",
                "params": {
                    "volumeId": vid,
                    "collection": v.get("collection", ""),
                    "dataShards": self.data_shards,
                    "parityShards": self.parity_shards,
                },
            })
        return proposals

    def _volume_size_limit(self, worker) -> int:
        r = master_json(worker.master, "GET", "/cluster/status", timeout=30)
        return int(r.get("volumeSizeLimit", 1 << 30))

    # -- Execute (ec_task.go:59) ---------------------------------------

    def _make_ctx(self, params: dict, collection: str,
                  vid: int) -> ECContext:
        ctx_kw = {}
        if self.backend:
            ctx_kw["backend"] = self.backend
        return ECContext(
            int(params.get("dataShards", self.data_shards)),
            int(params.get("parityShards", self.parity_shards)),
            collection, vid, **ctx_kw)

    def _lookup_urls(self, worker, vid: int) -> list[str]:
        locations = master_json(worker.master, "GET",
                                f"/dir/lookup?volumeId={vid}"
                                , timeout=30).get("locations", [])
        if not locations:
            raise RuntimeError(f"volume {vid} has no locations")
        return [l["url"] for l in locations]

    def _mark_readonly(self, urls: list[str], vid: int) -> None:
        # (:261)
        for url in urls:
            _must(http_json("POST", f"{url}/admin/set_readonly",
                            {"volumeId": vid, "readOnly": True}, timeout=30),
                  f"set readonly on {url}")

    def _pull_volume(self, worker, vid: int, collection: str,
                     source: str, base: str) -> None:
        """Copy .dat/.idx to the worker (:300) — the bulk pull the
        plugin boundary is designed to carry.  Streamed to disk in
        chunks (http_download): a 30GB volume must never be buffered in
        worker RAM (the reference streams CopyFile the same way,
        ec_task.go:300 / volume_server.proto:69)."""
        os.makedirs(worker.work_dir, exist_ok=True)
        for ext in (".dat", ".idx"):
            status, _hdrs = http_download(
                f"{source}/admin/volume_file?volumeId={vid}"
                f"&collection={collection}&ext={ext}", base + ext, timeout=600)
            if status != 200:
                raise RuntimeError(
                    f"copy {ext} from {source}: {status}")

    def _unwind_volumes(self, worker, collection: str, ctx: ECContext,
                        vol_urls: "dict[int, list[str]]") -> None:
        """Failure unwind, in order: (1) tear down any
        distributed/mounted shards so the master never serves stale EC
        state alongside the still-live volume, then (2) restore
        writability so the volume is not stranded readonly."""
        try:
            targets = master_json(worker.master, "GET",
                                  "/cluster/status", timeout=30)["dataNodes"]
        except (OSError, KeyError):
            targets = []
        for vid, urls in vol_urls.items():
            for target in targets:
                try:
                    http_json("POST",
                              f"{target}/admin/ec/delete_shards",
                              {"volumeId": vid,
                               "collection": collection,
                               "shardIds": list(range(ctx.total))}, timeout=30)
                except OSError:
                    pass
            for url in urls:
                try:
                    http_json("POST", f"{url}/admin/set_readonly",
                              {"volumeId": vid, "readOnly": False}, timeout=30)
                except OSError:
                    pass

    @staticmethod
    def _cleanup_local(base: str, ctx: ECContext) -> None:
        for ext in [".dat", ".idx", ".ecx", ".ecj", ".vif"] + \
                [to_ext(i) for i in range(ctx.total)]:
            try:
                os.remove(base + ext)
            except FileNotFoundError:
                pass

    def _delete_originals(self, urls: list[str], vid: int) -> None:
        # (:547) — only after every shard is safely mounted
        for url in urls:
            _must(http_json("POST", f"{url}/admin/delete_volume",
                            {"volumeId": vid}, timeout=30),
                  f"delete original on {url}")

    def execute(self, worker, job_id: str, params: dict) -> str:
        if params.get("encodeMode", self.encode_mode) == "scatter":
            if "volumeIds" in params:
                # scatter has no mesh-batch form (each volume streams
                # from its own source); run the volumes sequentially
                # rather than silently falling back to the
                # pull-everything worker path
                out = []
                for v in dict.fromkeys(int(x)
                                       for x in params["volumeIds"]):
                    p = dict(params, volumeId=v)
                    p.pop("volumeIds", None)
                    out.append(self.execute_scatter(worker, job_id, p))
                return "\n".join(out)
            return self.execute_scatter(worker, job_id, params)
        if "volumeIds" in params:
            return self.execute_batch(worker, job_id, params)
        vid = int(params["volumeId"])
        collection = params.get("collection", "")
        ctx = self._make_ctx(params, collection, vid)
        urls = self._lookup_urls(worker, vid)
        base = os.path.join(worker.work_dir, f"{vid}")
        # the pull-then-push path moves volume bytes THROUGH this
        # worker, which serves no foreground traffic of its own — so
        # the feedback throttle watches the source/dest volume
        # servers' /metrics for the job's duration (qos.py; a no-op
        # unless an SLO is configured)
        from ... import qos
        try:
            with qos.remote_slo_watch(urls):
                placement = self._encode_and_distribute(
                    worker, job_id, vid, collection, ctx, urls,
                    urls[0], base)
        except Exception:
            self._unwind_volumes(worker, collection, ctx, {vid: urls})
            raise
        finally:
            self._cleanup_local(base, ctx)
        self._delete_originals(urls, vid)
        return (f"volume {vid}: {ctx} shards encoded on worker "
                f"({ctx.backend}) and distributed to "
                f"{sum(1 for s in placement.values() if s)} servers")

    def execute_scatter(self, worker, job_id: str,
                        params: dict) -> str:
        """Admin-driven scatter-encode OFF the shell path: the worker
        plans placement and drives the source server's streaming
        scatter generate (`/admin/ec/generate` + placement) — volume
        bytes flow source -> destinations directly, never through this
        worker.  Runs under the cluster admin lease (the shell's lock)
        so placement cannot interleave with an operator's balance."""
        from ...shell.commands import _do_ec_encode
        from .balance import _LockedShellRun
        vid = int(params["volumeId"])
        collection = params.get("collection", "")
        worker.report_progress(job_id, 0.1,
                               f"scatter-encoding volume {vid}")
        opts = {"collection": collection}
        if "dataShards" in params:
            opts["dataShards"] = params["dataShards"]
        if "parityShards" in params:
            opts["parityShards"] = params["parityShards"]
        with _LockedShellRun(worker.master) as env:
            msg = _do_ec_encode(
                env, vid,
                int(params.get("dataShards", self.data_shards)),
                int(params.get("parityShards", self.parity_shards)),
                opts, mode="scatter")
        worker.report_progress(job_id, 0.9, "scattered and mounted")
        return msg

    def _encode_and_distribute(self, worker, job_id: str, vid: int,
                               collection: str, ctx: ECContext,
                               urls: list[str], source: str,
                               base: str) -> dict:
        self._mark_readonly(urls, vid)
        worker.report_progress(job_id, 0.1, "marked readonly")
        self._pull_volume(worker, vid, collection, source, base)
        worker.report_progress(job_id, 0.3, "copied volume files")

        # 3. encode locally (:426) — TPU kernels when present
        dat_size = os.path.getsize(base + ".dat")
        version = _read_dat_version(base)
        ec_encoder.write_sorted_file_from_idx(base)
        ec_encoder.write_ec_files(base, ctx)
        ec_encoder.save_ec_volume_info(base, ctx, dat_size, version)
        worker.report_progress(
            job_id, 0.6, f"encoded {ctx.total} shards ({ctx.backend})")

        # consistency check (:638 verifyDatIdxConsistency analog):
        # decode geometry must reproduce the source size
        if ec_decoder.find_dat_file_size(base, base) > dat_size:
            raise RuntimeError("ecx entries exceed dat size")

        # 4+5. distribute + mount
        placement = self._distribute_and_mount(worker, vid, collection,
                                               ctx, base)
        worker.report_progress(job_id, 0.8, "distributed shards")
        return placement

    def _distribute_and_mount(self, worker, vid: int, collection: str,
                              ctx: ECContext, base: str) -> dict:
        """Round-robin shard spread over alive servers (:532) + mount
        (shard_distribution.go:209)."""
        targets = master_json(worker.master, "GET",
                              "/cluster/status", timeout=30)["dataNodes"]
        if not targets:
            raise RuntimeError("no alive volume servers")
        placement: dict[str, list[int]] = {t: [] for t in targets}
        for sid in range(ctx.total):
            placement[targets[sid % len(targets)]].append(sid)
        for target, sids in placement.items():
            if not sids:
                continue
            for sid in sids:
                _push_file(target, vid, collection, to_ext(sid),
                           base + to_ext(sid))
            for ext in (".ecx", ".vif"):
                _push_file(target, vid, collection, ext, base + ext)
        for target, sids in placement.items():
            if sids:
                _must(http_json("POST", f"{target}/admin/ec/mount",
                                {"volumeId": vid,
                                 "collection": collection,
                                 "shardIds": sids}, timeout=30),
                      f"mount shards on {target}")
        return placement

    # -- batch execute: N volumes through ONE mesh launch per step -----
    # (BASELINE config 3; VERDICT r2 Next #9 — volumes ride the
    # data-parallel "stripe" axis, parallel/ec_batch.py)

    def execute_batch(self, worker, job_id: str, params: dict) -> str:
        from ...parallel.ec_batch import encode_volume_files_batch

        # dedupe while preserving order: a repeated id would append the
        # same volume's rows twice into one set of shard files
        vids = list(dict.fromkeys(int(v) for v in params["volumeIds"]))
        collection = params.get("collection", "")
        ctx = self._make_ctx(params, collection, 0)
        os.makedirs(worker.work_dir, exist_ok=True)
        vol_urls: dict[int, list[str]] = {}
        bases = {vid: os.path.join(worker.work_dir, f"{vid}")
                 for vid in vids}
        n = len(vids)
        try:
            # per-volume progress throughout: a 64-volume batch takes
            # long enough that a silent job would trip the admin's
            # stall reaper and double-execute
            for i, vid in enumerate(vids):
                vol_urls[vid] = self._lookup_urls(worker, vid)
                self._mark_readonly(vol_urls[vid], vid)
                self._pull_volume(worker, vid, collection,
                                  vol_urls[vid][0], bases[vid])
                worker.report_progress(
                    job_id, 0.05 + 0.25 * (i + 1) / n,
                    f"pulled volume {vid} ({i + 1}/{n})")

            # one mesh-batched encode for the whole set: volumes ride
            # the data-parallel stripe axis (parallel/ec_batch.py)
            for vid in vids:
                ec_encoder.write_sorted_file_from_idx(bases[vid])
            encode_volume_files_batch([bases[v] for v in vids], ctx)
            for vid in vids:
                base = bases[vid]
                dat_size = os.path.getsize(base + ".dat")
                ec_encoder.save_ec_volume_info(
                    base, ctx, dat_size, _read_dat_version(base))
                if ec_decoder.find_dat_file_size(base, base) > dat_size:
                    raise RuntimeError(
                        f"volume {vid}: ecx entries exceed dat size")
            worker.report_progress(
                job_id, 0.6,
                f"batch-encoded {n} volumes ({ctx.backend})")

            for i, vid in enumerate(vids):
                self._distribute_and_mount(worker, vid, collection,
                                           ctx, bases[vid])
                worker.report_progress(
                    job_id, 0.6 + 0.3 * (i + 1) / n,
                    f"distributed volume {vid} ({i + 1}/{n})")
        except Exception:
            self._unwind_volumes(worker, collection, ctx, vol_urls)
            raise
        finally:
            for base in bases.values():
                self._cleanup_local(base, ctx)
        for vid in vids:
            self._delete_originals(vol_urls[vid], vid)
        return (f"batch of {n} volumes {ctx} encoded over the "
                f"mesh ({ctx.backend}) and distributed")


class EcRebuildHandler(JobHandler):
    """Repair-plane twin of the encode handler: detect EC volumes with
    missing shards, trigger a slice-pipelined rebuild on the node
    holding the most survivors (command_ec_rebuild.go Detect/Execute
    shape).  The worker never stages shard bytes itself — the rebuilder
    streams survivors off its peers via ranged `/admin/ec/shard_read`
    (no whole-shard `/admin/ec/copy` round), so the accelerator node's
    ingest link is not the repair bottleneck."""

    job_type = "ec_rebuild"
    aliases = ["rebuild"]

    def capability(self) -> dict:
        # repair outranks balance (30) but defers to encode (80)
        return {"jobType": self.job_type, "canDetect": True,
                "canExecute": True, "weight": 70}

    def descriptor(self) -> dict:
        return {"jobType": self.job_type, "fields": []}

    def _shard_locations(self, worker, vid: int) -> "dict[str, list[int]]":
        from ...topology import fetch_ec_shard_locations
        return fetch_ec_shard_locations(worker.master, vid)

    def detect(self, worker) -> list[dict]:
        from ...storage.erasure_coding.ec_context import (
            TOTAL_SHARDS_COUNT)
        from ...topology import iter_volume_list_ec_shards
        vl = master_json(worker.master, "GET", "/vol/list", timeout=30)
        per_vid: dict[int, set] = {}
        holders: dict[int, str] = {}
        for node, e in iter_volume_list_ec_shards(vl):
            sids = per_vid.setdefault(e["volumeId"], set())
            bits = int(e.get("shardBits", e.get("ecIndexBits", 0)))
            sids.update(i for i in range(32) if bits >> i & 1)
            holders.setdefault(e["volumeId"], node["url"])
        proposals = []
        for vid, present in sorted(per_vid.items()):
            if present == set(range(TOTAL_SHARDS_COUNT)):
                # a full default-scheme stripe needs no per-volume
                # probes: the healthy steady state must cost zero
                # extra round-trips per detect cycle
                continue
            # a gap OR a non-default scheme: one info probe decides
            r = http_json(
                "GET", f"{holders[vid]}/admin/ec/info?volumeId={vid}",
                    timeout=30)
            if "error" in r:
                continue
            total = r["dataShards"] + r["parityShards"]
            missing = [s for s in range(total) if s not in present]
            if missing and len(present) >= r["dataShards"]:
                proposals.append({
                    "jobType": self.job_type,
                    "dedupeKey": f"ec_rebuild:{vid}",
                    "params": {"volumeId": vid,
                               "collection": r.get("collection", ""),
                               "missingShardIds": missing},
                })
        return proposals

    def execute(self, worker, job_id: str, params: dict) -> str:
        vid = int(params["volumeId"])
        collection = params.get("collection", "")
        locs = self._shard_locations(worker, vid)
        if not locs:
            raise RuntimeError(f"ec volume {vid} has no shards")
        # the authoritative scheme from a shard holder: a rebuilder
        # whose .vif predates the destroy()-keeps-.vif fix must not
        # fall back to a default 10+4 for a custom-scheme volume
        info = None
        for url in locs:
            r = http_json("GET", f"{url}/admin/ec/info?volumeId={vid}",
                    timeout=30)
            if "error" not in r:
                info = r
                break
        if info is None:
            raise RuntimeError(f"ec volume {vid}: no reachable shards")
        collection = collection or info.get("collection", "")
        from ...topology import shard_ids_to_urls
        rebuilder = max(locs, key=lambda u: len(locs[u]))
        shard_locations = shard_ids_to_urls(locs)
        worker.report_progress(job_id, 0.1,
                               f"streaming rebuild on {rebuilder}")
        r = _must(http_json(
            "POST", f"{rebuilder}/admin/ec/rebuild",
            {"volumeId": vid, "collection": collection,
             "mode": "stream", "shardLocations": shard_locations,
             "dataShards": info["dataShards"],
             "parityShards": info["parityShards"]},
            timeout=600.0), f"rebuild on {rebuilder}")
        rebuilt = r.get("rebuiltShardIds", [])
        if rebuilt:
            _must(http_json("POST", f"{rebuilder}/admin/ec/mount",
                            {"volumeId": vid, "collection": collection,
                             "shardIds": rebuilt}, timeout=30),
                  f"mount rebuilt shards on {rebuilder}")
        worker.report_progress(job_id, 0.7, f"rebuilt {rebuilt}")
        # re-spread like the shell flow: leaving every rebuilt shard
        # on the max-survivor node would silently break the stripe's
        # anti-correlation (one node failure must not cost >1 shard).
        # Under the cluster admin lease (.balance convention): an
        # unlocked balance interleaving with an operator's locked one
        # could dedupe/delete the same transient shard copy twice.
        from ...shell.commands import _balance_ec_volume
        from .balance import _LockedShellRun
        with _LockedShellRun(worker.master) as env:
            moved = _balance_ec_volume(
                env, vid, collection,
                info["dataShards"] + info["parityShards"])
        worker.report_progress(job_id, 0.9,
                               f"rebalanced {moved} shards")
        tele = r.get("telemetry") or {}
        return (f"volume {vid}: rebuilt shards {rebuilt} on "
                f"{rebuilder}, rebalanced {moved} (streamed "
                f"{tele.get('bytesFetchedTotal', 0) >> 20}MB @ "
                f"{tele.get('volumeGbps', 0)} GB/s volume-rate)")


def _read_dat_version(base: str) -> int:
    from ...storage.super_block import SuperBlock
    with open(base + ".dat", "rb") as f:
        return SuperBlock.parse(f.read(8), require_extra=False).version


def _push_file(target: str, vid: int, collection: str, ext: str,
               path: str) -> None:
    """Streamed push (http_upload): shard files are sent from disk with
    bounded memory (shard_distribution.go:101 target side)."""
    status, body, _ = http_upload(
        "POST", f"{target}/admin/receive_file?volumeId={vid}"
        f"&collection={collection}&ext={ext}", path, timeout=600)
    if status != 200:
        raise RuntimeError(f"push {ext} to {target}: {status} "
                           f"{body[:200]!r}")
