"""Vacuum JobHandler (plugin/worker vacuum handler +
worker/tasks/vacuum): detect garbage-heavy volumes, compact them."""

from __future__ import annotations

from ...operation import master_json
from ...server.httpd import http_json
from ..worker import JobHandler


class VacuumHandler(JobHandler):
    job_type = "vacuum"

    def __init__(self, garbage_threshold: float = 0.3):
        self.garbage_threshold = garbage_threshold

    def capability(self) -> dict:
        return {"jobType": self.job_type, "canDetect": True,
                "canExecute": True, "weight": 50}

    def descriptor(self) -> dict:
        return {"jobType": self.job_type, "fields": [
            {"name": "garbageThreshold", "type": "float",
             "default": self.garbage_threshold,
             "help": "compact volumes whose garbage ratio exceeds this"},
        ]}

    def detect(self, worker) -> list[dict]:
        from ...topology import iter_volume_list_volumes
        vl = master_json(worker.master, "GET", "/vol/list")
        proposals = []
        seen = set()
        for _node, v in iter_volume_list_volumes(vl):
            vid = v["id"]
            if vid in seen or v.get("readOnly"):
                continue
            seen.add(vid)
            live = max(v.get("size", 0) -
                       v.get("deletedByteCount", 0), 1)
            ratio = v.get("deletedByteCount", 0) / live
            if ratio > self.garbage_threshold:
                proposals.append({
                    "jobType": self.job_type,
                    "dedupeKey": f"vacuum:{vid}",
                    "params": {"volumeId": vid},
                })
        return proposals

    def execute(self, worker, job_id: str, params: dict) -> str:
        vid = int(params["volumeId"])
        locs = master_json(worker.master, "GET",
                               f"/dir/lookup?volumeId={vid}"
                               ).get("locations", [])
        from ..worker import must
        for loc in locs:
            must(http_json("POST", f"{loc['url']}/admin/vacuum",
                           {"volumeId": vid}),
                 f"vacuum on {loc['url']}")
        return f"volume {vid}: vacuumed on {len(locs)} servers"
