"""JobHandlers (weed/plugin/worker/*_handler.go)."""

from .balance import EcBalanceHandler, VolumeBalanceHandler  # noqa: F401
from .erasure_coding import EcEncodeHandler, EcRebuildHandler  # noqa: F401
from .vacuum import VacuumHandler  # noqa: F401
