"""Plugin worker runtime (weed/plugin/worker/worker.go +
handler_registry.go): hosts JobHandlers, speaks the worker protocol
with the admin (register -> poll -> detect/execute -> report)."""

from __future__ import annotations

import threading
import traceback

from ..server.httpd import http_json


def must(r: dict, what: str) -> dict:
    """RPC error dicts abort the operation — shared by all handlers."""
    if isinstance(r, dict) and r.get("error"):
        raise RuntimeError(f"{what}: {r['error']}")
    return r


def _post_with_retry(url: str, payload: dict, attempts: int = 30) -> None:
    """Report-back POSTs must survive transient admin outages — a lost
    completion report would otherwise kill the worker loop thread.
    ~5 minutes of capped backoff; a still-lost report is backstopped by
    the admin's job-stall requeue (admin.py JOB_STALL_AFTER)."""
    import time
    for i in range(attempts):
        try:
            http_json("POST", url, payload)
            return
        except OSError:
            time.sleep(min(2.0 ** i, 10.0))


def apply_config_values(handler: "JobHandler", values: dict) -> None:
    """Admin ConfigStore values -> handler attributes: descriptor
    field names are camelCase on the wire (plugin.proto forms),
    handler attrs snake_case.  Unknown names are ignored (the admin
    already schema-validated).  Shared by the HTTP long-poll worker
    and the gRPC stream worker so the rule cannot drift."""
    for name, value in values.items():
        attr = PluginWorker._snake(name)
        if hasattr(handler, attr):
            setattr(handler, attr, value)


class JobHandler:
    """Contract mirrored from plugin/worker JobHandler
    (erasure_coding_handler.go:48 Capability, :61 Descriptor,
    :187 Detect, :445 Execute)."""

    job_type = "base"
    aliases: list[str] = []

    def capability(self) -> dict:
        return {"jobType": self.job_type, "canDetect": True,
                "canExecute": True, "weight": 50}

    def descriptor(self) -> dict:
        """Declarative config schema (plugin.proto descriptor forms)."""
        return {"jobType": self.job_type, "fields": []}

    def detect(self, worker: "PluginWorker") -> list[dict]:
        """Return job proposals: {jobType, params, dedupeKey}."""
        return []

    def execute(self, worker: "PluginWorker", job_id: str,
                params: dict) -> str:
        raise NotImplementedError


class PluginWorker:
    """A maintenance worker process (weed worker / tpu_ec sidecar)."""

    def __init__(self, admin: str, master: str, work_dir: str,
                 handlers: list[JobHandler],
                 max_concurrent: int = 1,
                 poll_wait: float = 5.0):
        self.admin = admin
        self.master = master
        self.work_dir = work_dir
        self.handlers = {h.job_type: h for h in handlers}
        for h in handlers:  # aliases resolve to the same handler
            for alias in h.aliases:
                self.handlers.setdefault(alias, h)
        self.max_concurrent = max_concurrent
        self.poll_wait = poll_wait
        self.worker_id = ""
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.executed: list[str] = []  # job ids, newest last

    # -- lifecycle --------------------------------------------------------

    def start(self):
        r = http_json("POST", f"{self.admin}/worker/register", {
            "capabilities": [h.capability() for h in
                             self.handlers.values()],
            "descriptors": [h.descriptor() for h in
                            self.handlers.values()],
            "maxConcurrent": self.max_concurrent,
        })
        self.worker_id = r["workerId"]
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- protocol loop ----------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                msg = http_json("POST", f"{self.admin}/worker/poll", {
                    "workerId": self.worker_id,
                    "waitSeconds": self.poll_wait,
                }, timeout=self.poll_wait + 10)
            except OSError:
                if self._stop.wait(1.0):
                    return
                continue
            if msg.get("error"):
                # e.g. the admin restarted and lost its registry —
                # re-register with backoff instead of hot-spinning
                if self._stop.wait(1.0):
                    return
                try:
                    r = http_json(
                        "POST", f"{self.admin}/worker/register", {
                            "workerId": self.worker_id,
                            "capabilities": [h.capability() for h in
                                             self.handlers.values()],
                            "maxConcurrent": self.max_concurrent})
                    self.worker_id = r.get("workerId", self.worker_id)
                except OSError:
                    pass
                continue
            mtype = msg.get("type")
            if mtype == "runDetection":
                self._apply_config(msg.get("config") or {})
                self._run_detection()
            elif mtype == "executeJob":
                self._execute(msg["jobId"], msg["jobType"],
                              msg.get("params", {}),
                              request_id=msg.get("requestId", ""),
                              trace_parent=msg.get("traceParent", ""))

    @staticmethod
    def _snake(name: str) -> str:
        return "".join("_" + c.lower() if c.isupper() else c
                       for c in name)

    def _apply_config(self, config: dict) -> None:
        for job_type, values in config.items():
            h = self.handlers.get(job_type)
            if h is not None:
                apply_config_values(h, values)

    def _run_detection(self) -> None:
        proposals = []
        for h in self.handlers.values():
            try:
                proposals.extend(h.detect(self))
            except Exception:  # noqa: BLE001 — detection must not kill loop
                traceback.print_exc()
        if proposals:
            _post_with_retry(f"{self.admin}/worker/detection_result",
                             {"workerId": self.worker_id,
                              "proposals": proposals})

    def _execute(self, job_id: str, job_type: str, params: dict,
                 request_id: str = "", trace_parent: str = "") -> None:
        # join the submitter's trace (tracing.py): the job rode the
        # admin queue, so context arrives in the message, not headers.
        # A detection-born job without context mints its own ids so
        # the execution is still traceable by `job-<id>`.  Context is
        # RESTORED afterwards — this loop thread lives on, and a
        # leaked rid would trace every later poll into this job.
        from .. import tracing
        from ..util.request_id import reset_request_id, set_request_id
        rid = request_id or f"job-{job_id}"
        token = set_request_id(rid)
        tracing.adopt_remote_parent(trace_parent, role="worker")
        h = self.handlers.get(job_type)
        try:
            with tracing.span(f"job:{job_type}", role="worker") as sp:
                sp.set("jobId", job_id)
                try:
                    if h is None:
                        raise ValueError(
                            f"no handler for {job_type!r}")
                    message = h.execute(self, job_id, params)
                    success = True
                except Exception as e:  # noqa: BLE001 — report,
                    # don't die
                    traceback.print_exc()
                    message, success = f"{type(e).__name__}: {e}", \
                        False
                    sp.set_error(e)
            self.executed.append(job_id)
            _post_with_retry(f"{self.admin}/worker/complete", {
                "workerId": self.worker_id, "jobId": job_id,
                "success": success, "message": message,
                # the worker has no HTTP listener for trace.show to
                # query, so its spans ride the completion report and
                # the admin re-records them into ITS ring buffer
                "spans": tracing.spans_for(rid)})
        finally:
            reset_request_id(token)
            tracing.adopt_remote_parent("")

    def report_progress(self, job_id: str, progress: float,
                        message: str = "") -> None:
        try:
            http_json("POST", f"{self.admin}/worker/progress", {
                "workerId": self.worker_id, "jobId": job_id,
                "progress": progress, "message": message})
        except OSError:
            pass
