"""Plugin/worker plane: language-agnostic maintenance workers
(weed/plugin + weed/worker + pb/plugin.proto; design doc
admin/plugin/DESIGN.md).

The TPU enters the system here: a `tpu_ec` worker process owns the
accelerator and executes erasure-coding jobs dispatched by the admin —
exactly where the reference already runs EC off the volume server
(worker/tasks/erasure_coding/ec_task.go copies volume files to the
worker and encodes locally).
"""

from .admin import AdminServer  # noqa: F401
from .worker import PluginWorker  # noqa: F401
