"""Admin server: worker registry + detection scheduling + job dispatch
(weed/admin/maintenance/maintenance_manager.go + admin/plugin/:
PluginRegistry, DetectorScheduler, JobDispatcher, SchemaCoordinator,
ConfigStore per DESIGN.md).

The reference uses a worker-initiated bidi gRPC stream
(pb/plugin.proto:12 PluginControlService.WorkerStream).  Over plain
HTTP the same conversation becomes: worker registers (WorkerHello with
capabilities + config-schema Descriptors), then long-polls
/worker/poll for admin->worker messages (RunDetectionRequest /
ExecuteJobRequest) and POSTs worker->admin messages (DetectionResult /
JobProgressUpdate / JobCompleted).

Round 5 (VERDICT r4 #7): with `data_dir` set the plane persists under
`<data_dir>/plugin/` — the reference's persistence layout — so jobs,
dedupe keys, decision traces, the worker registry and per-job-type
config SURVIVE an admin restart:
  plugin/jobs.jsonl    append-only job event log (folded at load,
                       compacted when it grows past 4x the live set)
  plugin/workers.json  registry snapshot (ids, capabilities, schemas)
  plugin/config.json   ConfigStore: schema-validated per-type values,
                       delivered to workers with each RunDetection
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field

from ..server.httpd import HttpServer, Request, http_json


def _trace_ctx() -> "tuple[str, str]":
    """(request id, trace parent) of the request minting a job, so
    the eventual worker execution joins the submitter's trace."""
    from .. import tracing
    from ..util.request_id import get_request_id
    return get_request_id(), tracing.traceparent_header()


@dataclass
class WorkerInfo:
    worker_id: str
    capabilities: list[dict] = field(default_factory=list)
    last_seen: float = 0.0
    inflight: int = 0
    max_concurrent: int = 1

    def can(self, job_type: str) -> bool:
        return any(c.get("jobType") == job_type
                   for c in self.capabilities)


@dataclass
class Job:
    job_id: str
    job_type: str
    params: dict
    dedupe_key: str
    status: str = "pending"   # pending -> assigned -> done/failed
    worker_id: str = ""
    progress: float = 0.0
    message: str = ""
    created: float = field(default_factory=time.time)
    updated: float = field(default_factory=time.time)
    # decision trace (admin/plugin DESIGN.md WorkflowMonitor): why the
    # job exists and every state transition, survives restart
    trace: list = field(default_factory=list)
    # distributed-tracing context of the request that minted the job
    # (tracing.py): delivered with executeJob so the worker's spans
    # land in the submitter's trace
    request_id: str = ""
    trace_parent: str = ""

    def add_trace(self, event: str) -> None:
        self.trace.append({"ts": round(time.time(), 3),
                           "event": event})

    def to_json(self) -> dict:
        return {"jobId": self.job_id, "jobType": self.job_type,
                "params": self.params, "dedupeKey": self.dedupe_key,
                "status": self.status, "workerId": self.worker_id,
                "progress": self.progress, "message": self.message,
                "created": self.created, "updated": self.updated,
                "trace": self.trace, "requestId": self.request_id,
                "traceParent": self.trace_parent}

    @classmethod
    def from_json(cls, d: dict) -> "Job":
        return cls(job_id=d["jobId"], job_type=d["jobType"],
                   params=d.get("params", {}),
                   dedupe_key=d.get("dedupeKey", ""),
                   status=d.get("status", "pending"),
                   worker_id=d.get("workerId", ""),
                   progress=d.get("progress", 0.0),
                   message=d.get("message", ""),
                   created=d.get("created", 0.0),
                   updated=d.get("updated", 0.0),
                   trace=d.get("trace", []),
                   request_id=d.get("requestId", ""),
                   trace_parent=d.get("traceParent", ""))


class AdminServer:
    """Maintenance plane controller."""

    def __init__(self, master: str, host: str = "127.0.0.1", port: int = 0,
                 detection_interval: float = 30.0,
                 data_dir: "str | None" = None):
        self.master = master
        self.detection_interval = detection_interval
        self.workers: dict[str, WorkerInfo] = {}
        self.jobs: dict[str, Job] = {}
        self._dedupe: dict[str, str] = {}  # dedupe_key -> job_id
        # jobType -> descriptor fields (SchemaCoordinator) and
        # jobType -> operator values (ConfigStore)
        self.schemas: dict[str, list] = {}
        self.config: dict[str, dict] = {}
        self.lock = threading.RLock()
        self._stop = threading.Event()
        self.data_dir = data_dir
        self._jobs_f = None
        self._job_records = 0
        if data_dir:
            import os
            self._plugin_dir = os.path.join(data_dir, "plugin")
            os.makedirs(self._plugin_dir, exist_ok=True)
            with self.lock:
                self._load_state()
        self.http = HttpServer(host, port)
        self.http.role = "admin"          # tracing server spans
        # browser-plane write protection: every mutating /ui/* POST
        # must present this per-process CSRF token (served embedded in
        # the GET forms) AND, when security.toml configures an admin
        # key, admin credentials — an unauthenticated cross-site form
        # post must not be able to submit maintenance jobs
        self._csrf = uuid.uuid4().hex
        r = self.http.route
        r("GET", "/maintenance/config", self._get_config)
        r("POST", "/maintenance/config", self._set_config)
        r("GET", "/maintenance/job", self._job_detail)
        r("POST", "/worker/register", self._register)     # WorkerHello
        r("POST", "/worker/poll", self._poll)             # admin->worker
        r("POST", "/worker/detection_result", self._detection_result)
        r("POST", "/worker/progress", self._progress)     # JobProgressUpdate
        r("POST", "/worker/complete", self._complete)     # JobCompleted
        r("GET", "/", self._ui)
        # multi-page admin UI (weed/admin/view/app/ pages)
        r("GET", "/ui/volumes", self._ui_volumes)
        r("GET", "/ui/ec", self._ui_ec)
        r("GET", "/ui/jobs", self._ui_jobs)
        r("GET", "/ui/config", self._ui_config)
        r("POST", "/ui/config", self._ui_config_submit)
        r("POST", "/ui/actions", self._ui_actions)
        r("GET", "/maintenance/queue", self._queue)
        r("POST", "/maintenance/trigger_detection", self._trigger)
        r("POST", "/maintenance/submit_job", self._submit_job)
        from ..server.debug import install_debug_routes
        install_debug_routes(self.http)  # incl. ingested job traces
        self._detect_thread: threading.Thread | None = None
        self._pending_detection: list[str] = []  # worker ids to ask

    # -- lifecycle --------------------------------------------------------

    def start(self):
        self.http.start()
        # the reference's worker transport is gRPC (plugin.proto
        # WorkerStream + worker.proto WorkerStream, both admin-hosted:
        # admin/dash/worker_grpc_server.go); serve both alongside the
        # HTTP long-poll plane
        self.grpc_server, self.grpc_port = None, 0
        try:
            from ..pb.plugin_service import start_admin_grpc
            self.grpc_server, self.grpc_port = start_admin_grpc(
                self, host=self.http.host)
        except ImportError:     # grpcio absent: HTTP-only mode
            pass
        except Exception as e:  # pragma: no cover — a real defect
            import sys
            print(f"admin {self.url}: gRPC plane failed to start: "
                  f"{e!r}", file=sys.stderr)
        self._detect_thread = threading.Thread(
            target=self._detection_loop, daemon=True)
        self._detect_thread.start()
        return self

    def stop(self):
        self._stop.set()
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop(grace=0.5).wait()
            self.grpc_server = None
        self.http.stop()
        with self.lock:
            if self._jobs_f is not None:
                self._jobs_f.close()
                self._jobs_f = None

    # -- persistence (<dataDir>/plugin/, DESIGN.md layout) ---------------

    def _load_state(self) -> None:
        """Caller holds the lock (init-time recovery)."""
        import json
        import os
        jobs_path = os.path.join(self._plugin_dir, "jobs.jsonl")
        try:
            with open(jobs_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        d = json.loads(line)
                    except ValueError:
                        break   # torn tail: later records rewritten
                    self.jobs[d["jobId"]] = Job.from_json(d)
                    self._job_records += 1
        except OSError:
            pass
        for job in self.jobs.values():
            # an admin crash mid-assignment loses the worker's report
            # channel state: requeue live assignments on recovery
            if job.status == "assigned":
                job.status = "pending"
                job.worker_id = ""
                job.add_trace("requeued: admin restart")
            self._dedupe[job.dedupe_key] = job.job_id
        try:
            with open(os.path.join(self._plugin_dir,
                                   "workers.json")) as f:
                for d in json.load(f):
                    self.workers[d["workerId"]] = WorkerInfo(
                        worker_id=d["workerId"],
                        capabilities=d.get("capabilities", []),
                        last_seen=0.0,
                        max_concurrent=d.get("maxConcurrent", 1))
                    for desc in d.get("descriptors", []):
                        if desc.get("jobType"):
                            self.schemas[desc["jobType"]] =                                 desc.get("fields", [])
        except (OSError, ValueError):
            pass
        try:
            with open(os.path.join(self._plugin_dir,
                                   "config.json")) as f:
                self.config = json.load(f)
        except (OSError, ValueError):
            pass
        if len(self.jobs):
            self._compact_jobs()

    def _persist_job(self, job: Job) -> None:
        """Append the job's current state (caller holds the lock)."""
        if not self.data_dir:
            return
        import json
        import os
        if self._jobs_f is None:
            self._jobs_f = open(
                os.path.join(self._plugin_dir, "jobs.jsonl"), "a")
        self._jobs_f.write(json.dumps(job.to_json()) + "\n")
        self._jobs_f.flush()
        self._job_records += 1
        if self._job_records > 4 * max(len(self.jobs), 64):
            self._compact_jobs()

    def _compact_jobs(self) -> None:
        """Caller holds the lock."""
        import json
        import os
        if not self.data_dir:
            return
        if self._jobs_f is not None:
            self._jobs_f.close()
        path = os.path.join(self._plugin_dir, "jobs.jsonl")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for j in sorted(self.jobs.values(),
                            key=lambda j: j.created):
                f.write(json.dumps(j.to_json()) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._jobs_f = open(path, "a")
        self._job_records = len(self.jobs)

    def _persist_workers(self) -> None:
        """Caller holds the lock."""
        if not self.data_dir:
            return
        import json
        import os
        path = os.path.join(self._plugin_dir, "workers.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump([{
                "workerId": w.worker_id,
                "capabilities": w.capabilities,
                "maxConcurrent": w.max_concurrent,
                "descriptors": [
                    {"jobType": jt, "fields": fields}
                    for jt, fields in self.schemas.items()
                    if w.can(jt)],
            } for w in self.workers.values()], f)
        os.replace(tmp, path)

    def _persist_config(self) -> None:
        """Caller holds the lock."""
        if not self.data_dir:
            return
        import json
        import os
        path = os.path.join(self._plugin_dir, "config.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.config, f)
        os.replace(tmp, path)

    @property
    def url(self) -> str:
        return self.http.url

    # -- worker protocol handlers -----------------------------------------

    def _register(self, req: Request):
        b = req.json()
        wid = b.get("workerId") or uuid.uuid4().hex[:12]
        with self.lock:
            self.workers[wid] = WorkerInfo(
                worker_id=wid,
                capabilities=b.get("capabilities", []),
                # liveness ages on the monotonic clock (SWFS011): an
                # NTP step must not mass-reap or immortalize workers
                last_seen=time.monotonic(),
                max_concurrent=int(b.get("maxConcurrent", 1)))
            # SchemaCoordinator: Descriptors carry declarative config
            # forms (plugin.proto); the ConfigStore validates against
            # them and the UI renders them
            for desc in b.get("descriptors", []):
                if desc.get("jobType"):
                    self.schemas[desc["jobType"]] =                         desc.get("fields", [])
            self._persist_workers()
        return 200, {"workerId": wid}

    def _poll(self, req: Request):
        """Long-poll: return the next admin->worker message for this
        worker (detection request or job assignment)."""
        b = req.json()
        wid = b["workerId"]
        deadline = time.time() + float(b.get("waitSeconds", 10.0))
        while time.time() < deadline and not self._stop.is_set():
            with self.lock:
                w = self.workers.get(wid)
                if w is None:
                    return 404, {"error": "unregistered worker"}
                w.last_seen = time.monotonic()
                if wid in self._pending_detection:
                    self._pending_detection.remove(wid)
                    return 200, {"type": "runDetection",
                                 "config": dict(self.config)}
                job = self._next_job_for(w)
                if job is not None:
                    job.status = "assigned"
                    job.worker_id = wid
                    job.add_trace(f"assigned to {wid}")
                    self._persist_job(job)
                    w.inflight += 1
                    return 200, {"type": "executeJob",
                                 "jobId": job.job_id,
                                 "jobType": job.job_type,
                                 "params": job.params,
                                 "requestId": job.request_id,
                                 "traceParent": job.trace_parent}
            time.sleep(0.05)
        return 200, {"type": "none"}

    def _next_job_for(self, w: WorkerInfo) -> Job | None:
        if w.inflight >= w.max_concurrent:
            return None
        for job in sorted(self.jobs.values(), key=lambda j: j.created):
            if job.status == "pending" and w.can(job.job_type):
                return job
        return None

    def _detection_result(self, req: Request):
        """Worker Detect() proposals -> deduped job queue
        (DetectorScheduler + JobDispatcher)."""
        b = req.json()
        accepted = []
        with self.lock:
            for prop in b.get("proposals", []):
                key = prop.get("dedupeKey") or \
                    f"{prop['jobType']}:{prop['params'].get('volumeId')}"
                existing = self._dedupe.get(key)
                if existing and \
                        self.jobs[existing].status in ("pending",
                                                       "assigned"):
                    continue
                rid, tparent = _trace_ctx()
                job = Job(job_id=uuid.uuid4().hex[:12],
                          job_type=prop["jobType"],
                          params=prop["params"], dedupe_key=key,
                          request_id=rid, trace_parent=tparent)
                job.add_trace(
                    f"detected by {b.get('workerId', '?')}"
                    + (f": {prop['reason']}" if prop.get("reason")
                       else ""))
                self.jobs[job.job_id] = job
                self._dedupe[key] = job.job_id
                self._persist_job(job)
                accepted.append(job.job_id)
        return 200, {"accepted": accepted}

    def _ui(self, req: Request):
        """Status page (the minimal analog of the reference's admin web
        UI, weed/admin/view/ — live topology, workers, job queue)."""
        import html as _html
        try:
            from ..operation import master_json
            vl = master_json(self.master, "GET", "/vol/list")
            status = master_json(self.master, "GET", "/cluster/status")
        except OSError:
            vl, status = {}, {}
        rows = []
        for dc_name, dc in vl.get("dataCenters", {}).items():
            for rack_name, rack in dc.get("racks", {}).items():
                for node in rack.get("nodes", []):
                    rows.append(
                        f"<tr><td>{_html.escape(dc_name)}/"
                        f"{_html.escape(rack_name)}</td>"
                        f"<td>{_html.escape(node['url'])}</td>"
                        f"<td>{len(node.get('volumes', []))}/"
                        f"{node.get('maxVolumeCount', '?')}</td>"
                        f"<td>{len(node.get('ecShards', []))}</td>"
                        f"</tr>")
        with self.lock:
            workers = [
                f"<tr><td>{_html.escape(w.worker_id)}</td>"
                f"<td>{_html.escape(', '.join(sorted(str(c.get('jobType', '?')) for c in w.capabilities)))}</td>"
                f"<td>{w.inflight}/{w.max_concurrent}</td>"
                f"<td>{time.monotonic() - w.last_seen:.0f}s ago"
                f"</td></tr>"
                for w in self.workers.values()]
            jobs = [
                f"<tr><td><a href='/maintenance/job?id={j.job_id}'>"
                f"{j.job_id}</a></td>"
                f"<td>{_html.escape(j.job_type)}</td>"
                f"<td>{_html.escape(j.status)}</td>"
                f"<td>{j.progress:.0%}</td>"
                f"<td>{_html.escape(j.message or '')}</td>"
                f"<td>{_html.escape(j.trace[-1]['event'] if j.trace else '')}"
                f"</td></tr>"
                for j in sorted(self.jobs.values(),
                                key=lambda j: -j.created)[:50]]
            config_rows = [
                f"<tr><td>{_html.escape(jt)}</td>"
                f"<td>{_html.escape(', '.join(f['name'] for f in fields))}</td>"
                f"<td>{_html.escape(str(self.config.get(jt, {})))}"
                f"</td></tr>"
                for jt, fields in sorted(self.schemas.items())]
        inner = f"""<p>master: {_html.escape(self.master)} &middot;
leader: {_html.escape(str(status.get('leader', '?')))} &middot;
topology: {_html.escape(str(status.get('topologyId', '?')))}</p>
<h2>Data nodes</h2>
<table><tr><th>dc/rack</th><th>url</th><th>volumes</th>
<th>ec volumes</th></tr>{''.join(rows)}</table>
<h2>Workers</h2>
<table><tr><th>id</th><th>capabilities</th><th>inflight</th>
<th>seen</th></tr>{''.join(workers)}</table>
<h2>Job types (schemas + config)</h2>
<table><tr><th>type</th><th>schema fields</th><th>config</th></tr>
{''.join(config_rows)}</table>
<h2>Jobs (latest 50)</h2>
<table><tr><th>id</th><th>type</th><th>status</th><th>progress</th>
<th>message</th><th>last decision</th></tr>{''.join(jobs)}</table>"""
        return self._page("seaweedfs-tpu admin", inner)

    def _csrf_input(self) -> str:
        return (f"<input type='hidden' name='csrf' "
                f"value='{self._csrf}'>")

    def _ui_write_guard(self, req: Request,
                        form: dict) -> "tuple | None":
        """Gate for browser-driven writes (POST /ui/*): the
        security.toml admin key (when configured) and the GET-served
        CSRF token, both or 403.  Order matters — auth first, so an
        unauthenticated caller learns nothing about token validity."""
        from .. import security
        err = security.current().check_admin(
            req.query, req.headers, req.remote_ip)
        if err:
            return 403, {"error": f"admin credentials required: {err}"}
        if form.get("csrf") != self._csrf:
            return 403, {"error": "missing or stale CSRF token; "
                                  "reload the form page"}
        return None

    @staticmethod
    def _form(req: Request) -> dict:
        """Decode an HTML form body; keep_blank_values so a field
        cleared to empty REACHES validation instead of silently
        keeping the old value (shared by both UI POST handlers)."""
        import urllib.parse as _up
        return {k: v[0] for k, v in
                _up.parse_qs((req.body or b"").decode(),
                             keep_blank_values=True).items()}

    class _FormShim:
        """Request shim: hands a parsed HTML form to the JSON config
        handler so both entry points share one validation path."""

        def __init__(self, payload: dict):
            self._payload = payload
            self.query: dict = {}

        def json(self) -> dict:
            return self._payload

    # -- multi-page UI (weed/admin/view/app/: cluster_volumes.templ,
    # cluster_ec_volumes.templ, maintenance_queue.templ,
    # maintenance_config_schema.templ roles) ---------------------------

    _NAV = ("<p><a href='/'>dashboard</a> | "
            "<a href='/ui/volumes'>volumes</a> | "
            "<a href='/ui/ec'>ec</a> | "
            "<a href='/ui/jobs'>jobs</a> | "
            "<a href='/ui/config'>config</a></p>")

    def _page(self, title: str, inner: str):
        import html as _html
        body = f"""<!doctype html><html><head>
<title>{_html.escape(title)} - seaweedfs-tpu admin</title>
<style>body{{font-family:sans-serif;margin:2em}}
table{{border-collapse:collapse;margin:1em 0}}
td,th{{border:1px solid #ccc;padding:4px 10px;text-align:left}}
h2{{margin-top:1.5em}} .ok{{color:#2a2}} .bad{{color:#c22}}
input{{margin:2px}}</style></head><body>
<h1>{_html.escape(title)}</h1>{self._NAV}{inner}</body></html>"""
        return 200, (body.encode(), "text/html; charset=utf-8")

    def _topology(self) -> dict:
        try:
            from ..operation import master_json
            return master_json(self.master, "GET", "/vol/list")
        except OSError:
            return {}

    def _ui_volumes(self, req: Request):
        """Per-volume inventory across the topology
        (cluster_volumes.templ role)."""
        import html as _html
        from ..topology import iter_volume_list_volumes
        rows = []
        for node, v in sorted(
                iter_volume_list_volumes(self._topology()),
                key=lambda t: (t[1]["id"], t[0]["url"])):
            garbage = v.get("deletedByteCount", 0)
            size = max(v.get("size", 0), 1)
            flags = []
            if v.get("readOnly"):
                flags.append("readonly")
            if v.get("remoteTiered"):
                flags.append("remote")
            rows.append(
                f"<tr><td>{v['id']}</td>"
                f"<td>{_html.escape(v.get('collection') or '-')}</td>"
                f"<td>{_html.escape(node['url'])}</td>"
                f"<td>{v.get('size', 0):,}</td>"
                f"<td>{v.get('fileCount', 0)}</td>"
                f"<td>{garbage / size:.0%}</td>"
                f"<td>{_html.escape(','.join(flags) or '-')}</td>"
                f"</tr>")
        return self._page(
            "Volumes",
            "<table><tr><th>id</th><th>collection</th><th>node</th>"
            "<th>bytes</th><th>files</th><th>garbage</th>"
            f"<th>flags</th></tr>{''.join(rows)}</table>"
            f"<p>{len(rows)} volume replicas</p>")

    def _ui_ec(self, req: Request):
        """EC volumes and shard spread (cluster_ec_volumes.templ)."""
        import html as _html
        from ..topology import iter_volume_list_ec_shards
        by_vol: dict[int, list] = {}
        for node, e in iter_volume_list_ec_shards(self._topology()):
            bits = int(e.get("ecIndexBits", 0))
            sids = [i for i in range(32) if bits >> i & 1]
            by_vol.setdefault(e.get("volumeId", e.get("id")),
                              []).append((node["url"], sids))
        rows = []
        for vid, spread in sorted(by_vol.items()):
            total = sum(len(s) for _, s in spread)
            cells = "; ".join(
                f"{_html.escape(url)}: {','.join(map(str, s))}"
                for url, s in sorted(spread))
            cls = "ok" if total >= 14 else "bad"
            rows.append(f"<tr><td>{vid}</td>"
                        f"<td class='{cls}'>{total}</td>"
                        f"<td>{cells}</td></tr>")
        return self._page(
            "EC volumes",
            "<table><tr><th>volume</th><th>shards</th>"
            f"<th>placement</th></tr>{''.join(rows)}</table>"
            f"<p>{len(rows)} EC volumes</p>")

    def _ui_jobs(self, req: Request):
        """Full job history with status filter + decision traces
        (maintenance_queue.templ + persisted job history)."""
        import html as _html
        want = req.query.get("status", "")
        with self.lock:
            jobs = sorted(self.jobs.values(),
                          key=lambda j: -j.created)
        # counts from the SAME snapshot the table renders, so the
        # filter totals can never disagree with the rows
        counts: dict[str, int] = {}
        for j in jobs:
            counts[j.status] = counts.get(j.status, 0) + 1
        if want:
            jobs = [j for j in jobs if j.status == want]
        filters = " | ".join(
            f"<a href='/ui/jobs?status={s}'>{s} ({n})</a>"
            for s, n in sorted(counts.items()))
        rows = []
        for j in jobs[:200]:
            trace = "<br>".join(
                f"{_html.escape(t.get('event', ''))} "
                f"{_html.escape(str(t.get('detail', '')))}"
                for t in j.trace[-3:])
            rows.append(
                f"<tr><td><a href='/maintenance/job?id={j.job_id}'>"
                f"{j.job_id}</a></td>"
                f"<td>{_html.escape(j.job_type)}</td>"
                f"<td>{_html.escape(j.status)}</td>"
                f"<td>{j.progress:.0%}</td>"
                f"<td>{_html.escape(str(j.params)[:80])}</td>"
                f"<td>{trace}</td></tr>")
        with self.lock:
            types_ = sorted(self.schemas)
        submit_opts = "".join(f"<option>{_html.escape(t)}</option>"
                              for t in types_)
        actions = (
            "<h2>Actions</h2>"
            "<form method='post' action='/ui/actions' "
            "style='display:inline'>"
            "<input type='hidden' name='action' value='detect'>"
            f"{self._csrf_input()}"
            "<button>run detection now</button></form> "
            "<form method='post' action='/ui/actions' "
            "style='display:inline'>"
            "<input type='hidden' name='action' value='submit'>"
            f"{self._csrf_input()}"
            f"<select name='jobType'>{submit_opts}</select> "
            "params (JSON): <input name='params' value='{}' "
            "size='30'> <button>submit job</button></form>")
        return self._page(
            "Jobs",
            f"<p>filter: <a href='/ui/jobs'>all</a> | {filters}</p>"
            + actions +
            "<table><tr><th>id</th><th>type</th><th>status</th>"
            "<th>progress</th><th>params</th><th>decisions</th></tr>"
            f"{''.join(rows)}</table>")

    def _ui_actions(self, req: Request):
        """Browser-driven maintenance actions (the reference admin
        UI's POST handlers): run a detection round now, or submit a
        job by type — both share the JSON API handlers' logic."""
        import json as _json
        form = self._form(req)
        denied = self._ui_write_guard(req, form)
        if denied is not None:
            return denied
        if form.get("action") == "detect":
            self._trigger(self._FormShim({}))
            return 303, (b"", {"Location": "/ui/jobs",
                               "Content-Type": "text/plain"})
        if form.get("action") == "submit":
            try:
                params = _json.loads(form.get("params") or "{}")
            except ValueError as e:
                return self._page("Submit error",
                                  f"<p class='bad'>bad params JSON: "
                                  f"{e}</p>"
                                  "<p><a href='/ui/jobs'>back</a></p>")
            status, payload = self._submit_job(self._FormShim(
                {"jobType": form.get("jobType", ""),
                 "params": params}))
            if status != 200:
                import html as _html
                return self._page(
                    "Submit error",
                    f"<p class='bad'>"
                    f"{_html.escape(str(payload))}</p>"
                    "<p><a href='/ui/jobs'>back</a></p>")
            return 303, (b"", {"Location": "/ui/jobs",
                               "Content-Type": "text/plain"})
        return 400, {"error": "unknown action"}

    def _ui_config(self, req: Request):
        """Schema-driven config FORMS (admin/plugin/DESIGN.md
        SchemaCoordinator: worker Descriptors carry the field schema,
        the operator edits values, RunDetection delivers them)."""
        import html as _html
        with self.lock:
            schemas = {jt: list(fields)
                       for jt, fields in sorted(self.schemas.items())}
            values = {jt: dict(self.config.get(jt, {}))
                      for jt in schemas}
        forms = []
        for jt, fields in schemas.items():
            inputs = []
            for f in fields:
                name = f["name"]
                cur = values[jt].get(name, f.get("default", ""))
                ftype = f.get("type", "string")
                inputs.append(
                    f"<label>{_html.escape(name)} "
                    f"<small>({_html.escape(ftype)})</small> "
                    f"<input name='{_html.escape(name)}' "
                    f"value='{_html.escape(str(cur))}'></label><br>")
            forms.append(
                f"<h2>{_html.escape(jt)}</h2>"
                f"<form method='post' action='/ui/config'>"
                f"<input type='hidden' name='jobType' "
                f"value='{_html.escape(jt)}'>"
                f"{self._csrf_input()}"
                f"{''.join(inputs)}"
                f"<button>apply</button></form>")
        if not forms:
            forms = ["<p>no worker has registered a config schema "
                     "yet</p>"]
        return self._page("Config", "".join(forms))

    def _ui_config_submit(self, req: Request):
        """HTML-form arm of /maintenance/config POST: same schema
        validation, then redirect back to the form."""
        form = self._form(req)
        denied = self._ui_write_guard(req, form)
        if denied is not None:
            return denied
        form.pop("csrf", None)       # not a schema field
        jt = form.pop("jobType", "")
        status, payload = self._set_config(self._FormShim(
            {"jobType": jt, "values": form}))
        if status != 200:
            import html as _html
            return self._page(
                "Config error",
                f"<p class='bad'>{_html.escape(str(payload))}</p>"
                "<p><a href='/ui/config'>back</a></p>")
        return 303, (b"", {"Location": "/ui/config",
                           "Content-Type": "text/plain"})

    def _submit_job(self, req: Request):
        """Operator-submitted job (the analog of dispatching work from
        the admin UI / shell rather than detection) — e.g. a
        multi-volume batch EC job for the mesh-batched worker path."""
        b = req.json()
        job_type = b.get("jobType")
        if not job_type:
            return 400, {"error": "jobType required"}
        params = b.get("params", {})
        with self.lock:
            # a job nobody can run would sit pending forever and wedge
            # its dedupe key — refuse it at submit time
            if not any(w.can(job_type) for w in self.workers.values()):
                return 400, {"error": f"no registered worker has the "
                                      f"{job_type!r} capability"}
            key = b.get("dedupeKey") or uuid.uuid4().hex
            # a batch EC job claims every per-volume key too, so it can
            # never run concurrently with a detection-queued single-
            # volume job for one of its members (the loser's unwind
            # would delete the winner's mounted shards AFTER the
            # original volume is gone — permanent data loss)
            keys = [key]
            if job_type == "erasure_coding" and \
                    isinstance(params.get("volumeIds"), list):
                keys += [f"ec:{int(v)}" for v in params["volumeIds"]]
            for k in keys:
                existing = self._dedupe.get(k)
                if existing and self.jobs[existing].status in (
                        "pending", "assigned"):
                    return 409, {"error": f"conflicts with live job "
                                          f"{existing} ({k})",
                                 "jobId": existing, "deduped": True}
            rid, tparent = _trace_ctx()
            job = Job(job_id=uuid.uuid4().hex[:12], job_type=job_type,
                      params=params, dedupe_key=key,
                      request_id=rid, trace_parent=tparent)
            job.add_trace("submitted by operator")
            self.jobs[job.job_id] = job
            for k in keys:
                self._dedupe[k] = job.job_id
            self._persist_job(job)
        return 200, {"jobId": job.job_id}

    def _touch(self, worker_id: str) -> None:
        w = self.workers.get(worker_id)
        if w is not None:
            w.last_seen = time.monotonic()

    def _progress(self, req: Request):
        b = req.json()
        with self.lock:
            # progress is a liveness signal: a single-threaded worker
            # cannot poll mid-job, so the reaper must count this
            self._touch(b.get("workerId", ""))
            job = self.jobs.get(b["jobId"])
            if job is not None:
                job.progress = float(b.get("progress", 0.0))
                job.message = b.get("message", "")
                job.updated = time.time()
        return 200, {}

    def _complete(self, req: Request):
        b = req.json()
        # worker job spans ride the completion report (the worker has
        # no listener for trace.show to query); re-record them here so
        # this admin's /debug/traces serves the job's execution trace
        if b.get("spans"):
            from .. import tracing
            tracing.ingest(b["spans"])
        with self.lock:
            self._touch(b.get("workerId", ""))
            job = self.jobs.get(b["jobId"])
            if job is not None:
                reporter = b.get("workerId", "")
                if job.status != "assigned" or \
                        job.worker_id != reporter:
                    # only the current owner of a live assignment may
                    # complete it: finished jobs, stall-requeued jobs
                    # (status pending — inflight already returned by the
                    # reaper), and reassigned jobs all ignore the report
                    return 200, {"ignored": True}
                job.status = "done" if b.get("success") else "failed"
                job.message = b.get("message", "")
                job.progress = 1.0
                job.updated = time.time()
                job.add_trace(f"{job.status} by {reporter}: "
                              f"{job.message[:200]}")
                self._persist_job(job)
                w = self.workers.get(reporter)
                if w is not None:
                    w.inflight = max(0, w.inflight - 1)
        return 200, {}

    # -- ops API ----------------------------------------------------------

    _FIELD_TYPES = {"int": int, "float": float, "string": str,
                    "bool": bool}

    def _get_config(self, req: Request):
        """ConfigStore + SchemaCoordinator view: per-job-type schema
        (from worker Descriptors) with current values."""
        with self.lock:
            return 200, {"jobTypes": {
                jt: {"fields": fields,
                     "values": dict(self.config.get(jt, {}))}
                for jt, fields in sorted(self.schemas.items())}}

    def _set_config(self, req: Request):
        """Schema-validated config update ({jobType, values}); applied
        to workers with the next RunDetection, persisted across
        restarts."""
        b = req.json()
        jt = b.get("jobType", "")
        values = b.get("values", {})
        with self.lock:
            fields = self.schemas.get(jt)
            if fields is None:
                return 404, {"error": f"no schema for job type {jt!r} "
                                      f"(no worker registered it)"}
            by_name = {f["name"]: f for f in fields}
            cleaned = {}
            for name, val in values.items():
                f = by_name.get(name)
                if f is None:
                    return 400, {"error": f"unknown field {name!r} for "
                                          f"{jt} (schema: "
                                          f"{sorted(by_name)})"}
                want = self._FIELD_TYPES.get(f.get("type", "string"),
                                             str)
                try:
                    cleaned[name] = want(val) if want is not bool                         else (val if isinstance(val, bool)
                              else str(val).lower() in ("1", "true",
                                                        "yes"))
                except (TypeError, ValueError):
                    return 400, {"error":
                                 f"field {name!r} wants "
                                 f"{f.get('type')}, got {val!r}"}
            self.config.setdefault(jt, {}).update(cleaned)
            self._persist_config()
            return 200, {"jobType": jt,
                         "values": dict(self.config[jt])}

    def _job_detail(self, req: Request):
        """Full job record incl. the decision trace
        (DESIGN.md WorkflowMonitor surface)."""
        jid = req.query.get("id", "")
        with self.lock:
            job = self.jobs.get(jid)
            if job is None:
                return 404, {"error": f"no job {jid!r}"}
            return 200, job.to_json()

    def _queue(self, req: Request):
        with self.lock:
            return 200, {"jobs": [{
                "jobId": j.job_id, "jobType": j.job_type,
                "status": j.status, "progress": j.progress,
                "message": j.message, "params": j.params,
            } for j in sorted(self.jobs.values(),
                              key=lambda j: j.created)]}

    def _trigger(self, req: Request):
        with self.lock:
            self._pending_detection = [
                wid for wid, w in self.workers.items()
                if any(c.get("canDetect") for c in w.capabilities)]
            asked = list(self._pending_detection)
        return 200, {"asked": asked}

    # a worker silent for this long is presumed dead; its assigned jobs
    # requeue so the dedupe key stops blocking re-detection
    WORKER_DEAD_AFTER = 60.0
    # an assigned job with no progress for this long requeues even if
    # its worker still polls (covers a lost completion report)
    JOB_STALL_AFTER = 300.0

    def _detection_loop(self) -> None:
        tick = min(self.detection_interval, 5.0)
        next_detection = time.time() + self.detection_interval
        while not self._stop.wait(tick):
            self._reap_dead_workers()
            if time.time() >= next_detection:
                next_detection = time.time() + self.detection_interval
                with self.lock:
                    self._pending_detection = [
                        wid for wid, w in self.workers.items()
                        if any(c.get("canDetect")
                               for c in w.capabilities)]

    def _reap_dead_workers(self) -> None:
        now = time.time()        # job.updated is persisted wall time
        mono = time.monotonic()  # worker liveness is in-memory
        with self.lock:
            dead = {wid for wid, w in self.workers.items()
                    if w.inflight > 0 and
                    mono - w.last_seen > self.WORKER_DEAD_AFTER}
            for job in self.jobs.values():
                if job.status != "assigned":
                    continue
                # persisted wall timestamp survives an admin restart;
                # monotonic would not compare across processes
                stalled = (now - job.updated  # noqa: SWFS011
                           > self.JOB_STALL_AFTER)
                if job.worker_id in dead or stalled:
                    w = self.workers.get(job.worker_id)
                    if w is not None and job.worker_id not in dead:
                        w.inflight = max(0, w.inflight - 1)
                    job.status = "pending"
                    job.worker_id = ""
                    job.updated = now
                    job.message = "requeued: worker lost or stalled"
                    job.add_trace(job.message)
                    self._persist_job(job)
            for wid in dead:
                self.workers[wid].inflight = 0
