"""Failpoint plane: named fault-injection sites on every role.

Production EC stores live or die on behavior under partial failure
(arXiv:1709.05365: online EC degrades disproportionately under
component faults; arXiv:1908.01527's repair pipelining assumes
survivors can vanish mid-stream), so failure must be a first-class,
injectable, *tested* scenario — not something only a lucky SIGKILL in
CI ever exercises.  This module is the registry of named injection
sites compiled into the data plane; arming one makes the named call
site misbehave on demand, deterministically.

Sites are plain dotted strings, passed to `fire(site, key=...)` at the
instrumented call site.  The compiled-in sites:

  httpd.pool.connect       client pool: dialing a fresh connection
  httpd.pool.request       client pool: before each request attempt
  httpd.stream.chunk       http_stream_request: per sent window
  httpd.relay.chunk        http_relay: per relayed chunk
  httpd.download.chunk     http_download: per received chunk
  rpc.stub.call            gRPC stub: before each outbound call
  volume.shard_write.recv  scatter receiver: per received chunk
  volume.receive_file.recv receive_file: per received chunk
  volume.shard_read.serve  shard_read: before serving the range
  volume.read.serve        volume data path: before a needle GET is
                           answered (cache included) — key carries
                           the serving replica's url, so `match`
                           wedges exactly one replica (the hedged-
                           read chaos lever)
  ec.rebuild.slice         RemoteShardSource: per fetched window
  ec.encode.window         RemoteShardSink: per pushed window
  master.heartbeat         volume server: before each heartbeat POST
  master.lookup            master: /dir/lookup handler entry
  filer.entry.put          filer: before persisting an entry
  filer.chunk.fetch        filer: before a chunk view is resolved on
                           the read path (cache included)

Actions:

  error     raise FaultInjected (an OSError) at the site
  delay     sleep `ms` milliseconds, then continue
  truncate  return the "truncate" directive — the site ends its
            stream early (fewer bytes than promised, clean framing)
  drop      return the "drop" directive — the site severs the
            connection mid-body (dirty close, no terminal chunk)

Arms fire with probability `p` (default 1.0) from a deterministic
per-arm `random.Random(seed)`, at most `n` times (default unlimited),
and only when `match` (if set) is a substring of the site's `key`
argument (e.g. a peer url — fault one destination, not all).

Arming:

  * environment: SEAWEEDFS_TPU_FAULTS="site=action,k=v,k=v;site2=..."
    parsed at import (every role inherits it from its launcher);
  * runtime: POST /debug/faults on any role (server/debug.py), body
    {"spec": "..."} or {"site":..., "action":..., ...} or
    {"clear": true} — the chaos suite's lever.

Every trigger increments `faults_triggered_total{site}` in the shared
process registry (stats.PROCESS) so a chaos run can assert its faults
actually fired.
"""

from __future__ import annotations

import os
import random
import threading
import time

ACTIONS = ("error", "delay", "truncate", "drop")


class FaultInjected(OSError):
    """Raised at an armed `error` site.  An OSError subclass so every
    transport-failure handler (retry, failover, unwind) treats it
    exactly like the real network fault it stands in for."""


class _Arm:
    def __init__(self, site: str, action: str, p: float = 1.0,
                 n: "int | None" = None, ms: float = 0.0,
                 seed: "int | None" = None, match: str = ""):
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; "
                             f"use one of {ACTIONS}")
        self.site = site
        self.action = action
        self.p = min(max(float(p), 0.0), 1.0)
        self.n = None if n is None else int(n)
        self.ms = float(ms)
        self.match = match
        if seed is None:
            seed = _default_seed()
        self.seed = seed
        self._rng = random.Random(seed)

    def should_fire(self, key: str) -> bool:
        if self.n is not None and self.n <= 0:
            return False
        if self.match and self.match not in key:
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        if self.n is not None:
            self.n -= 1
        return True

    def describe(self) -> dict:
        return {"site": self.site, "action": self.action, "p": self.p,
                "n": self.n, "ms": self.ms, "match": self.match,
                "seed": self.seed}


_lock = threading.Lock()
_arms: "dict[str, list[_Arm]]" = {}
_triggered: "dict[str, int]" = {}


def _default_seed() -> int:
    try:
        return int(os.environ.get("SEAWEEDFS_TPU_FAULTS_SEED", "") or 0)
    except ValueError:
        return 0


def arm(site: str, action: str, p: float = 1.0,
        n: "int | None" = None, ms: float = 0.0,
        seed: "int | None" = None, match: str = "") -> None:
    a = _Arm(site, action, p=p, n=n, ms=ms, seed=seed, match=match)
    with _lock:
        _arms.setdefault(site, []).append(a)


def disarm(site: "str | None" = None) -> None:
    with _lock:
        if site is None:
            _arms.clear()
        else:
            _arms.pop(site, None)


def reset() -> None:
    """Disarm everything and zero the trigger counts (test isolation)."""
    with _lock:
        _arms.clear()
        _triggered.clear()


def parse_spec(spec: str) -> "list[_Arm]":
    """`site=action[,k=v...]` entries separated by `;`.  Keys: p, n,
    ms, seed, match (`,` separates options so a `match` value may
    hold a host:port).  Malformed entries raise ValueError — a chaos
    run with a typo'd fault spec must fail loudly, not run
    fault-free."""
    arms: list[_Arm] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        site, eq, rest = entry.partition("=")
        if not eq or not site.strip():
            raise ValueError(f"bad fault entry {entry!r}: "
                             f"want site=action[,k=v...]")
        parts = rest.split(",")
        action = parts[0].strip()
        kw: dict = {}
        for kv in parts[1:]:
            k, eq2, v = kv.partition("=")
            k = k.strip()
            if not eq2 or k not in ("p", "n", "ms", "seed", "match"):
                raise ValueError(f"bad fault option {kv!r} in {entry!r}")
            if k == "match":
                kw[k] = v.strip()
            elif k in ("p", "ms"):
                kw[k] = float(v)
            else:
                kw[k] = int(v)
        arms.append(_Arm(site.strip(), action, **kw))
    return arms


def arm_spec(spec: str) -> int:
    """Parse and arm a spec string; returns the number of arms added."""
    arms = parse_spec(spec)
    with _lock:
        for a in arms:
            _arms.setdefault(a.site, []).append(a)
    return len(arms)


def fire(site: str, key: str = "") -> "str | None":
    """The instrumented call site's hook.  Returns None (continue),
    or a directive string ("truncate" / "drop") the site interprets;
    raises FaultInjected for `error` arms; sleeps for `delay` arms.
    Unarmed sites cost one dict lookup under a lock."""
    with _lock:
        arms = _arms.get(site)
        if not arms:
            return None
        hit = None
        for a in arms:
            if a.should_fire(key):
                hit = a
                break
        if hit is None:
            return None
        _triggered[site] = _triggered.get(site, 0) + 1
        action, ms = hit.action, hit.ms
    _count_metric(site, action)
    if action == "delay":
        time.sleep(ms / 1e3)
        return None
    if action == "error":
        raise FaultInjected(
            f"fault injected at {site}" + (f" ({key})" if key else ""))
    return action


def _count_metric(site: str, action: str) -> None:
    from . import stats
    stats.PROCESS.counter_add(
        "faults_triggered_total", 1.0,
        help_text="armed failpoint triggers", site=site, action=action)


def armed() -> "list[dict]":
    with _lock:
        return [a.describe() for arms in _arms.values() for a in arms]


def triggered() -> "dict[str, int]":
    with _lock:
        return dict(_triggered)


def _arm_from_env() -> None:
    spec = os.environ.get("SEAWEEDFS_TPU_FAULTS", "")
    if spec:
        arm_spec(spec)


_arm_from_env()
