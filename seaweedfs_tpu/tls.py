"""TLS/mTLS for the control+data plane (weed/security/tls.go).

The reference mutually authenticates its gRPC plane with certificates
from security.toml ([grpc] ca/cert/key sections).  This build wires
the same trust model through Python's ssl: every HttpServer wraps its
socket when a TlsConfig is active, and every client helper
(httpd.http_bytes / http_json — the single funnel all roles dial
through) switches to https with the cluster CA pinned.  With
require_client_cert (mTLS), servers accept only peers presenting a
certificate signed by the cluster CA.

`generate_cluster_certs` mints a self-contained PKI (CA + server +
client certs) with `cryptography` — the analog of the reference's
`weed scaffold` + openssl recipes.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from dataclasses import dataclass


@dataclass
class TlsConfig:
    ca_cert: str            # PEM path: cluster CA certificate
    cert: str               # PEM path: this node's certificate chain
    key: str                # PEM path: this node's private key
    require_client_cert: bool = False  # mTLS (tls.go VerifyClientCert)

    def server_context(self) -> ssl.SSLContext:
        # cached: contexts are built once per config, not per request —
        # every heartbeat/read/raft RPC re-reading three PEM files and
        # forfeiting TLS session resumption would dominate latency
        ctx = self.__dict__.get("_server_ctx")
        if ctx is None:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.cert, self.key)
            if self.require_client_cert:
                ctx.load_verify_locations(self.ca_cert)
                ctx.verify_mode = ssl.CERT_REQUIRED
            self.__dict__["_server_ctx"] = ctx
        return ctx

    def client_context(self) -> ssl.SSLContext:
        ctx = self.__dict__.get("_client_ctx")
        if ctx is None:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.load_verify_locations(self.ca_cert)
            # cluster nodes address each other by IP:port; the SAN
            # carries the IPs, hostname verification stays on
            ctx.load_cert_chain(self.cert, self.key)
            self.__dict__["_client_ctx"] = ctx
        return ctx


def generate_cluster_certs(directory: str,
                           hosts: "list[str] | None" = None) -> dict:
    """Mint CA + node certificates; returns {"ca": ..., "cert": ...,
    "key": ...} paths.  One shared node cert serves both server and
    client roles (every role dials every other role)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    hosts = hosts or ["127.0.0.1", "localhost"]
    os.makedirs(directory, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def _name(cn):
        return x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    def _write(path, data):
        with open(path, "wb") as f:
            f.write(data)
        return path

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_cert = (x509.CertificateBuilder()
               .subject_name(_name("seaweedfs-tpu CA"))
               .issuer_name(_name("seaweedfs-tpu CA"))
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(minutes=5))
               .not_valid_after(now + datetime.timedelta(days=3650))
               .add_extension(x509.BasicConstraints(ca=True,
                                                    path_length=0),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))

    node_key = ec.generate_private_key(ec.SECP256R1())
    san = []
    for h in hosts:
        try:
            san.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            san.append(x509.DNSName(h))
    node_cert = (x509.CertificateBuilder()
                 .subject_name(_name("seaweedfs-tpu node"))
                 .issuer_name(ca_cert.subject)
                 .public_key(node_key.public_key())
                 .serial_number(x509.random_serial_number())
                 .not_valid_before(now - datetime.timedelta(minutes=5))
                 .not_valid_after(now + datetime.timedelta(days=825))
                 .add_extension(x509.SubjectAlternativeName(san),
                                critical=False)
                 .add_extension(
                     x509.ExtendedKeyUsage(
                         [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
                          x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]),
                     critical=False)
                 .sign(ca_key, hashes.SHA256()))

    pem = serialization.Encoding.PEM
    paths = {
        "ca": _write(os.path.join(directory, "ca.crt"),
                     ca_cert.public_bytes(pem)),
        "cert": _write(os.path.join(directory, "node.crt"),
                       node_cert.public_bytes(pem)),
        "key": _write(
            os.path.join(directory, "node.key"),
            node_key.private_bytes(
                pem, serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption())),
    }
    os.chmod(paths["key"], 0o600)
    return paths
