"""S3-compatible remote storage client + mount bookkeeping
(weed/remote_storage/s3/s3_storage_client.go,
weed/command/filer_remote_mount.go).

The client signs with our own SigV4 signer, so it talks to ANY
S3-compatible endpoint — including our own gateway, which is what the
tests (and the reference's) point it at.
"""

from __future__ import annotations

import json
import urllib.parse
import xml.etree.ElementTree as ET

from ..s3.auth import sign_request
from ..server.httpd import http_bytes

CONF_DIR = "/etc/remote"
MOUNTS_PATH = "/etc/remote/mounts.json"


class RemoteError(OSError):
    pass


class S3RemoteStorage:
    """remote_storage.RemoteStorageClient, S3 flavor."""

    def __init__(self, endpoint: str, access_key: str,
                 secret_key: str, bucket: str):
        self.endpoint = endpoint.removeprefix("http://")
        self.access_key = access_key
        self.secret_key = secret_key
        self.bucket = bucket

    @classmethod
    def from_conf(cls, conf: dict, bucket: str = "") -> "S3RemoteStorage":
        return cls(conf["endpoint"], conf.get("accessKey", ""),
                   conf.get("secretKey", ""),
                   bucket or conf.get("bucket", ""))

    def _call(self, method: str, key: str, body: bytes = b"",
              query: dict | None = None, headers: dict | None = None
              ) -> "tuple[int, bytes, dict]":
        path = f"/{self.bucket}/{key}" if key else f"/{self.bucket}"
        q = dict(query or {})
        signed = sign_request(method, self.endpoint, path, q,
                              dict(headers or {}), body,
                              self.access_key, self.secret_key)
        qs = ("?" + urllib.parse.urlencode(q)) if q else ""
        return http_bytes(method, f"{self.endpoint}" +
                          urllib.parse.quote(path) + qs,
                          body or None, signed)

    # -- objects -----------------------------------------------------------

    def traverse(self, prefix: str = ""):
        """Yield (key, size, mtime_iso, etag) under prefix
        (ListObjectsV2 pagination)."""
        token = ""
        while True:
            q = {"list-type": "2", "prefix": prefix,
                 "max-keys": "1000"}
            if token:
                q["continuation-token"] = token
            st, body, _ = self._call("GET", "", query=q)
            if st != 200:
                raise RemoteError(f"list {self.bucket}/{prefix}: {st}")
            root = ET.fromstring(body)
            ns = root.tag.split("}")[0] + "}" if "}" in root.tag else ""
            for c in root.iter(f"{ns}Contents"):
                fields = {el.tag.rsplit("}", 1)[-1]: (el.text or "")
                          for el in c}
                yield (fields["Key"], int(fields.get("Size", 0)),
                       fields.get("LastModified", ""),
                       fields.get("ETag", "").strip('"'))
            token = ""
            for el in root.iter(f"{ns}NextContinuationToken"):
                token = el.text or ""
            if not token:
                return

    def read(self, key: str, offset: int = 0,
             size: "int | None" = None) -> bytes:
        headers = {}
        if offset or size is not None:
            end = "" if size is None else str(offset + size - 1)
            headers["range"] = f"bytes={offset}-{end}"
        st, body, _ = self._call("GET", key, headers=headers)
        if st == 404:
            raise FileNotFoundError(f"{self.bucket}/{key}")
        if st not in (200, 206):
            raise RemoteError(f"read {self.bucket}/{key}: {st}")
        if st == 200 and (offset or size is not None):
            # endpoint ignored Range: slice locally
            body = body[offset:offset + size if size else None]
        return body

    def write(self, key: str, data: bytes) -> None:
        st, body, _ = self._call("PUT", key, data)
        if st != 200:
            raise RemoteError(f"write {self.bucket}/{key}: {st} "
                              f"{body[:200]!r}")

    def delete(self, key: str) -> None:
        st, _, _ = self._call("DELETE", key)
        if st not in (200, 204, 404):
            raise RemoteError(f"delete {self.bucket}/{key}: {st}")

    def stat(self, key: str) -> "dict | None":
        st, _, h = self._call("HEAD", key)
        if st == 404:
            return None
        if st != 200:
            raise RemoteError(f"stat {self.bucket}/{key}: {st}")
        return {"size": int(h.get("Content-Length", 0)),
                "etag": h.get("ETag", "").strip('"')}

    def list_buckets(self) -> "list[str]":
        """GET / (S3 ListBuckets) on the remote endpoint — the one
        service-level call, signed for path "/" (no bucket prefix)."""
        import re as _re
        signed = sign_request("GET", self.endpoint, "/", {}, {}, b"",
                              self.access_key, self.secret_key)
        status, body, _ = http_bytes(
            "GET", f"{self.endpoint}/", None, signed)
        if status != 200:
            raise RemoteError(f"list buckets: {status}")
        return _re.findall(r"<Name>([^<]+)</Name>", body.decode(
            "utf-8", "replace"))

    def create_bucket(self) -> None:
        st, _, _ = self._call("PUT", "")
        if st not in (200, 409):
            raise RemoteError(f"create bucket {self.bucket}: {st}")


# -- conf + mount bookkeeping (stored IN the filer) ------------------------

def conf_to_pb_bytes(name: str, conf: dict) -> bytes:
    """Our JSON conf -> the reference's remote_pb.RemoteConf wire
    bytes (pb/remote.proto; the reference persists this form at
    /etc/remote/<name>.remote.conf)."""
    from ..pb import remote_pb2
    pb = remote_pb2.RemoteConf(
        type=conf.get("type", "s3"), name=name,
        s3_access_key=conf.get("accessKey", ""),
        s3_secret_key=conf.get("secretKey", ""),
        s3_region=conf.get("region", ""),
        s3_endpoint=conf.get("endpoint", ""),
        s3_force_path_style=bool(conf.get("forcePathStyle", True)),
        s3_v4_signature=bool(conf.get("v4Signature", True)))
    return pb.SerializeToString()


def conf_from_pb_bytes(data: bytes) -> dict:
    from ..pb import remote_pb2
    pb = remote_pb2.RemoteConf.FromString(data)
    return {"type": pb.type or "s3", "endpoint": pb.s3_endpoint,
            "accessKey": pb.s3_access_key,
            "secretKey": pb.s3_secret_key, "region": pb.s3_region,
            "forcePathStyle": pb.s3_force_path_style,
            "v4Signature": pb.s3_v4_signature}


def save_conf(filer: str, name: str, conf: dict) -> None:
    st, _, _ = http_bytes(
        "PUT", f"{filer}{CONF_DIR}/{name}.conf",
        json.dumps(conf).encode())
    if st not in (200, 201):
        raise RemoteError(f"save remote conf {name}: {st}")
    # wire-form twin beside it so a reference deployment reading this
    # filer tree finds the config in its own format
    try:
        http_bytes("PUT", f"{filer}{CONF_DIR}/{name}.remote.conf",
                   conf_to_pb_bytes(name, conf))
    except (OSError, ImportError):
        pass


def load_conf(filer: str, name: str) -> dict:
    st, body, _ = http_bytes("GET", f"{filer}{CONF_DIR}/{name}.conf")
    if st == 200:
        return json.loads(body)
    # fall back to the reference's protobuf conf (a tree configured
    # by the reference's `remote.configure` works as-is)
    st, body, _ = http_bytes(
        "GET", f"{filer}{CONF_DIR}/{name}.remote.conf")
    if st == 200:
        try:
            return conf_from_pb_bytes(body)
        except Exception as e:
            raise RemoteError(
                f"undecodable remote conf {name!r}: {e}") from e
    raise RemoteError(f"no remote conf {name!r} ({st})")


def load_mounts(filer: str) -> dict:
    st, body, _ = http_bytes("GET", f"{filer}{MOUNTS_PATH}")
    if st != 200:
        return {}
    return json.loads(body)


def save_mounts(filer: str, mounts: dict) -> None:
    st, _, _ = http_bytes("PUT", f"{filer}{MOUNTS_PATH}",
                          json.dumps(mounts, indent=1).encode())
    if st not in (200, 201):
        raise RemoteError(f"save mounts: {st}")


def remote_for_path(filer: str, path: str
                    ) -> "tuple[S3RemoteStorage, str] | None":
    """(client, remote_key) for a filer path under a mount, else
    None.  Longest mount prefix wins."""
    mounts = load_mounts(filer)
    best = None
    for d in mounts:
        cd = d.rstrip("/")
        if (path == cd or path.startswith(cd + "/")) and \
                (best is None or len(cd) > len(best)):
            best = cd
    if best is None:
        return None
    m = mounts[best]
    conf = load_conf(filer, m["conf"])
    client = S3RemoteStorage.from_conf(conf, m.get("bucket", ""))
    rel = path[len(best):].lstrip("/")
    prefix = m.get("keyPrefix", "")
    key = (prefix.rstrip("/") + "/" + rel).lstrip("/") if prefix \
        else rel
    return client, key


def _remote_marker(size: int, etag: str = "") -> str:
    return json.dumps({"size": size, "etag": etag})


def mount_remote(filer: str, directory: str, conf_name: str,
                 bucket: str, key_prefix: str = "") -> int:
    """Record the mount and pull remote metadata into filer entries
    (filer_remote_mount.go syncMetadata): each object becomes an
    entry with a remote pointer and NO chunks.  Returns entry count."""
    conf = load_conf(filer, conf_name)
    client = S3RemoteStorage.from_conf(conf, bucket)
    mounts = load_mounts(filer)
    mounts[directory.rstrip("/")] = {"conf": conf_name,
                                     "bucket": bucket,
                                     "keyPrefix": key_prefix}
    save_mounts(filer, mounts)
    n = 0
    for key, size, _mtime, etag in client.traverse(key_prefix):
        rel = key[len(key_prefix):].lstrip("/") if key_prefix else key
        if not rel or rel.endswith("/"):
            continue
        path = f"{directory.rstrip('/')}/{rel}"
        marker = _remote_marker(size, etag)
        # syncMetadata semantics: only touch entries whose remote
        # pointer CHANGED, and never replace a purely-local file —
        # an entry with chunks but NO remote marker is a local edit
        # not yet pushed; clobbering it would lose data
        existing = _meta_lookup(filer, path)
        if existing is not None:
            ext_marker = existing.get("extended", {}).get("remote")
            if ext_marker == marker:
                n += 1
                continue
            if ext_marker is None and existing.get("chunks"):
                n += 1     # local file shadows the remote one
                continue
        _meta_create(filer, path, {"remote": marker})
        n += 1
    return n


def _meta_lookup(filer: str, path: str) -> "dict | None":
    st, body, _ = http_bytes(
        "GET", f"{filer}/__meta__/lookup?path=" +
        urllib.parse.quote(path))
    return json.loads(body) if st == 200 else None


def _meta_create(filer: str, path: str, extended: dict) -> None:
    st, _, _ = http_bytes(
        "POST", f"{filer}/__meta__/create",
        json.dumps({"path": path, "extended": extended}).encode(),
        {"Content-Type": "application/json"})
    if st != 200:
        raise RemoteError(f"meta create {path}: {st}")


def cache_path(filer: str, path: str,
               located: "tuple[S3RemoteStorage, str] | None" = None
               ) -> int:
    """Materialize remote content into local chunks (remote.cache):
    returns bytes cached.  The ORIGINAL remote marker is re-attached
    verbatim — inventing a new one (e.g. without the etag) would make
    the next meta.sync see a "changed" pointer and evict the cache.
    `located` lets bulk callers resolve the mount once."""
    entry = _meta_lookup(filer, path)
    marker = (entry or {}).get("extended", {}).get("remote")
    if marker is None:
        raise RemoteError(f"{path} is not remote-backed")
    if located is None:
        located = remote_for_path(filer, path)
        if located is None:
            raise RemoteError(f"{path} is not under a remote mount")
    client, key = located
    data = client.read(key)
    st, _, _ = http_bytes("PUT", filer + urllib.parse.quote(path),
                          data)
    if st not in (200, 201):
        raise RemoteError(f"cache write {path}: {st}")
    # content PUT rebuilt the entry: re-attach the SAME marker
    _meta_patch_extended(filer, path, {"remote": marker})
    return len(data)


def uncache_path(filer: str, path: str) -> None:
    """Drop local chunks, keep the remote-backed entry
    (remote.uncache)."""
    st, body, _ = http_bytes(
        "GET", f"{filer}/__meta__/lookup?path=" +
        urllib.parse.quote(path))
    if st != 200:
        raise RemoteError(f"lookup {path}: {st}")
    entry = json.loads(body)
    marker = entry.get("extended", {}).get("remote")
    if not marker:
        raise RemoteError(f"{path} is not remote-backed")
    _meta_create(filer, path, {"remote": marker})   # replaces chunks


def _meta_patch_extended(filer: str, path: str,
                         extended: dict) -> None:
    st, _, _ = http_bytes(
        "POST", f"{filer}/__meta__/patch_extended",
        json.dumps({"path": path, "extended": extended}).encode(),
        {"Content-Type": "application/json"})
    if st != 200:
        raise RemoteError(f"meta patch {path}: {st}")
