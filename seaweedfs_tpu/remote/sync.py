"""filer.remote.sync (weed/command/filer_remote_sync.go +
filer_remote_sync_dir.go): tail the filer's metadata log and push
local changes under a remote-mounted directory back to the foreign
store — writes upload, deletes delete, renames delete+upload.

Offset checkpointing mirrors filer.sync: the last fully-applied
event's tsNs persists to a local state file, so a restarted syncer
resumes without skipping or reapplying history
(remote_storage/track_sync_offset.go)."""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse

from ..server.httpd import http_bytes, http_json
from .remote_storage import RemoteError, load_conf, load_mounts, \
    S3RemoteStorage


class RemoteSyncer:
    def __init__(self, filer: str, directory: str,
                 state_path: str | None = None,
                 poll_interval: float = 0.5):
        self.filer = filer
        self.dir = directory.rstrip("/")
        self.state_path = state_path or \
            f"remote-sync{self.dir.replace('/', '_')}.offset"
        self.poll_interval = poll_interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        mounts = load_mounts(filer)
        if self.dir not in mounts:
            raise RemoteError(f"{self.dir} is not remote-mounted")
        m = mounts[self.dir]
        self.client = S3RemoteStorage.from_conf(
            load_conf(filer, m["conf"]), m.get("bucket", ""))
        self.key_prefix = m.get("keyPrefix", "")

    # -- offset checkpoint ------------------------------------------------

    def _load_offset(self) -> int:
        try:
            with open(self.state_path) as f:
                return int(json.load(f)["tsNs"])
        except (OSError, ValueError, KeyError):
            return 0

    def _save_offset(self, ts_ns: int) -> None:
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"tsNs": ts_ns}, f)
        os.replace(tmp, self.state_path)

    # -- event application -------------------------------------------------

    def _key_for(self, path: str) -> "str | None":
        if not (path == self.dir or path.startswith(self.dir + "/")):
            return None
        rel = path[len(self.dir):].lstrip("/")
        if not rel:
            return None
        return (self.key_prefix.rstrip("/") + "/" + rel).lstrip("/") \
            if self.key_prefix else rel

    def _apply(self, ev: dict) -> None:
        new = ev.get("newEntry")
        old = ev.get("oldEntry")
        # deletes (incl. the delete half of renames leaving the dir)
        if old and not (new and new.get("fullPath") ==
                        old.get("fullPath")):
            key = self._key_for(old["fullPath"])
            if key and not old.get("isDirectory"):
                self.client.delete(key)
        if new and not new.get("isDirectory"):
            key = self._key_for(new["fullPath"])
            if key is None:
                return
            ext = new.get("extended", {})
            if ext.get("remote") and not new.get("chunks"):
                return      # our own mount-metadata entries
            st, body, _ = http_bytes(
                "GET", self.filer +
                urllib.parse.quote(new["fullPath"]))
            if st != 200:
                return
            # idempotence guard: remote.cache round-trips content the
            # remote already holds — an md5-matching object needs no
            # re-upload (and must not clobber concurrent remote-side
            # updates with a stale copy)
            import hashlib
            stat = self.client.stat(key)
            if stat is not None and stat.get("etag") == \
                    hashlib.md5(body).hexdigest():
                return
            self.client.write(key, body)

    def run_once(self) -> int:
        """Apply pending events; returns how many were applied."""
        since = self._load_offset()
        r = http_json("GET", f"{self.filer}/__meta__/events"
                             f"?sinceNs={since}&limit=500")
        applied = 0
        for ev in r.get("events", []):
            self._apply(ev)
            self._save_offset(int(ev["tsNs"]))
            applied += 1
        return applied

    # -- daemon ------------------------------------------------------------

    def start(self) -> "RemoteSyncer":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                n = self.run_once()
            except (OSError, RemoteError):
                n = 0
            if n == 0:
                self._stop.wait(self.poll_interval)
