"""Remote storage gateway (reference: weed/remote_storage/ +
weed/command/filer_remote_mount.go / filer_remote_sync.go).

A filer directory can MOUNT a prefix of a foreign S3-compatible
object store: metadata is pulled into filer entries carrying a remote
pointer (filer_pb.RemoteEntry analog in extended["remote"]) with no
chunks; the filer read path fetches uncached content straight from
the remote (read-through), `remote.cache` materializes it into local
chunks, and RemoteSyncer tails the filer metadata log to push local
writes/deletes back up — the reference's filer.remote.sync loop.

Remote connection configs persist in the filer under
/etc/remote/<name>.conf; mounts in /etc/remote/mounts.json — the
same place the reference keeps them, so every filer/gateway process
sees one truth.
"""

from .remote_storage import (RemoteError, S3RemoteStorage, cache_path,
                             load_conf, load_mounts, mount_remote,
                             remote_for_path, save_conf, save_mounts,
                             uncache_path)
from .sync import RemoteSyncer

__all__ = ["RemoteError", "S3RemoteStorage", "RemoteSyncer",
           "cache_path", "load_conf", "load_mounts", "mount_remote",
           "remote_for_path", "save_conf", "save_mounts",
           "uncache_path"]
