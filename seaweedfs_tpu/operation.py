"""Client SDK verbs (weed/operation/): assign, upload, submit, lookup,
delete — the operations every gateway and tool builds on."""

from __future__ import annotations

from dataclasses import dataclass

from .server.httpd import http_bytes, http_json


@dataclass
class Assignment:
    fid: str
    url: str
    public_url: str
    count: int


def assign(master: str, count: int = 1, collection: str = "",
           replication: str = "", ttl: str = "") -> Assignment:
    """operation/assign_file_id.go Assign."""
    qs = f"count={count}"
    if collection:
        qs += f"&collection={collection}"
    if replication:
        qs += f"&replication={replication}"
    if ttl:
        qs += f"&ttl={ttl}"
    r = http_json("GET", f"{master}/dir/assign?{qs}")
    if "error" in r:
        raise RuntimeError(f"assign: {r['error']}")
    return Assignment(r["fid"], r["url"], r.get("publicUrl", r["url"]),
                      r.get("count", count))


def upload(url: str, fid: str, data: bytes, name: str = "",
           mime: str = "") -> dict:
    """operation/upload_content.go Upload."""
    qs = f"?name={name}" if name else ""
    headers = {"Content-Type": mime} if mime else {}
    status, body, _ = http_bytes("POST", f"{url}/{fid}{qs}", data, headers)
    if status >= 300:
        raise RuntimeError(f"upload {fid} -> {status}: {body[:200]!r}")
    import json
    return json.loads(body)


def submit(master: str, data: bytes, name: str = "", mime: str = "",
           collection: str = "", replication: str = "",
           ttl: str = "") -> str:
    """operation/submit.go: assign + upload; returns the fid."""
    a = assign(master, collection=collection, replication=replication,
               ttl=ttl)
    upload(a.url, a.fid, data, name=name, mime=mime)
    return a.fid


def lookup(master: str, vid: int) -> list[dict]:
    """operation/lookup.go Lookup -> [{url, publicUrl}]."""
    r = http_json("GET", f"{master}/dir/lookup?volumeId={vid}")
    if "error" in r:
        raise LookupError(r["error"])
    return r["locations"]


def read(master: str, fid: str, offset: int = 0,
         size: int | None = None) -> bytes:
    """Full or ranged needle read (ranged avoids whole-chunk transfers
    on the filer's chunk-view path)."""
    vid = int(fid.split(",", 1)[0])
    locs = lookup(master, vid)
    headers = {}
    if offset or size is not None:
        end = f"{offset + size - 1}" if size is not None else ""
        headers["Range"] = f"bytes={offset}-{end}"
    last_err = None
    for loc in locs:
        status, body, _ = http_bytes("GET", f"{loc['url']}/{fid}",
                                     None, headers)
        if status in (200, 206):
            return body
        last_err = f"{loc['url']} -> {status}"
    raise RuntimeError(f"read {fid}: {last_err}")


def delete(master: str, fid: str) -> None:
    vid = int(fid.split(",", 1)[0])
    for loc in lookup(master, vid):
        http_bytes("DELETE", f"{loc['url']}/{fid}")
