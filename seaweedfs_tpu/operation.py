"""Client SDK verbs (weed/operation/): assign, upload, submit, lookup,
delete — the operations every gateway and tool builds on.  Lookups go
through a TTL'd vid->locations cache (weed/wdclient/vid_map.go)."""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from dataclasses import dataclass

from . import security
from .server.httpd import http_bytes, http_json
from .util import deadline as _deadline
from .util import request_id


class VidCache:
    """wdclient/vid_map.go: volume-id -> locations with TTL + explicit
    invalidation on read failure.  TTL math runs on the monotonic
    clock (SWFS011): an NTP step backwards would otherwise pin stale
    locations alive indefinitely, and a step forward would flush a
    fresh cache on every lookup."""

    TTL = 10.0

    def __init__(self):
        self._m: dict[tuple[str, int], tuple[float, list[dict]]] = {}
        self._lock = threading.Lock()

    def get(self, master: str, vid: int) -> "list[dict] | None":
        with self._lock:
            hit = self._m.get((master, vid))
            if hit and time.monotonic() - hit[0] < self.TTL:
                return hit[1]
        return None

    def put(self, master: str, vid: int, locs: list[dict]) -> None:
        with self._lock:
            self._m[(master, vid)] = (time.monotonic(), locs)

    def invalidate(self, master: str, vid: int) -> None:
        with self._lock:
            self._m.pop((master, vid), None)


_vid_cache = VidCache()


# -- leader-following master access (wdclient/masterclient.go:471
#    KeepConnectedToMaster + leader re-dial) ------------------------------

_leader_cache: dict[str, str] = {}
_leader_lock = threading.Lock()


def master_json(master: str, method: str, path: str,
                payload: dict | None = None, timeout: float = 30.0,
                headers: dict | None = None) -> dict:
    """Call a master endpoint against an HA seed list.

    `master` may be one address or a comma-separated seed list; followers
    answer leader-only paths with {"error": "not leader", "leader": url}
    and this helper re-dials the hinted leader (the reference's
    masterclient re-dial on leadership announcements).  The discovered
    leader is cached per seed-spec for subsequent calls."""
    seeds = [s.strip() for s in master.split(",") if s.strip()]
    with _leader_lock:
        cached = _leader_cache.get(master)
    order = ([cached] if cached else []) + \
        [s for s in seeds if s != cached]
    last = "no masters configured"
    last_exc: "OSError | None" = None
    tried: set[str] = set()
    while order:
        url = order.pop(0)
        if url in tried:
            continue
        tried.add(url)
        try:
            r = http_json(method, f"{url}{path}", payload, timeout,
                          headers=headers)
        except _deadline.DeadlineExceeded:
            # budget verdict: trying the next seed cannot conjure
            # time, and erasing the type here would cost the caller
            # its 504 translation (and re-assign loops their fail-fast)
            raise
        except OSError as e:
            last = f"{url}: {e}"
            last_exc = e
            continue
        if r.get("error") == "not leader":
            hint = r.get("leader", "")
            last = f"{url}: not leader"
            if hint and hint not in tried:
                order.insert(0, hint)
            continue
        with _leader_lock:
            _leader_cache[master] = url
        return r
    # chain the transport exception: callers can see through the
    # wrapper to e.g. a BreakerOpen's retry_after (assign_and_upload
    # waits a master breaker's cooldown out instead of failing fast)
    raise OSError(f"master_json {path}: {last}") from last_exc


@dataclass
class Assignment:
    fid: str
    url: str
    public_url: str
    count: int
    auth: str = ""  # per-fid write jwt minted by the master


def assign(master: str, count: int = 1, collection: str = "",
           replication: str = "", ttl: str = "") -> Assignment:
    """operation/assign_file_id.go Assign."""
    qs = f"count={count}"
    if collection:
        qs += f"&collection={collection}"
    if replication:
        qs += f"&replication={replication}"
    if ttl:
        qs += f"&ttl={ttl}"
    from . import profiling
    with profiling.stage("assign"):
        r = master_json(master, "GET", f"/dir/assign?{qs}",
                        timeout=_deadline.io_timeout(
                            30.0, site="master.assign"))
    if "error" in r:
        raise RuntimeError(f"assign: {r['error']}")
    return Assignment(r["fid"], r["url"], r.get("publicUrl", r["url"]),
                      r.get("count", count), r.get("auth", ""))


class UploadError(RuntimeError):
    def __init__(self, msg: str, status: int):
        super().__init__(msg)
        self.status = status


def upload(url: str, fid: str, data: bytes, name: str = "",
           mime: str = "", auth: str = "") -> dict:
    """operation/upload_content.go Upload.  `auth` is the per-fid write
    jwt from assign (falls back to signing locally when this process
    holds the write key, e.g. in-process filer).

    Plain anonymous uploads (no name, no mime, no jwt — the filer's
    chunk shape) try the server's native C++ write plane first
    (server/write_plane.py): the C++ epoll loop recvs, appends and
    acks with zero Python on the server, and this side talks to it
    over a lean persistent socket instead of http.client — together
    the big per-request CPU cuts of the ISSUE 12 funnel.  Anything
    the plane doesn't own 404s and falls through to the pooled POST
    below, byte-for-byte the original path."""
    if not name and not mime and not auth and data and \
            not security.current().volume_write_key:
        from . import profiling
        with profiling.stage("upload"):
            r = _write_via_write_plane(url, fid, data)
        if r is not None:
            # flight-recorder flag: this write was acked by the C++
            # plane, Python never touched the server-side needle path
            profiling.flight_note("nativePlane", "write")
            return r
    qs = "?" + urllib.parse.urlencode({"name": name}) if name else ""
    headers = {"Content-Type": mime} if mime else {}
    # a fixed-fid needle write is idempotent by construction (a replay
    # of the same fid+cookie+bytes is answered "unchanged" by
    # volume.write_needle's dedup) — declaring it lets the pooled
    # keep-alive client re-issue the POST inline when a REUSED socket
    # died before the request hit the wire, instead of surfacing every
    # keep-alive race as a tenant-visible error or a fresh-assign
    # retry round (the funnel stays on warm sockets end-to-end)
    headers["X-Idempotent"] = "1"
    if not auth:
        auth = security.current().write_jwt(fid)
    if auth:
        headers["Authorization"] = f"Bearer {auth}"
    from . import profiling
    with profiling.stage("upload"):
        status, body, _ = http_bytes(
            "POST", f"{url}/{fid}{qs}", data, headers,
            timeout=_deadline.io_timeout(60.0, site="volume.upload"))
    if status >= 300:
        raise UploadError(f"upload {fid} -> {status}: {body[:200]!r}",
                          status)
    return json.loads(body)


# -- assign batching (the persistent-funnel half of the group-commit
#    write path): one master round-trip reserves a RANGE of file keys
#    (assign?count=N against a range-reserving sequencer), and the
#    next N-1 writes derive their fids locally — same vid, same
#    cookie, key+i — exactly the reference's count-assign contract
#    (operation/assign_file_id.go Fid_i derivation).  The master hop
#    was ~25% of the filer write wall; amortized N-fold it vanishes
#    from the funnel.  Derived fids carry no per-fid master JWT; in
#    signed deployments the uploader signs locally with the shared
#    write key (security.toml is cluster-wide), which upload() already
#    does for auth="".
#
#    Safety: windows expire after ASSIGN_TTL (one heartbeat-ish, so a
#    volume the master would no longer pick is never written long
#    after its state changed), are keyed by the full placement spec,
#    and are dropped on any upload failure BEFORE the fresh-assign
#    retry (a readonly/moved volume costs one retry, exactly as
#    before).  SEAWEEDFS_TPU_ASSIGN_BATCH sets the window (default
#    16); 1 restores per-write assigns.

class _AssignCache:
    TTL = 2.0

    def __init__(self):
        self._m: dict = {}          # spec -> [Assignment, next_i, exp]
        self._lock = threading.Lock()
        # single-flight window refresh: when a window exhausts, every
        # concurrent writer misses at once — one refresher goes to the
        # master, the rest wait on this lock and re-take from the
        # fresh window (a thundering herd of N assigns per refresh
        # otherwise lands on the master in lockstep)
        self._refresh: dict = {}

    def refresh_lock(self, spec) -> "threading.Lock":
        with self._lock:
            lk = self._refresh.get(spec)
            if lk is None:
                lk = self._refresh[spec] = threading.Lock()
            return lk

    def take(self, spec) -> "Assignment | None":
        with self._lock:
            ent = self._m.get(spec)
            if ent is None:
                return None
            a, i, exp = ent
            if i >= a.count or time.monotonic() > exp:
                del self._m[spec]
                return None
            ent[1] += 1
        if i == 0:
            return a
        from .storage import types as _types
        base = _types.parse_file_id(a.fid)
        fid = str(_types.FileId(base.volume_id, base.key + i,
                                base.cookie))
        return Assignment(fid, a.url, a.public_url, 1, auth="")

    def put(self, spec, a: Assignment) -> None:
        if a.count <= 1:
            return
        with self._lock:
            # [.., 1, ..]: the base fid is handed to the caller
            self._m[spec] = [a, 1, time.monotonic() + self.TTL]

    def invalidate(self, spec) -> None:
        with self._lock:
            self._m.pop(spec, None)


_assign_cache = _AssignCache()


def assign_batch_size() -> int:
    import os
    try:
        return max(1, int(os.environ.get(
            "SEAWEEDFS_TPU_ASSIGN_BATCH", "") or 16))
    except ValueError:
        return 16


def assign_and_upload(master: str, data: bytes, name: str = "",
                      mime: str = "", collection: str = "",
                      replication: str = "", ttl: str = "",
                      retries: int = 3) -> "tuple[Assignment, dict]":
    """assign + upload with a FRESH assign on each retry (the
    reference's assign-then-upload loop).  Retried: transport
    failures, 5xx, and 409 volume-state rejections — a volume marked
    readonly for EC encode between the assign and the upload is a
    routine race once background maintenance runs under live traffic
    (the soak scenario), and the stale assignment, not the data, is
    what's wrong.  Other 4xx are deterministic rejections and raise
    immediately.  Assigns are batched through the module's window
    cache (see _AssignCache); any failure drops the window first so
    the retry always re-assigns fresh.  Returns (assignment, upload
    response)."""
    last: Exception | None = None
    batch = assign_batch_size()
    spec = (master, collection, replication, ttl)
    for attempt in range(max(retries, 1)):
        if attempt:
            # short ramp before re-assigning: the usual cause is a
            # volume-state transition the master hasn't absorbed yet
            # (readonly heartbeats race); re-assigning in the same
            # millisecond just replays the stale map
            time.sleep(0.05 * attempt)
        from_cache = False
        try:
            a = _assign_cache.take(spec) if batch > 1 and \
                not attempt else None
            from_cache = a is not None
            if a is None and batch > 1 and not attempt:
                # single-flight: one thread refreshes the window, the
                # stampede re-takes from it
                with _assign_cache.refresh_lock(spec):
                    a = _assign_cache.take(spec)
                    from_cache = a is not None
                    if a is None:
                        a = assign(master, count=batch,
                                   collection=collection,
                                   replication=replication, ttl=ttl)
                        _assign_cache.put(spec, a)
            elif a is None:
                a = assign(master, count=batch, collection=collection,
                           replication=replication, ttl=ttl)
            r = upload(a.url, a.fid, data, name=name, mime=mime,
                       auth=a.auth)
            return a, r
        except UploadError as e:
            _assign_cache.invalidate(spec)
            if not from_cache and e.status != 409 and e.status < 500:
                raise  # deterministic rejection — retrying can't help
            # a rejected CACHED fid is stale-window evidence (the
            # volume moved/unmounted/filled since the assign), never a
            # verdict on the data: drop the window, re-assign fresh
            last = e
        except _deadline.DeadlineExceeded:
            # the budget is spent: re-assigning cannot conjure time —
            # fail fast (the edge answers 504 / the client's error
            # path owns recovery with a fresh budget)
            _assign_cache.invalidate(spec)
            raise
        except (RuntimeError, OSError) as e:
            _assign_cache.invalidate(spec)
            last = e
            from .util.retry import BreakerOpen
            cause = e if isinstance(e, BreakerOpen) else e.__cause__
            if isinstance(cause, BreakerOpen) and \
                    attempt + 1 < max(retries, 1):
                # the breaker'd peer is a SOLE dependency here (the
                # master, or the assigned volume): fail-fast exists to
                # fan AWAY from a sick peer, but with nowhere else to
                # go the right move is to wait the cooldown out — a
                # brief master restart then costs this write latency,
                # not a tenant-visible 500
                time.sleep(min(max(cause.retry_after, 0.1), 2.0))
    raise RuntimeError(f"upload failed after {retries} attempts: {last}")


def submit(master: str, data: bytes, name: str = "", mime: str = "",
           collection: str = "", replication: str = "",
           ttl: str = "", retries: int = 3) -> str:
    """operation/submit.go: assign + upload; returns the fid."""
    a, _ = assign_and_upload(master, data, name=name, mime=mime,
                             collection=collection,
                             replication=replication, ttl=ttl,
                             retries=retries)
    return a.fid


_followers: "dict[str, object]" = {}
_follower_refs: "dict[str, int]" = {}
_followers_lock = threading.Lock()


def enable_follow(master: str) -> None:
    """Start (refcounted per master spec, process-wide) the wdclient
    follow stream: a push-fed vid map + leader tracking over
    /cluster/watch (masterclient.go:471 KeepConnectedToMaster).
    Long-lived processes (filer, mount, gateways) call this; lookups
    then resolve from the pushed map with no RPC and no TTL staleness.
    Each enable_follow must be paired with one disable_follow — the
    stream stops when the last user leaves (two filers in one process
    must not kill each other's stream)."""
    from .wdclient import MasterFollower
    with _followers_lock:
        _follower_refs[master] = _follower_refs.get(master, 0) + 1
        if master not in _followers:
            _followers[master] = MasterFollower(master).start()


def disable_follow(master: str) -> None:
    with _followers_lock:
        refs = _follower_refs.get(master, 0) - 1
        if refs > 0:
            _follower_refs[master] = refs
            return
        _follower_refs.pop(master, None)
        f = _followers.pop(master, None)
    if f is not None:
        f.stop()


def lookup(master: str, vid: int, use_cache: bool = True) -> list[dict]:
    """operation/lookup.go Lookup -> [{url, publicUrl}].  Resolution
    order: the follow-stream map (push-fed, authoritative) when
    enabled, then the TTL'd cache, then a lookup RPC."""
    if use_cache:
        # use_cache=False demands an authoritative RPC (delete()'s
        # all-404-means-gone logic, read()'s stale-location retry) —
        # the push map may trail a just-moved volume, so it is only
        # consulted on the cached path
        follower = _followers.get(master)
        if follower is not None:
            locs = follower.get_locations(vid)
            if locs is not None:
                return locs
        cached = _vid_cache.get(master, vid)
        if cached is not None:
            return cached
    r = master_json(master, "GET", f"/dir/lookup?volumeId={vid}",
                    timeout=_deadline.io_timeout(
                        30.0, site="master.lookup"))
    if "error" in r:
        raise LookupError(r["error"])
    _vid_cache.put(master, vid, r["locations"])
    return r["locations"]


_uds_probe: dict[str, "str | None"] = {}
_uds_lock = threading.Lock()


def _server_status(url: str) -> dict:
    """Cached /status probe per volume server (fast-path discovery:
    udsPath + readPlanePort + writePlanePort)."""
    with _uds_lock:
        if url in _uds_probe:
            return _uds_probe[url]
    try:
        t = _deadline.io_timeout(5.0, site="status.probe")
    except _deadline.DeadlineExceeded:
        # budget already spent: answer "no plane" for THIS request
        # without caching — a tight-budget first caller must not
        # permanently mark a healthy server plane-less
        return {}
    try:
        st, body, _ = http_bytes("GET", f"{url}/status", None, None, t)
        doc = json.loads(body) if st == 200 else {}
    except _deadline.DeadlineExceeded:
        return {}       # mid-call budget verdict: same no-cache rule
    except (OSError, ValueError, TypeError):
        # TypeError: tests monkeypatch http_bytes with narrow fakes —
        # discovery must degrade to "no plane", never break an upload
        d = _deadline.get()
        if d is not None and d.expired():
            # the probe lost to the BUDGET (t was budget-capped), not
            # to the server: serve "no plane" uncached so the next,
            # roomier caller re-probes
            return {}
        doc = {}
    with _uds_lock:
        _uds_probe[url] = doc
    return doc


def _invalidate_status(url: str) -> None:
    """Drop the cached /status probe (a plane connection refused means
    the server restarted — its plane ports moved)."""
    with _uds_lock:
        _uds_probe.pop(url, None)


# -- lean plane client ----------------------------------------------------
#
# The native planes speak strict minimal HTTP/1.1 (we control both
# ends), so the client side skips http.client entirely: a per-thread
# persistent socket per plane address, a hand-assembled request, a
# ~100-byte response parsed with two partitions.  http.client costs
# several hundred µs of pure Python per call — at native-plane rates
# that overhead IS the funnel (arXiv:1709.05365's host-side tax, client
# edition).

_plane_local = threading.local()


def _plane_request(addr: str, method: str, path: str,
                   body: bytes = b"", timeout: float = 10.0
                   ) -> "tuple[int, bytes]":
    """One request over the thread's persistent plane socket; retries
    once on a stale keep-alive socket (plane requests are idempotent:
    fixed-fid writes dedup server-side, reads are reads).  Raises
    OSError when the plane is unreachable.

    `timeout` bounds the WHOLE call, not each socket operation: the
    recv loops re-derive their per-op timeout from what is left, so a
    wedged (or byte-trickling) C++ plane parks this client for at most
    the budget — when the request carries a deadline the effective
    bound shrinks to the remaining budget (the caller derives
    `timeout` via util/deadline.io_timeout)."""
    import socket as _socket
    socks = getattr(_plane_local, "socks", None)
    if socks is None:
        socks = _plane_local.socks = {}
    # stitch headers (ISSUE 18): the plane records the request id into
    # its flight ring and forwards it on the upstream plane hop, so a
    # plane-served request traces under the same id as its Python hops
    extra = ""
    rid = request_id.get_request_id()
    if rid:
        extra += f"{request_id.HEADER}: {rid}\r\n"
    d = _deadline.get()
    if d is not None:
        remaining_ms = int(d.remaining() * 1e3)
        if remaining_ms > 0:
            extra += f"{_deadline.HEADER}: {remaining_ms}\r\n"
    req = (f"{method} {path} HTTP/1.1\r\n"
           f"Host: {addr}\r\n{extra}"
           f"Content-Length: {len(body)}\r\n\r\n").encode()
    end = time.monotonic() + timeout

    def _left() -> float:
        # a spent REQUEST budget must surface as the budget verdict it
        # is (the caller re-raises it), never as the socket.timeout
        # "plane wedged" verdict below — misreading it would invalidate
        # a healthy server's status cache and tear down its socket
        d = _deadline.get()
        if d is not None and d.expired():
            _deadline.note_exceeded("plane.io")
            raise _deadline.DeadlineExceeded("plane.io")
        left = end - time.monotonic()
        if left <= 0:
            raise _socket.timeout(
                f"plane {addr}: call budget ({timeout:.2f}s) spent")
        return left

    for attempt in (0, 1):
        sock = socks.get(addr)
        reused = sock is not None
        if sock is None:
            host, _, port = addr.rpartition(":")
            sock = _socket.create_connection((host, int(port)),
                                             timeout=_left())
            sock.setsockopt(_socket.IPPROTO_TCP,
                            _socket.TCP_NODELAY, 1)
            socks[addr] = sock
        try:
            sock.settimeout(_left())
            sock.sendall(req + body if len(body) < (256 << 10)
                         else req)
            if len(body) >= (256 << 10):
                sock.sendall(body)
            buf = b""
            while b"\r\n\r\n" not in buf:
                sock.settimeout(_left())
                chunk = sock.recv(65536)
                if not chunk:
                    raise OSError("plane socket closed mid-response")
                buf += chunk
                if len(buf) > (64 << 10):
                    raise OSError("oversized plane response header")
            head, _, rest = buf.partition(b"\r\n\r\n")
            status = int(head.split(b" ", 2)[1])
            clen = 0
            for line in head.split(b"\r\n")[1:]:
                k, _, v = line.partition(b":")
                if k.strip().lower() == b"content-length":
                    clen = int(v.strip())
                    break
            while len(rest) < clen:
                sock.settimeout(_left())
                chunk = sock.recv(65536)
                if not chunk:
                    raise OSError("plane socket closed mid-body")
                rest += chunk
            return status, rest[:clen]
        except _deadline.DeadlineExceeded:
            # _left()'s budget verdict mid-call: an in-flight response
            # may be abandoned on the wire, so the keep-alive socket
            # must still be dropped (it would poison the next request)
            # — but the stale-socket re-dial below must NOT run: a
            # fresh dial cannot conjure budget, and the retry would
            # count a second exceed for one spent budget
            try:
                sock.close()
            except OSError:
                pass
            socks.pop(addr, None)
            raise
        except OSError:
            try:
                sock.close()
            except OSError:
                pass
            socks.pop(addr, None)
            if reused and attempt == 0:
                continue     # stale keep-alive: one fresh re-dial
            raise
    raise OSError("unreachable")  # pragma: no cover


def _write_plane_addr_for(url: str) -> "str | None":
    port = _server_status(url).get("writePlanePort") or 0
    if not port:
        return None
    host = url.split("://")[-1].rsplit(":", 1)[0]
    return f"{host}:{port}"


def _plane_vid_misses() -> dict:
    m = getattr(_plane_local, "vid_misses", None)
    if m is None:
        m = _plane_local.vid_misses = {}
    return m


def _write_via_write_plane(url: str, fid: str, data: bytes
                           ) -> "dict | None":
    """Native write-plane fast path; None falls back to the pooled
    Python-port POST.  A 404 (unregistered/replicated volume, seen
    key) is remembered per-vid briefly so steady traffic to a volume
    the plane will never own doesn't pay a probe round-trip per
    write."""
    addr = _write_plane_addr_for(url)
    if addr is None:
        return None
    vid = fid.partition(",")[0]
    misses = _plane_vid_misses()
    neg_until = misses.get((addr, vid))
    if neg_until is not None:
        if time.monotonic() < neg_until:
            return None
        del misses[(addr, vid)]
    # derive the plane call's budget OUTSIDE the try: an already-spent
    # deadline must fail the write fast, not read as "plane down" (the
    # OSError below both invalidates the status probe and falls back
    # to the pooled POST — wrong on both counts for a budget verdict)
    t = _deadline.io_timeout(10.0, site="plane.write")
    try:
        status, body = _plane_request(addr, "POST", f"/{fid}", data,
                                      timeout=t)
    except _deadline.DeadlineExceeded:
        raise                     # budget verdict, not a plane verdict
    except OSError:
        # a recv that parked until the BUDGET ran out raises plain
        # socket.timeout (t was capped by the remaining budget at
        # derivation) — still the budget's verdict, and a healthy
        # server must not be marked plane-less for the client's clock
        _deadline.reraise_if_expired("plane.write")
        _invalidate_status(url)   # restarted server: re-probe ports
        return None
    if status == 201:
        try:
            return json.loads(body)
        except ValueError:
            return None
    misses[(addr, vid)] = time.monotonic() + 2.0
    return None


def _uds_path_for(url: str) -> "str | None":
    """The volume server's UDS read socket when it is reachable from
    THIS host (same machine / shared filesystem namespace); cached per
    server.  None = use HTTP."""
    import os
    p = _server_status(url).get("udsPath") or ""
    return p if p and os.path.exists(p) else None


def _read_plane_addr_for(url: str) -> "str | None":
    """host:port of the server's native C++ read plane
    (server/read_plane.py), or None."""
    port = _server_status(url).get("readPlanePort") or 0
    if not port:
        return None
    host = url.split("://")[-1].rsplit(":", 1)[0]
    return f"{host}:{port}"


def _read_via_read_plane(locs, fid: str) -> "bytes | None":
    """Native read-plane fast path (TCP, cross-host): plain needles
    come back 200 from the C++ plane; anything it doesn't serve
    (unregistered, compressed, named, ttl'd) 404s and the caller falls
    through to the main HTTP port."""
    for loc in locs:
        addr = _read_plane_addr_for(loc["url"])
        if not addr:
            continue
        # budget derived outside the try (see _write_via_write_plane)
        t = _deadline.io_timeout(10.0, site="plane.read")
        try:
            # lean persistent-socket client (same funnel as the write
            # plane): the C++ plane speaks strict minimal HTTP, so the
            # http.client machinery is pure overhead here
            status, body = _plane_request(addr, "GET", f"/{fid}",
                                          timeout=t)
        except _deadline.DeadlineExceeded:
            raise                 # budget verdict, not a plane verdict
        except OSError:
            # see _write_via_write_plane: a budget-bounded park is the
            # budget's verdict, never "plane down"
            _deadline.reraise_if_expired("plane.read")
            _invalidate_status(loc["url"])
            continue
        if status == 200:
            return body
    return None


def _uds_read_one(loc, vid: int, key: int, cookie: int
                  ) -> "tuple[bytes | None, bool]":
    """One location's same-host UDS zero-copy attempt.  Returns
    (data, stop): data on success; stop=True when the needle's
    semantics live server-side (compressed/chunked/ttl'd — HTTP must
    serve it, and every replica would answer the same); (None, False)
    = not served here (no local socket / transport error / cookie
    mismatch) — the caller tries its next plane or location."""
    from .server.uds_reader import uds_read_needle
    p = _uds_path_for(loc["url"])
    if not p:
        return None, False
    try:
        n = uds_read_needle(p, vid, key)
    except (OSError, LookupError, ValueError):
        return None, False  # fall to HTTP (which also retries)
    if n.cookie != cookie:
        # a per-replica mismatch is not terminal — the HTTP path
        # 404s one replica and tries the next; do the same
        return None, False
    if n.is_compressed() or n.is_chunked_manifest() or n.has_ttl():
        return None, True
    return bytes(n.data), False


def _read_via_uds(locs, vid: int, key: int, cookie: int
                  ) -> "bytes | None":
    """Same-host zero-copy fast path (server/uds_reader.py, the RDMA
    sidecar analog): fetch the raw needle record over the unix socket
    and validate client-side.  None = not applicable here (no local
    socket / compressed / chunked / ttl'd needle — HTTP handles
    those)."""
    for loc in locs:
        data, stop = _uds_read_one(loc, vid, key, cookie)
        if data is not None:
            return data
        if stop:
            return None  # semantics live server-side: use HTTP
    return None


def _maybe_hedged_read(locs, fid: str, headers,
                       plane_ok: bool = False, vid: int = -1,
                       key: int = -1, cookie: int = -1
                       ) -> "bytes | None":
    """Hedge-capable fetch of `fid` across the first two locations
    (util/hedge; first-wins).  Only deadline-carrying requests enter:
    the hedge plane exists to meet budgets, and the un-deadlined path
    (bench arms, bulk tools) must keep the zero-handoff sequential
    funnel.  Each leg covers its location's WHOLE funnel — when
    `plane_ok` (the whole-needle unauthenticated shape the native
    planes serve): same-host UDS zero-copy first, the C++ read plane
    second, then the HTTP port — so deadline-carrying reads keep the
    fast paths AND one wedged replica costs ~p95 whichever plane it
    is wedged on.  None = not applicable or no success — the caller's
    sequential loops proceed unchanged."""
    from .util import hedge as _hedge
    if not _hedge.reads_enabled():
        return None
    d = _deadline.get()
    if d is None:
        return None
    threshold = _hedge.read_threshold()
    if threshold is None:
        return None                       # tracker cold: no baseline
    if d.remaining() <= threshold + _deadline.MIN_TIMEOUT:
        return None                       # no room for a second leg

    def fetch(loc):
        if plane_ok:
            if key >= 0:
                data, _stop = _uds_read_one(loc, vid, key, cookie)
                if data is not None:
                    return 200, data
            addr = _read_plane_addr_for(loc["url"])
            if addr:
                try:
                    status, pbody = _plane_request(
                        addr, "GET", f"/{fid}",
                        timeout=_deadline.io_timeout(
                            10.0, site="plane.read"))
                    if status == 200:
                        return 200, pbody
                except _deadline.DeadlineExceeded:
                    raise
                except OSError:
                    # raced hedge leg: fall through to the HTTP port
                    # WITHOUT invalidating the status cache — the
                    # sequential funnel owns that verdict
                    pass
        status, body, _ = http_bytes(
            "GET", f"{loc['url']}/{fid}", None, headers,
            timeout=_deadline.io_timeout(60.0, site="volume.read"))
        return status, body

    val, _hedged = _hedge.hedged_fetch(
        lambda: fetch(locs[0]), lambda: fetch(locs[1]), threshold,
        lambda sv: sv[0] in (200, 206), kind="read")
    return val[1] if val is not None else None


def read(master: str, fid: str, offset: int = 0,
         size: int | None = None) -> bytes:
    """Full or ranged needle read (ranged avoids whole-chunk transfers
    on the filer's chunk-view path)."""
    vid = int(fid.split(",", 1)[0])
    locs = lookup(master, vid)
    plane_shape = offset == 0 and size is None and \
        not security.current().volume_read_key
    headers = {}
    if offset or size is not None:
        end = f"{offset + size - 1}" if size is not None else ""
        headers["Range"] = f"bytes={offset}-{end}"
    # read gating (jwt.go readSigningKey): sign locally when configured
    read_auth = security.current().read_jwt(fid)
    if read_auth:
        headers["Authorization"] = f"Bearer {read_auth}"
    key = cookie = -1
    if plane_shape:
        try:
            part = fid.split(",", 1)[1]
            key, cookie = int(part[:-8], 16), int(part[-8:], 16)
        except (IndexError, ValueError):
            key = cookie = -1
    if len(locs) >= 2:
        # hedged replica read (util/hedge), BEFORE the sequential
        # native-plane funnel: when this request carries a deadline
        # and the primary replica exceeds the p95-tracked threshold,
        # the read is re-issued to a second location and the first
        # success wins — one slow/wedged replica costs ~p95, not the
        # whole budget (each hedge leg runs its location's full
        # UDS -> C++ plane -> HTTP port ladder, so the fast paths are
        # kept AND covered).  Returns None (unarmed / tokenless /
        # tracker cold / no success) -> the classic sequential funnel
        # below still owns the request.
        body = _maybe_hedged_read(locs, fid, headers,
                                  plane_ok=plane_shape, vid=vid,
                                  key=key, cookie=cookie)
        if body is not None:
            return body
    if plane_shape and key >= 0:
        # whole-needle, unauthenticated-read deployments: native C++
        # read plane first (works cross-host, serves via kernel
        # sendfile); UDS second (same-host only).  Successes feed the
        # hedge threshold tracker — on plane-serving deployments these
        # ARE the primary reads, and a cold tracker would never arm
        # the hedge for them.
        from . import profiling
        from .util import hedge as _hedge
        t0 = time.monotonic()
        data = _read_via_read_plane(locs, fid)
        if data is not None:
            _hedge.note_primary(time.monotonic() - t0)
            profiling.flight_note("nativePlane", "read-cpp")
            return data
        data = _read_via_uds(locs, vid, key, cookie)
        if data is not None:
            _hedge.note_primary(time.monotonic() - t0)
            profiling.flight_note("nativePlane", "read-uds")
            return data
    last_err = None
    for attempt in range(2):
        for loc in locs:
            t0 = time.monotonic()
            try:
                status, body, _ = http_bytes(
                    "GET", f"{loc['url']}/{fid}", None, headers,
                    timeout=_deadline.io_timeout(60.0,
                                                 site="volume.read"))
            except _deadline.DeadlineExceeded:
                raise
            except OSError as e:
                last_err = f"{loc['url']} -> {e}"
                continue
            if status in (200, 206):
                from .util import hedge as _hedge
                _hedge.note_primary(time.monotonic() - t0)
                return body
            last_err = f"{loc['url']} -> {status}"
        # stale cache? refresh once and retry (vidmap invalidation)
        _vid_cache.invalidate(master, vid)
        if attempt == 0:
            try:
                locs = lookup(master, vid, use_cache=False)
            except LookupError as e:
                raise RuntimeError(f"read {fid}: {e}")
    raise RuntimeError(f"read {fid}: {last_err}")


def delete(master: str, fid: str) -> None:
    """operation/delete_content.go: delete at one replica location — the
    volume server fans the delete out to siblings (store_replicate.go:142
    ReplicatedDelete / store_ec_delete.go:38), and fans out even when its
    own copy is already gone.  A 2xx from any location therefore means
    every holder was told.  A location that 404s without hosting the
    volume can't fan out, so the loop continues past 404s; only when
    EVERY location answered 404 is the needle treated as already gone.
    Anything else raises — a lost delete is never silent."""
    vid = int(fid.split(",", 1)[0])
    last = "no locations"
    # fresh lookup: the all-404-means-gone conclusion below is unsound
    # over a stale TTL'd cache (moved volumes would 404 everywhere)
    locs = lookup(master, vid, use_cache=False)
    answered = 0
    headers = security.current().write_headers(fid)
    for loc in locs:
        try:
            status, body, _ = http_bytes(
                "DELETE", f"{loc['url']}/{fid}", headers=headers,
                timeout=_deadline.io_timeout(60.0,
                                             site="volume.delete"))
        except _deadline.DeadlineExceeded:
            # the budget verdict must surface as itself (the fronts'
            # 504 translation, retry's no-re-issue rule), never fold
            # into the generic "delete failed" RuntimeError below
            raise
        except OSError as e:
            last = f"{loc['url']}: {e}"
            continue
        if status < 300:
            return
        if status == 404:
            answered += 1
            continue
        last = f"{loc['url']} -> {status}: {body[:200]!r}"
    if locs and answered == len(locs):
        return  # gone (or never existed) everywhere
    raise RuntimeError(f"delete {fid}: {last}")
