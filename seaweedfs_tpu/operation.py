"""Client SDK verbs (weed/operation/): assign, upload, submit, lookup,
delete — the operations every gateway and tool builds on.  Lookups go
through a TTL'd vid->locations cache (weed/wdclient/vid_map.go)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from .server.httpd import http_bytes, http_json


class VidCache:
    """wdclient/vid_map.go: volume-id -> locations with TTL + explicit
    invalidation on read failure."""

    TTL = 10.0

    def __init__(self):
        self._m: dict[tuple[str, int], tuple[float, list[dict]]] = {}
        self._lock = threading.Lock()

    def get(self, master: str, vid: int) -> "list[dict] | None":
        with self._lock:
            hit = self._m.get((master, vid))
            if hit and time.time() - hit[0] < self.TTL:
                return hit[1]
        return None

    def put(self, master: str, vid: int, locs: list[dict]) -> None:
        with self._lock:
            self._m[(master, vid)] = (time.time(), locs)

    def invalidate(self, master: str, vid: int) -> None:
        with self._lock:
            self._m.pop((master, vid), None)


_vid_cache = VidCache()


@dataclass
class Assignment:
    fid: str
    url: str
    public_url: str
    count: int


def assign(master: str, count: int = 1, collection: str = "",
           replication: str = "", ttl: str = "") -> Assignment:
    """operation/assign_file_id.go Assign."""
    qs = f"count={count}"
    if collection:
        qs += f"&collection={collection}"
    if replication:
        qs += f"&replication={replication}"
    if ttl:
        qs += f"&ttl={ttl}"
    r = http_json("GET", f"{master}/dir/assign?{qs}")
    if "error" in r:
        raise RuntimeError(f"assign: {r['error']}")
    return Assignment(r["fid"], r["url"], r.get("publicUrl", r["url"]),
                      r.get("count", count))


def upload(url: str, fid: str, data: bytes, name: str = "",
           mime: str = "") -> dict:
    """operation/upload_content.go Upload."""
    qs = f"?name={name}" if name else ""
    headers = {"Content-Type": mime} if mime else {}
    status, body, _ = http_bytes("POST", f"{url}/{fid}{qs}", data, headers)
    if status >= 300:
        raise RuntimeError(f"upload {fid} -> {status}: {body[:200]!r}")
    import json
    return json.loads(body)


def submit(master: str, data: bytes, name: str = "", mime: str = "",
           collection: str = "", replication: str = "",
           ttl: str = "") -> str:
    """operation/submit.go: assign + upload; returns the fid."""
    a = assign(master, collection=collection, replication=replication,
               ttl=ttl)
    upload(a.url, a.fid, data, name=name, mime=mime)
    return a.fid


def lookup(master: str, vid: int, use_cache: bool = True) -> list[dict]:
    """operation/lookup.go Lookup -> [{url, publicUrl}]."""
    if use_cache:
        cached = _vid_cache.get(master, vid)
        if cached is not None:
            return cached
    r = http_json("GET", f"{master}/dir/lookup?volumeId={vid}")
    if "error" in r:
        raise LookupError(r["error"])
    _vid_cache.put(master, vid, r["locations"])
    return r["locations"]


def read(master: str, fid: str, offset: int = 0,
         size: int | None = None) -> bytes:
    """Full or ranged needle read (ranged avoids whole-chunk transfers
    on the filer's chunk-view path)."""
    vid = int(fid.split(",", 1)[0])
    locs = lookup(master, vid)
    headers = {}
    if offset or size is not None:
        end = f"{offset + size - 1}" if size is not None else ""
        headers["Range"] = f"bytes={offset}-{end}"
    last_err = None
    for attempt in range(2):
        for loc in locs:
            try:
                status, body, _ = http_bytes(
                    "GET", f"{loc['url']}/{fid}", None, headers)
            except OSError as e:
                last_err = f"{loc['url']} -> {e}"
                continue
            if status in (200, 206):
                return body
            last_err = f"{loc['url']} -> {status}"
        # stale cache? refresh once and retry (vidmap invalidation)
        _vid_cache.invalidate(master, vid)
        if attempt == 0:
            try:
                locs = lookup(master, vid, use_cache=False)
            except LookupError as e:
                raise RuntimeError(f"read {fid}: {e}")
    raise RuntimeError(f"read {fid}: {last_err}")


def delete(master: str, fid: str) -> None:
    vid = int(fid.split(",", 1)[0])
    for loc in lookup(master, vid):
        http_bytes("DELETE", f"{loc['url']}/{fid}")
