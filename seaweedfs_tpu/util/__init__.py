"""Foundation utilities (weed/util/*)."""
