"""Group commit: one durability barrier amortized across concurrent
writers (the classic DB write-ahead-log trick, and the exact host-side
overhead arXiv:1709.05365 measures dominating online-EC stores).

The write path pays three per-request durability barriers — the filer
store's transaction commit, the metadata log's segment flush, and the
volume's .dat+.idx flush (plus `os.fsync` on the -fsync tier).  Each
is correct but serial: N concurrent writers pay N barriers for bytes
that one barrier would have covered.  `CommitBarrier` turns each site
into leader/follower group commit:

* a writer finishes its (cheap, buffered) mutation, then calls
  `commit()`;
* the first writer to arrive becomes the LEADER of the open batch;
  later arrivals join the batch as followers and block;
* the leader waits for the previous batch's flush to finish (batches
  are strictly serialized — this wait IS the gather window: while
  batch N flushes, batch N+1's members accumulate, so batch size
  self-clocks to barrier latency), closes its batch, runs the flush
  callback ONCE, and wakes every member;
* every member returns only after a flush that started after its
  mutation was buffered — ack semantics are byte-for-byte the same as
  flush-per-write, the barrier is just shared.

A single in-flight writer passes straight through: it becomes leader
of a batch of one and flushes immediately, so p50 at concurrency=1 is
the seed's p50 (no gather sleep on an idle site).  An optional linger
(`SEAWEEDFS_TPU_GROUP_COMMIT_MAX_WAIT_US`, default 0) lets a leader
that already has company hold the batch open briefly for stragglers —
useful only when the barrier is expensive relative to arrival spacing
(the -fsync tier); the self-clocking serialization needs no linger.

A flush failure (ENOSPC, a closed handle) propagates to EVERY member
of the failed batch — no writer is acked by a barrier that did not
reach the kernel.

Knobs (env):
  SEAWEEDFS_TPU_GROUP_COMMIT              "0" disables the layer:
                                          commit() == flush() (seed
                                          per-write behavior)
  SEAWEEDFS_TPU_GROUP_COMMIT_MAX_WAIT_US  leader linger for a batch
                                          that already has >= 2
                                          members (0)
  SEAWEEDFS_TPU_GROUP_COMMIT_MAX_BATCH    linger stops once the batch
                                          reaches this size (64)

Observability: every flushed batch lands
`group_commit_batch_size{site}` (histogram — mean batch = sum/count)
and every writer's barrier wait lands
`group_commit_wait_seconds{site}` in stats.PROCESS, rendered by
`cluster.top` and read by `bench.py write_path`.
"""

from __future__ import annotations

import os
import threading
import time


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def enabled() -> bool:
    """SEAWEEDFS_TPU_GROUP_COMMIT=0 reverts every site to per-write
    flushes (the bench A/B's off arm)."""
    return os.environ.get("SEAWEEDFS_TPU_GROUP_COMMIT", "1") != "0"


def max_wait_s() -> float:
    """Leader linger window in seconds (from the _MAX_WAIT_US knob)."""
    return max(0, _env_int(
        "SEAWEEDFS_TPU_GROUP_COMMIT_MAX_WAIT_US", 0)) / 1e6


def max_batch() -> int:
    return max(1, _env_int("SEAWEEDFS_TPU_GROUP_COMMIT_MAX_BATCH", 64))


def _metrics():
    from .. import stats
    return stats.PROCESS


class _Batch:
    """One commit window: members joined, a leader claimed, one flush
    verdict shared by all."""

    __slots__ = ("members", "claimed", "done", "error")

    def __init__(self):
        self.members = 0
        self.claimed = False
        self.done = threading.Event()
        self.error: "BaseException | None" = None


class CommitBarrier:
    """Leader/follower group commit around one flush callable.

    `flush` must make EVERYTHING buffered at its call time durable
    (to the OS page cache, or the platter on an fsync tier) — e.g.
    `file.flush()`, `conn.commit()`.  It is only ever called by one
    thread at a time (batches are serialized on an internal lock), and
    it may take whatever site lock it needs — the designated helper is
    where flush-under-lock is allowed (SWFS012)."""

    def __init__(self, flush, site: str = ""):
        self._flush = flush
        self.site = site
        self._lock = threading.Lock()       # guards _batch
        self._flush_lock = threading.Lock()  # serializes batch flushes
        self._batch = _Batch()
        # cumulative counters for cheap snapshots (tests, /debug)
        self.flushes = 0
        self.committed = 0
        # histogram observers, resolved lazily on first use (stats
        # imports util.* — resolving here would cycle at import time)
        self._obs_wait = None
        self._obs_flush = None

    # -- the one entry point ----------------------------------------------

    def commit(self) -> int:
        """Block until a flush that STARTED after this call covers the
        caller's buffered work.  Returns the batch size when this
        caller led the flush, 0 when it rode another leader's barrier.
        Raises the flush's exception (shared by the whole batch)."""
        if not enabled():
            # the kill switch restores per-write barriers, but the
            # flush callable's single-caller contract still holds —
            # sites like MetaLog._group_commit_drain mutate handle
            # state that concurrent unserialized flushes would race
            with self._flush_lock:
                self._flush()
            return 1
        t0 = time.perf_counter()
        with self._lock:
            batch = self._batch
            batch.members += 1
            lead = not batch.claimed
            if lead:
                batch.claimed = True
        if not lead:
            batch.done.wait()
            self._note_wait(time.perf_counter() - t0)
            if batch.error is not None:
                raise batch.error
            return 0

        # leader: wait out the previous batch's flush — members pile
        # into this batch meanwhile (the self-clocking gather window)
        with self._flush_lock:
            linger = max_wait_s()
            if linger > 0:
                self._linger(batch, linger)
            with self._lock:
                # close the window: arrivals from here on buffer ahead
                # of our flush (still covered — flush-after-buffer is
                # the only ordering that matters) but wait for the
                # NEXT barrier, whose flush also starts after their
                # mutation.  Durability is never early-acked.
                self._batch = _Batch()
                n = batch.members
            try:
                self._flush()
            except BaseException as e:
                batch.error = e
                raise
            finally:
                batch.done.set()
                self._note_flush(n, time.perf_counter() - t0)
        return n

    def sync(self) -> None:
        """Force a barrier now (readers that must see persisted state:
        metalog disk replay, close paths).  Equivalent to an empty
        member's commit()."""
        self.commit()

    # -- linger (optional gather beyond the serialization window) ---------

    def _linger(self, batch: _Batch, seconds: float) -> None:
        """Hold a batch that already has company open for stragglers.
        A batch of one never lingers — single-writer p50 must not pay
        a gather sleep for followers that are not coming."""
        deadline = time.perf_counter() + seconds
        cap = max_batch()
        while True:
            with self._lock:
                n = batch.members
            if n <= 1 or n >= cap:
                return
            left = deadline - time.perf_counter()
            if left <= 0:
                return
            time.sleep(min(left, 0.0002))

    # -- telemetry --------------------------------------------------------

    def _note_wait(self, seconds: float) -> None:
        # observers resolved once per site (stats.Metrics.observer,
        # ROADMAP 1d): every barrier member pays this on its ack path
        obs = self._obs_wait
        if obs is None:
            from ..stats import GROUP_COMMIT_WAIT_BUCKETS
            obs = self._obs_wait = _metrics().observer(
                "group_commit_wait_seconds",
                buckets=GROUP_COMMIT_WAIT_BUCKETS,
                help_text="time a writer waited on the shared "
                          "durability barrier", site=self.site or "?")
        obs(seconds)

    def _note_flush(self, n: int, leader_seconds: float) -> None:
        self.flushes += 1
        self.committed += n
        obs = self._obs_flush
        if obs is None:
            from ..stats import (GROUP_COMMIT_BATCH_BUCKETS,
                                 GROUP_COMMIT_WAIT_BUCKETS)
            m = _metrics()
            obs = self._obs_flush = (
                m.observer(
                    "group_commit_batch_size",
                    buckets=GROUP_COMMIT_BATCH_BUCKETS,
                    help_text="writers covered per shared durability "
                              "barrier (mean batch = sum/count)",
                    site=self.site or "?"),
                m.observer(
                    "group_commit_wait_seconds",
                    buckets=GROUP_COMMIT_WAIT_BUCKETS,
                    site=self.site or "?"))
        obs[0](float(n))
        obs[1](leader_seconds)
