"""Probabilistic skip list (reference: weed/util/skiplist — the
ordered map under the reference's name-list directory listings).

Ordered key->value map with O(log n) insert/delete/search and
in-order range scans — the operations the LSM memtable and large
directory listings need.  Deterministic tower heights derive from
the key's hash rather than a RNG: identical trees across restarts
make behavior reproducible under test, and jax-style determinism is
the house rule even off-device.
"""

from __future__ import annotations

import zlib

MAX_LEVEL = 16


class _Node:
    __slots__ = ("key", "value", "forward")

    def __init__(self, key, value, level: int):
        self.key = key
        self.value = value
        self.forward: list = [None] * level


class SkipList:
    def __init__(self):
        self._head = _Node(None, None, MAX_LEVEL)
        self._level = 1
        self._len = 0

    @staticmethod
    def _height_for(key) -> int:
        # deterministic 1/2-decay tower height from a PROCESS-STABLE
        # key digest.  The builtin hash() is salted per process for
        # str/bytes (PYTHONHASHSEED), which silently falsified the
        # documented cross-restart determinism for exactly the key
        # type every caller uses (entry paths) — crc32 is unsalted,
        # cheap, and well-mixed enough after the avalanche below
        # (advisor round-5 leftover, fixed in ISSUE 13).
        if isinstance(key, str):
            h = zlib.crc32(key.encode("utf-8", "surrogatepass"))
        elif isinstance(key, (bytes, bytearray)):
            h = zlib.crc32(key)
        else:
            h = hash(key) & 0xFFFFFFFF
        h ^= h >> 16
        h = (h * 0x45D9F3B) & 0xFFFFFFFF
        h ^= h >> 16
        level = 1
        while (h & 1) and level < MAX_LEVEL:
            level += 1
            h >>= 1
        return level

    def __len__(self) -> int:
        return self._len

    def __contains__(self, key) -> bool:
        return self.get(key, _MISSING) is not _MISSING

    def _find_predecessors(self, key):
        update = [self._head] * MAX_LEVEL
        x = self._head
        for i in range(self._level - 1, -1, -1):
            while x.forward[i] is not None and x.forward[i].key < key:
                x = x.forward[i]
            update[i] = x
        return update, x.forward[0]

    def insert(self, key, value) -> None:
        update, nxt = self._find_predecessors(key)
        if nxt is not None and nxt.key == key:
            nxt.value = value
            return
        level = self._height_for(key)
        if level > self._level:
            self._level = level
        node = _Node(key, value, level)
        for i in range(level):
            node.forward[i] = update[i].forward[i]
            update[i].forward[i] = node
        self._len += 1

    def delete(self, key) -> bool:
        update, nxt = self._find_predecessors(key)
        if nxt is None or nxt.key != key:
            return False
        for i in range(len(nxt.forward)):
            if update[i].forward[i] is nxt:
                update[i].forward[i] = nxt.forward[i]
        while self._level > 1 and \
                self._head.forward[self._level - 1] is None:
            self._level -= 1
        self._len -= 1
        return True

    def get(self, key, default=None):
        x = self._head
        for i in range(self._level - 1, -1, -1):
            while x.forward[i] is not None and x.forward[i].key < key:
                x = x.forward[i]
        x = x.forward[0]
        if x is not None and x.key == key:
            return x.value
        return default

    def items(self, start=None, end=None, include_start: bool = True):
        """In-order (key, value) scan over [start, end) — the
        range-read shape directory listings page with."""
        x = self._head
        if start is not None:
            for i in range(self._level - 1, -1, -1):
                while x.forward[i] is not None and \
                        x.forward[i].key < start:
                    x = x.forward[i]
        x = x.forward[0]
        while x is not None:
            if end is not None and x.key >= end:
                return
            if start is None or include_start or x.key != start:
                yield x.key, x.value
            x = x.forward[0]

    def keys(self):
        for k, _v in self.items():
            yield k

    def first(self):
        n = self._head.forward[0]
        return (n.key, n.value) if n is not None else None


_MISSING = object()
