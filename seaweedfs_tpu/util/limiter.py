"""Concurrency-bounded execution + bounded outbound HTTP
(reference: weed/util/limited_executor.go and the
util_http/client bounded transport).

`BoundedExecutor` caps in-flight tasks with a semaphore on SUBMIT
(not just worker count): a producer fanning out thousands of chunk
uploads blocks once the bound is hit instead of queueing unbounded
work — the backpressure shape limited_executor.go provides.

`bounded_parallel(fn, items, limit)` is the common map-with-bound:
runs fn over items with at most `limit` in flight, preserves order,
re-raises the first failure after letting started work finish.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor


class BoundedExecutor:
    def __init__(self, limit: int = 8):
        self.limit = max(1, int(limit))
        self._pool = ThreadPoolExecutor(max_workers=self.limit)
        self._slots = threading.Semaphore(self.limit)

    def submit(self, fn, *args, **kwargs):
        """Blocks while `limit` tasks are in flight (backpressure on
        the producer, limited_executor.go semantics)."""
        self._slots.acquire()

        def run():
            try:
                return fn(*args, **kwargs)
            finally:
                self._slots.release()
        return self._pool.submit(run)

    def shutdown(self, wait: bool = True) -> None:
        self._pool.shutdown(wait=wait)


def bounded_parallel(fn, items, limit: int = 8) -> list:
    """Map fn over items with at most `limit` concurrent calls;
    results in input order.  Sequential fast path for 0/1 items (no
    thread overhead on the common single-chunk write)."""
    items = list(items)
    if len(items) <= 1:
        return [fn(x) for x in items]
    with ThreadPoolExecutor(max_workers=min(limit,
                                            len(items))) as pool:
        return list(pool.map(fn, items))
