"""Concurrency-bounded execution + bounded outbound HTTP
(reference: weed/util/limited_executor.go and the
util_http/client bounded transport).

`BoundedExecutor` caps in-flight tasks with a semaphore on SUBMIT
(not just worker count): a producer fanning out thousands of chunk
uploads blocks once the bound is hit instead of queueing unbounded
work — the backpressure shape limited_executor.go provides.

`bounded_parallel(fn, items, limit)` is the common map-with-bound:
runs fn over items with at most `limit` in flight, preserves order,
re-raises the first failure after letting started work finish.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor


class BoundedExecutor:
    def __init__(self, limit: int = 8):
        self.limit = max(1, int(limit))
        self._pool = ThreadPoolExecutor(max_workers=self.limit)
        self._slots = threading.Semaphore(self.limit)

    def submit(self, fn, *args, **kwargs):
        """Blocks while `limit` tasks are in flight (backpressure on
        the producer, limited_executor.go semantics)."""
        self._slots.acquire()

        def run():
            try:
                return fn(*args, **kwargs)
            finally:
                self._slots.release()
        return self._pool.submit(run)

    def shutdown(self, wait: bool = True) -> None:
        self._pool.shutdown(wait=wait)


# process-wide persistent worker pool for short-lived fan-outs (the
# filer's chunk-upload funnel).  A fresh ThreadPoolExecutor per call
# spawns threads that die with the call — and with them every
# thread-local keep-alive socket the pooled HTTP client holds, so a
# multi-chunk upload re-paid the TCP setup tax on every chunk of every
# request.  Long-lived workers keep their connection pools warm
# end-to-end (httpd._thread_pools is per-thread by design).
_SHARED_WORKERS = 16
_shared_pool: "ThreadPoolExecutor | None" = None
_shared_lock = threading.Lock()


def shared_pool() -> ThreadPoolExecutor:
    global _shared_pool
    with _shared_lock:
        if _shared_pool is None:
            _shared_pool = ThreadPoolExecutor(
                max_workers=_SHARED_WORKERS,
                thread_name_prefix="weed-funnel")
        return _shared_pool


def bounded_parallel(fn, items, limit: int = 8,
                     persistent: bool = False) -> list:
    """Map fn over items with at most `limit` concurrent calls;
    results in input order.  Sequential fast path for 0/1 items (no
    thread overhead on the common single-chunk write).

    persistent=True runs on the process-wide shared_pool() — workers
    (and their per-thread keep-alive connection pools) outlive the
    call — with a semaphore providing this call's `limit` so one
    caller cannot monopolize the shared workers."""
    items = list(items)
    if len(items) <= 1:
        return [fn(x) for x in items]
    if persistent:
        # bound SUBMISSION, not execution: acquiring inside the worker
        # would park pool threads on the semaphore and let one large
        # fan-out occupy the whole shared pool while doing `limit`
        # items of work — blocked capacity must wait in the caller
        slots = threading.Semaphore(max(1, limit))
        pool = shared_pool()
        futures = []

        def run(x):
            try:
                return fn(x)
            finally:
                slots.release()

        for x in items:
            slots.acquire()
            futures.append(pool.submit(run, x))
        return [f.result() for f in futures]
    with ThreadPoolExecutor(max_workers=min(limit,
                                            len(items))) as pool:
        return list(pool.map(fn, items))
