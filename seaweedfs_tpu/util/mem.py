"""Slab buffer pool (reference: weed/util/mem/slot_pool.go —
power-of-two size-classed free lists so the data plane recycles big
byte buffers instead of churning the allocator).

Python strings/bytes are immutable, so the pooled unit is a
`bytearray` (the only mutable buffer the stdlib I/O stack accepts).
`allocate(n)` returns a bytearray of capacity >= n from the smallest
fitting slab; `free(buf)` returns it.  Each slab's free list is
bounded, so a burst can't pin memory forever (the reference bounds
pools the same way via sync.Pool's GC behavior).
"""

from __future__ import annotations

import threading

_MIN_SHIFT = 10            # 1KB
_MAX_SHIFT = 27            # 128MB — mirrors slot_pool.go's ceiling
_PER_SLAB = 8              # bounded free list per size class

_lock = threading.Lock()
_slabs: dict[int, list[bytearray]] = {}
_stats = {"allocations": 0, "reuses": 0, "frees": 0, "dropped": 0}


def _shift_for(size: int) -> int:
    shift = _MIN_SHIFT
    while (1 << shift) < size and shift < _MAX_SHIFT:
        shift += 1
    return shift


def allocate(size: int) -> bytearray:
    """A bytearray with len == size, capacity == next power of two.
    Oversize requests fall through to a plain allocation."""
    if size > (1 << _MAX_SHIFT):
        _stats["allocations"] += 1
        return bytearray(size)
    shift = _shift_for(size)
    with _lock:
        free = _slabs.get(shift)
        if free:
            buf = free.pop()
            _stats["reuses"] += 1
            # shrink/grow the VIEW to the requested length; capacity
            # stays the slab size underneath
            if len(buf) != size:
                if len(buf) < size:
                    buf.extend(b"\x00" * (size - len(buf)))
                else:
                    del buf[size:]
            return buf
        _stats["allocations"] += 1
    return bytearray(size)


def free(buf: bytearray) -> None:
    """Return a buffer to its slab (zeroing is the CALLER's job when
    the content is sensitive — same contract as slot_pool.go)."""
    if not isinstance(buf, bytearray):
        return
    cap = len(buf)
    if cap > (1 << _MAX_SHIFT) or cap < (1 << _MIN_SHIFT):
        _stats["dropped"] += 1
        return
    shift = _shift_for(cap)
    with _lock:
        free_list = _slabs.setdefault(shift, [])
        if len(free_list) >= _PER_SLAB:
            _stats["dropped"] += 1
            return
        free_list.append(buf)
        _stats["frees"] += 1


def stats() -> dict:
    with _lock:
        return dict(_stats,
                    pooled=sum(len(v) for v in _slabs.values()))
