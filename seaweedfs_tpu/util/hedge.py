"""Hedged replica reads: tail-latency insurance for the read path.

One slow replica must not spend a whole request budget (the
tail-at-scale shape: p99 of a fan-in is dominated by the slowest
leg).  When a read has a second location AND the request carries a
deadline, the primary fetch runs on a hedge worker; if it has not
answered within a p95-tracked latency threshold and the hedge token
budget allows, the SAME fetch is issued to the second replica and the
first success wins — the loser's response is discarded.

Load safety is the token budget: every tracked primary read earns
`SEAWEEDFS_TPU_HEDGE_RATIO` (0.1) of a token, capped at
`SEAWEEDFS_TPU_HEDGE_BURST` (16), and every *issued* hedge spends
one — steady state hedges are bounded at ~10% extra reads no matter
how slow the cluster gets, so hedging can never double cluster load.
The threshold is the p95 of recent successful primary reads (floored
at `SEAWEEDFS_TPU_HEDGE_MIN_MS`, 2ms): hedges fire only for reads
already slower than ~19 of their 20 predecessors.

Only deadline-carrying requests hedge (`SEAWEEDFS_TPU_HEDGE_READS=0`
disables entirely): the un-deadlined path — every benchmark arm, bulk
tooling — keeps the zero-handoff sequential funnel, so the plane
costs nothing where nobody asked for latency bounds.

Workers are plain daemon threads (not concurrent.futures: its
non-daemon workers would hold interpreter exit hostage to a parked
recv); per-thread pooled sockets persist across hedged calls exactly
like the main funnel's.

Observability: `hedges_issued_total` / `hedges_won_total` on the
shared registry; won/issued is the plane's value per token spent.
"""

from __future__ import annotations

import os
import queue
import threading
import time

from . import deadline as _deadline


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def reads_enabled() -> bool:
    return os.environ.get("SEAWEEDFS_TPU_HEDGE_READS", "1") \
        not in ("0", "false")


# runtime overrides (SLO autopilot, ISSUE 20): the env vars stay the
# operator-set BASELINE; the autopilot steers around it through these
# setters, which are autopilot-controlled knobs — mutate them only
# through the actuator registry (devtools rule SWFS021).  None =
# defer to the env.
_min_ms_override: "float | None" = None
_ratio_override: "float | None" = None


def set_min_threshold_ms(ms: "float | None") -> None:
    global _min_ms_override
    _min_ms_override = None if ms is None else max(0.0, float(ms))


def set_ratio(ratio: "float | None") -> None:
    global _ratio_override
    _ratio_override = None if ratio is None else max(0.0,
                                                     float(ratio))


def effective_ratio() -> float:
    if _ratio_override is not None:
        return _ratio_override
    return max(0.0, _env_float("SEAWEEDFS_TPU_HEDGE_RATIO", 0.1))


def min_threshold() -> float:
    if _min_ms_override is not None:
        return _min_ms_override / 1e3
    return _env_float("SEAWEEDFS_TPU_HEDGE_MIN_MS", 2.0) / 1e3


class LatencyTracker:
    """A quantile over a ring of recent latency samples (the hedge
    threshold's p95 here; qos.py's brownout median reuses it).  Tiny
    on the hot path: note() is one lock round + a ring write;
    quantile() sorts `size` floats only when a decision is actually
    being made."""

    def __init__(self, size: int = 128, min_samples: int = 8):
        self.size = size
        self.min_samples = min_samples
        self._ring: "list[float]" = []
        self._i = 0
        self._lock = threading.Lock()

    def note(self, seconds: float) -> None:
        with self._lock:
            if len(self._ring) < self.size:
                self._ring.append(seconds)
            else:
                self._ring[self._i] = seconds
                self._i = (self._i + 1) % self.size

    def note_many(self, seconds_batch: "list[float]") -> None:
        """Bulk note(): one lock round for a whole drained batch (the
        native-plane flight-record drain feeds thousands of samples a
        second — per-sample locking was measurable there).  A batch at
        least `size` long simply becomes the ring."""
        if not seconds_batch:
            return
        with self._lock:
            if len(seconds_batch) >= self.size:
                self._ring = list(seconds_batch[-self.size:])
                self._i = 0
                return
            for s in seconds_batch:
                if len(self._ring) < self.size:
                    self._ring.append(s)
                else:
                    self._ring[self._i] = s
                    self._i = (self._i + 1) % self.size

    def quantile(self, q: float = 0.95) -> "float | None":
        with self._lock:
            if len(self._ring) < self.min_samples:
                return None
            s = sorted(self._ring)
        return s[min(int(len(s) * q), len(s) - 1)]

    def reset(self) -> None:
        with self._lock:
            self._ring = []
            self._i = 0


class _TokenPool:
    """The hedge budget: earned by primary reads, spent per issued
    hedge.  Starts full — a cold process may hedge its very first
    slow read."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tokens: "float | None" = None

    def _burst(self) -> float:
        return max(1.0, _env_float("SEAWEEDFS_TPU_HEDGE_BURST", 16.0))

    def earn(self) -> None:
        ratio = effective_ratio()
        with self._lock:
            if self._tokens is None:
                self._tokens = self._burst()
            else:
                self._tokens = min(self._burst(), self._tokens + ratio)

    def take(self) -> bool:
        with self._lock:
            if self._tokens is None:
                self._tokens = self._burst()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def reset(self) -> None:
        with self._lock:
            self._tokens = None


read_tracker = LatencyTracker()
_tokens = _TokenPool()


def note_primary(seconds: float) -> None:
    """Record one successful primary read: feeds the threshold
    tracker AND earns the fractional hedge token that primary reads
    fund the hedge budget with."""
    read_tracker.note(seconds)
    _tokens.earn()


def take_token() -> bool:
    return _tokens.take()


def read_threshold() -> "float | None":
    """When to fire the hedge: p95 of recent primary reads, floored —
    None until the tracker has seen enough traffic to know what
    "slow" means here."""
    p95 = read_tracker.quantile(0.95)
    if p95 is None:
        return None
    return max(p95, min_threshold())


def reset() -> None:
    """Test isolation: forget latency history, refill tokens, drop
    any autopilot override back to the env baseline."""
    read_tracker.reset()
    _tokens.reset()
    set_min_threshold_ms(None)  # noqa: SWFS021 — reset to baseline,
    set_ratio(None)             # not a competing controller


# -- the hedge worker pool -------------------------------------------------
#
# Plain daemon threads over a SimpleQueue, GROWN ON DEMAND up to a
# cap: every deadline-carrying read parks a worker on its PRIMARY
# fetch for up to min(budget, socket timeout), so a fixed-size pool
# would let one wedged replica under modest concurrency absorb every
# worker and starve healthy reads' fetches in the queue.  A submit
# that finds no idle worker starts a fresh one instead (the cached-
# pool shape); parked-primary concurrency is thereby bounded by the
# CALLERS' concurrency, not by a pool constant, while the token
# budget keeps issued hedges — the only extra cluster load — at
# ~HEDGE_RATIO of reads regardless of pool size.  Idle workers park
# on the queue forever (daemon threads on persistent pooled sockets:
# retaining them is the point).  Per-thread pooled sockets persist,
# and interpreter exit never joins a parked recv
# (concurrent.futures' non-daemon workers would).

_work: "queue.SimpleQueue" = queue.SimpleQueue()
_workers_lock = threading.Lock()
_workers_started = 0
_tasks_outstanding = 0      # submitted, not yet finished


def _worker_cap() -> int:
    try:
        return max(2, int(os.environ.get(
            "SEAWEEDFS_TPU_HEDGE_WORKERS", "") or 64))
    except ValueError:
        return 64


def _worker_loop() -> None:
    global _tasks_outstanding
    while True:
        fn = _work.get()
        try:
            fn()
        except BaseException:   # noqa: SWFS004 — belt-and-braces: a
            # task's verdict (result OR exception) travels through the
            # caller's queue inside the task itself; a raise here
            # could only be a bug in that plumbing, and it must never
            # kill a shared worker
            pass
        finally:
            with _workers_lock:
                _tasks_outstanding -= 1


def _submit(fn) -> None:
    global _workers_started, _tasks_outstanding
    with _workers_lock:
        # invariant (below the cap): workers >= outstanding tasks, so
        # a new task NEVER waits behind a parked primary for a worker.
        # An idle-count heuristic instead would race: a just-spawned
        # worker looks idle while it is about to consume an older
        # queued task, and the submit that trusted it then queues.
        _tasks_outstanding += 1
        if _workers_started < min(_tasks_outstanding, _worker_cap()):
            threading.Thread(target=_worker_loop, daemon=True,
                             name=f"hedge-{_workers_started}"
                             ).start()
            _workers_started += 1
    _work.put(fn)


def hedged_fetch(primary, secondary, threshold_s: float, is_success,
                 kind: str = "read"):
    """First-wins race between two fetch callables.

    `primary` runs immediately (on a hedge worker, so this caller can
    keep watching the clock); if no verdict lands within
    `threshold_s` and a hedge token is available, `secondary` is
    issued too.  The first result passing `is_success` wins; the
    loser is discarded when it eventually lands.  Returns
    (result | None, hedged: bool) — None means no success (callers
    fall back to their sequential path).  The captured deadline is
    re-bound on the workers so their socket timeouts stay
    budget-derived."""
    results: "queue.SimpleQueue" = queue.SimpleQueue()
    d = _deadline.get()

    def run(tag: int, fn):
        def task():
            t0 = time.monotonic()
            try:
                with _deadline.use(d):
                    val = fn()
            except BaseException as e:  # noqa: BLE001 — raced verdict
                results.put((tag, e, None, time.monotonic() - t0))
            else:
                results.put((tag, None, val, time.monotonic() - t0))
        _submit(task)

    run(0, primary)
    outstanding = 1
    hedged = False
    # overall wall guard: the deadline when armed, else a generous cap
    # (each fetch carries its own socket timeout regardless)
    rem = d.remaining() if d is not None else 600.0
    end = time.monotonic() + rem
    while outstanding:
        if not hedged:
            wait = min(threshold_s, end - time.monotonic())
        else:
            wait = end - time.monotonic()
        try:
            tag, err, val, took = results.get(
                timeout=max(wait, 0.001))
        except queue.Empty:
            if not hedged and time.monotonic() < end and take_token():
                hedged = True
                _metrics().counter_add(
                    "hedges_issued_total", 1.0,
                    help_text="secondary replica fetches issued past "
                              "the latency threshold", kind=kind)
                # flight-recorder note (profiling.flight_note rides
                # the CALLER's context — this loop runs on the
                # handler/request thread, only the fetches are pooled)
                _flight_note("hedge", {
                    "kind": kind, "issued": True, "won": False,
                    "thresholdMs": round(threshold_s * 1e3, 2)})
                run(1, secondary)
                outstanding += 1
                continue
            if time.monotonic() >= end:
                break       # budget spent waiting; caller fails fast
            continue        # no token: keep waiting on the primary
        outstanding -= 1
        if tag == 0 and err is None and is_success(val):
            note_primary(took)
        if err is None and is_success(val):
            if tag == 1:
                _metrics().counter_add(
                    "hedges_won_total", 1.0,
                    help_text="hedged fetches that answered first",
                    kind=kind)
                _flight_note("hedge", {
                    "kind": kind, "issued": True, "won": True,
                    "thresholdMs": round(threshold_s * 1e3, 2)})
            return val, hedged
    return None, hedged


def _flight_note(key: str, value) -> None:
    from .. import profiling
    profiling.flight_note(key, value)


def _metrics():
    from .. import stats
    return stats.PROCESS
