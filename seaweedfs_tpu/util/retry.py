"""One retry policy for the whole data plane, plus the per-peer
health map / circuit breaker every client funnel consults.

Before this module each caller invented its own failure handling:
`httpd._pooled_request` retried once on a dead socket, `shard_source`
had ad-hoc mid-stream failover, `MasterFollower._run` fixed-slept,
`store_ec._remote_read` improvised — and nothing ever *stopped*
hammering a peer that was down.  This module gives them one
vocabulary:

* **retry_call**: capped exponential backoff with FULL jitter
  (delay ~ U(0, min(cap, base * 2^attempt)) — the AWS-architecture
  shape: synchronized retry storms from N clients decorrelate), only
  for idempotent work (the caller declares it; `_one_pooled_request`'s
  POST send-failed rule stays where it is), drawing on a per-process
  **retry budget** so a dying dependency costs bounded extra load;

* **per-peer circuit breaker**: consecutive transport failures trip a
  peer OPEN (calls fail fast with BreakerOpen instead of burning a
  timeout each), a cooldown later ONE half-open probe is let through —
  success closes the breaker, failure re-opens it.  Consulted by the
  pooled HTTP client, gRPC stubs, the master follower, `store_ec`
  remote shard reads, and the `ec.encode` scatter planner (a tripped
  destination is re-planned, not failed on).

Knobs (all env):

  SEAWEEDFS_TPU_RETRY_MAX_ATTEMPTS   total attempts per call (3)
  SEAWEEDFS_TPU_RETRY_BASE_MS        first backoff ceiling (50)
  SEAWEEDFS_TPU_RETRY_CAP_MS         backoff ceiling (2000)
  SEAWEEDFS_TPU_RETRY_BUDGET         retry-token bucket size (64)
  SEAWEEDFS_TPU_RETRY_BUDGET_REFILL  tokens refilled per second (4)
  SEAWEEDFS_TPU_BREAKER_THRESHOLD    consecutive failures to trip (5)
  SEAWEEDFS_TPU_BREAKER_COOLDOWN_MS  open time before a probe (2000)

Every retry and every breaker transition is observable: a
`retry.<site>` span rides the active trace (trace.show shows the
stall next to the hop that caused it) and `retry_attempts_total{site}`
/ `peer_breaker_state{peer}` land in the shared stats.PROCESS
registry that every role's /metrics appends.
"""

from __future__ import annotations

import os
import random
import threading
import time


class BreakerOpen(OSError):
    """Fail-fast refusal: the peer's breaker is open.  An OSError so
    existing transport-failure handling (failover, unwind, error
    bodies) applies; catch it specifically to re-plan instead."""

    def __init__(self, peer: str, retry_after: float):
        super().__init__(
            f"breaker open for peer {peer} "
            f"(retry in {retry_after:.1f}s)")
        self.peer = peer
        self.retry_after = retry_after


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def max_attempts() -> int:
    return max(1, _env_int("SEAWEEDFS_TPU_RETRY_MAX_ATTEMPTS", 3))


def backoff_base() -> float:
    return _env_float("SEAWEEDFS_TPU_RETRY_BASE_MS", 50.0) / 1e3


def backoff_cap() -> float:
    return _env_float("SEAWEEDFS_TPU_RETRY_CAP_MS", 2000.0) / 1e3


def breaker_threshold() -> int:
    return max(1, _env_int("SEAWEEDFS_TPU_BREAKER_THRESHOLD", 5))


def breaker_cooldown() -> float:
    return _env_float("SEAWEEDFS_TPU_BREAKER_COOLDOWN_MS", 2000.0) / 1e3


def backoff_delay(attempt: int, base: "float | None" = None,
                  cap: "float | None" = None,
                  rng: "random.Random | None" = None) -> float:
    """Full-jitter delay for retry number `attempt` (1-based)."""
    base = backoff_base() if base is None else base
    cap = backoff_cap() if cap is None else cap
    ceiling = min(cap, base * (2 ** max(attempt - 1, 0)))
    return (rng or random).uniform(0, ceiling)


# -- per-process retry budget (token bucket) ------------------------------
#
# Retries multiply load exactly when the system is least able to absorb
# it; the budget caps process-wide retry *rate* so a dying dependency
# costs a bounded amount of extra traffic, after which callers fail
# fast until the bucket refills.

class _Budget:
    def __init__(self):
        self._lock = threading.Lock()
        self._tokens: "float | None" = None
        self._stamp = 0.0

    def _capacity(self) -> float:
        return float(max(0, _env_int("SEAWEEDFS_TPU_RETRY_BUDGET", 64)))

    def _refill_rate(self) -> float:
        return max(0.0,
                   _env_float("SEAWEEDFS_TPU_RETRY_BUDGET_REFILL", 4.0))

    def take(self) -> bool:
        now = time.monotonic()
        cap = self._capacity()
        with self._lock:
            if self._tokens is None:
                self._tokens = cap
            else:
                self._tokens = min(
                    cap, self._tokens +
                    (now - self._stamp) * self._refill_rate())
            self._stamp = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def remaining(self) -> float:
        with self._lock:
            return self._capacity() if self._tokens is None \
                else self._tokens

    def reset(self) -> None:
        with self._lock:
            self._tokens = None
            self._stamp = 0.0


_budget = _Budget()


def budget_take() -> bool:
    ok = _budget.take()
    if not ok:
        _metrics().counter_add(
            "retry_budget_exhausted_total", 1.0,
            help_text="retries refused by the process retry budget")
    _metrics().gauge_set(
        "retry_budget_remaining", _budget.remaining(),
        help_text="retry tokens left in the process budget")
    return ok


def budget_remaining() -> float:
    return _budget.remaining()


# -- per-peer circuit breaker ---------------------------------------------

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class _Breaker:
    __slots__ = ("peer", "failures", "state", "opened_at", "probing",
                 "probe_started", "trips", "last_error")

    def __init__(self, peer: str):
        self.peer = peer
        self.failures = 0
        self.state = CLOSED
        self.opened_at = 0.0
        self.probing = False
        self.probe_started = 0.0
        self.trips = 0
        self.last_error = ""


_breakers: "dict[str, _Breaker]" = {}
_breakers_lock = threading.Lock()


def _breaker(peer: str) -> _Breaker:
    b = _breakers.get(peer)
    if b is None:
        b = _breakers.setdefault(peer, _Breaker(peer))
    return b


def _gauge_state_value(peer: str, state: str) -> None:
    _metrics().gauge_set(
        "peer_breaker_state", _STATE_GAUGE[state],
        help_text="per-peer circuit state (0 closed, 1 half-open, "
                  "2 open)", peer=peer)


def check_peer(peer: str) -> None:
    """Raise BreakerOpen when the peer is open and its cooldown has
    not elapsed; move open -> half-open (admitting THIS caller as the
    single probe) when it has.  No-op for closed/unknown peers."""
    if not peer:
        return
    transitioned = None
    with _breakers_lock:
        b = _breakers.get(peer)
        if b is None or b.state == CLOSED:
            return
        if b.state == OPEN:
            wait = b.opened_at + breaker_cooldown() - time.monotonic()
            if wait > 0:
                raise BreakerOpen(peer, wait)
            b.state = HALF_OPEN
            b.probing = True
            b.probe_started = time.monotonic()
            transitioned = b.state
        elif b.probing:
            # half-open: exactly one probe in flight at a time — but a
            # probe whose caller died without a verdict (an exception
            # outside the recorded set, a killed thread) must not
            # blacklist the peer forever, so a stale slot is reclaimed
            # by THIS caller after the probe TTL
            if time.monotonic() - b.probe_started > _probe_ttl():
                b.probe_started = time.monotonic()
            else:
                raise BreakerOpen(peer, breaker_cooldown())
        else:
            b.probing = True
            b.probe_started = time.monotonic()
    if transitioned:
        _gauge_state_value(peer, transitioned)


def _probe_ttl() -> float:
    """How long a half-open probe may stay unresolved before its slot
    is reclaimed: generous enough for any sane call timeout, bounded
    so an abandoned probe can't wedge the breaker."""
    return max(breaker_cooldown() * 2, 120.0)


def probe_release(peer: str) -> None:
    """Give back a half-open probe slot WITHOUT a health verdict —
    the probe call failed for a non-transport reason (serialization
    error, programming bug), which proves nothing about the peer.
    The next caller is admitted as a fresh probe."""
    if not peer:
        return
    with _breakers_lock:
        b = _breakers.get(peer)
        if b is not None and b.probing:
            b.probing = False


def record_success(peer: str) -> None:
    if not peer:
        return
    changed = False
    with _breakers_lock:
        b = _breakers.get(peer)
        if b is None:
            return
        changed = b.state != CLOSED
        b.failures = 0
        b.state = CLOSED
        b.probing = False
        b.last_error = ""
    if changed:
        _gauge_state_value(peer, CLOSED)


def record_failure(peer: str, error: str = "") -> None:
    if not peer:
        return
    tripped = None
    with _breakers_lock:
        b = _breaker(peer)
        b.failures += 1
        b.probing = False
        if error:
            b.last_error = error[:200]
        if b.state == HALF_OPEN or (b.state == CLOSED and
                                    b.failures >= breaker_threshold()):
            b.state = OPEN
            b.opened_at = time.monotonic()
            b.trips += 1
            tripped = (b.failures, b.last_error)
    if tripped is not None:
        _gauge_state_value(peer, OPEN)
        _metrics().counter_add(
            "peer_breaker_trips_total", 1.0,
            help_text="breaker close->open transitions", peer=peer)
        from . import wlog
        wlog.warning(
            f"peer breaker OPEN for {peer} after {tripped[0]} "
            f"consecutive failures"
            + (f" (last: {tripped[1]})" if tripped[1] else ""))


def peer_state(peer: str) -> str:
    with _breakers_lock:
        b = _breakers.get(peer)
        if b is None:
            return CLOSED
        if b.state == OPEN and \
                time.monotonic() >= b.opened_at + breaker_cooldown():
            return HALF_OPEN  # a probe would be admitted
        return b.state


def peer_available(peer: str) -> bool:
    """Planner-facing: False only while the peer is open with cooldown
    remaining (half-open peers are probe-worthy)."""
    return peer_state(peer) != OPEN


def health_snapshot() -> "dict[str, dict]":
    """JSON-able per-peer health for /debug/health and trace.show."""
    with _breakers_lock:
        return {
            peer: {"state": b.state, "consecutiveFailures": b.failures,
                   "trips": b.trips, "lastError": b.last_error}
            for peer, b in sorted(_breakers.items())
            if b.state != CLOSED or b.trips or b.failures}


def reset(peer: "str | None" = None) -> None:
    """Forget breaker state (and refill the budget when peer is None)
    — test isolation between chaos scenarios."""
    with _breakers_lock:
        if peer is None:
            _breakers.clear()
        else:
            _breakers.pop(peer, None)
    if peer is None:
        _budget.reset()


# -- the one retry loop ---------------------------------------------------

def _metrics():
    from .. import stats
    return stats.PROCESS


def _note_retry(site: str, peer: str, attempt: int, error: str,
                delay: float) -> None:
    _metrics().counter_add(
        "retry_attempts_total", 1.0,
        help_text="re-issued attempts after a transport failure",
        site=site or "?")
    # trace annotation: a zero-work span covering the backoff sleep,
    # parented under whatever span the caller is in — trace.show then
    # shows the retry (and which peer caused it) inline
    from .. import tracing
    tracing.emit_span(
        f"retry.{site or 'call'}", time.time(), delay,
        attrs={"attempt": attempt, "peer": peer, "error": error[:160]},
        error=False)


def _deterministic(e: BaseException) -> bool:
    """A failure whose outcome cannot change on re-issue: a TLS
    certificate-verification verdict (the peer presented the wrong
    identity — configuration, not weather).  Retrying burns budget and
    backoff time to learn the same thing; the caller needs the error."""
    import ssl
    return isinstance(e, ssl.SSLCertVerificationError)


def retry_call(fn, site: str = "", peer: str = "",
               idempotent: bool = True, attempts: "int | None" = None,
               base: "float | None" = None, cap: "float | None" = None,
               retry_on: tuple = (OSError,)):
    """Run `fn()` under the unified policy.

    Consults the peer's breaker before every attempt (BreakerOpen
    fails fast and is never retried here — the peer told us to go
    away), records success/failure to the health map, and re-issues
    only idempotent work, spending one retry-budget token per
    re-issue.  Deadline-aware (util/deadline): an attempt whose
    backoff sleep plus the minimum useful timeout would outlive the
    request's remaining budget is refused — the caller gets the
    transport error NOW instead of a doomed retry that finishes after
    the client gave up.  `fn` must be safe to call `attempts`
    times."""
    from . import deadline as _deadline
    attempts = max_attempts() if attempts is None else max(1, attempts)
    last: "BaseException | None" = None
    for attempt in range(1, attempts + 1):
        check_peer(peer)
        try:
            result = fn()
        except BreakerOpen:
            raise
        except _deadline.DeadlineExceeded:
            # the budget is spent: deterministic (budgets only
            # shrink), no verdict on the peer — return a held probe
            # slot and surface immediately
            probe_release(peer)
            raise
        except retry_on as e:
            if _deterministic(e):
                # a failed TLS handshake is a configuration verdict:
                # no retry token spent, no backoff slept — but the
                # probe slot is returned so the breaker can't wedge
                probe_release(peer)
                raise
            rem0 = _deadline.remaining()
            if rem0 is not None and rem0 <= 0.0:
                # the attempt lost to the BUDGET, not the peer: its
                # socket timeout was budget-capped, so a healthy-but-
                # slower peer times out exactly when the budget dies.
                # Recording that as a peer failure would let sustained
                # tight-budget traffic trip a healthy peer's breaker —
                # surface the budget verdict instead, charging nothing
                probe_release(peer)
                _deadline.note_exceeded(site or "retry")
                raise _deadline.DeadlineExceeded(
                    site or "retry") from e
            record_failure(peer, repr(e))
            last = e
            if not idempotent or attempt >= attempts:
                raise
            delay = backoff_delay(attempt, base, cap)
            rem = _deadline.remaining()
            if rem is not None and \
                    delay + _deadline.MIN_TIMEOUT > rem:
                # a doomed attempt: by the time the backoff elapses
                # there is no budget left for even a minimal dial —
                # spend nothing (no retry token) and fail now, AS the
                # budget verdict (the fronts translate
                # DeadlineExceeded to 504 + Retry-After; re-raising
                # the transport error would read as a generic 500
                # while the metric claims a deadline exceed)
                _deadline.note_exceeded(site or "retry")
                raise _deadline.DeadlineExceeded(
                    site or "retry") from e
            if not budget_take():
                raise
            _note_retry(site, peer, attempt, repr(e), delay)
            time.sleep(delay)
            continue
        except BaseException:
            # non-transport failure (bad payload, programming error):
            # no verdict on the peer, but a held half-open probe slot
            # must be returned or the breaker wedges open forever
            probe_release(peer)
            raise
        record_success(peer)
        return result
    raise last  # pragma: no cover — loop always returns or raises
