"""Configuration layer (reference: weed/util/config.go — viper-backed
TOML files with WEED_* environment overrides, and the per-role
scaffold TOMLs `filer.toml` / `notification.toml` / `replication.toml`
from weed/command/scaffold/).

Three pieces:

1. `apply_env_defaults(subparsers)` — every CLI flag of every role can
   be defaulted from the environment as `WEED_<ROLE>_<FLAG>` (flag
   name uppercased, dots/dashes -> underscores), matching the
   reference's viper `SetEnvPrefix("weed")` behavior.  Explicit
   command-line flags still win: the env only REPLACES the parser
   default.

2. `find_toml(name)` — the reference's search path: ./, ~/.seaweedfs/,
   /etc/seaweedfs/ (util/config.go LoadConfiguration).

3. Role helpers that read the scaffold shapes:
   - `filer_store_from_toml(path)`: the `[sqlite]` / `[leveldb2]`-
     family sections with `enabled = true` choose the filer store
     (our archetypes: sqlite, lsm, redis2->redis).
   - `notification_from_toml(path)`: `[notification.*]` sections ->
     the `-notification` spec string the filer CLI takes.
"""

from __future__ import annotations

import os

try:
    import tomllib
except ModuleNotFoundError:          # py<3.11: the tomli backport
    import tomli as tomllib

SEARCH_DIRS = (".", os.path.expanduser("~/.seaweedfs"),
               "/etc/seaweedfs")


def find_toml(name: str) -> "str | None":
    for d in SEARCH_DIRS:
        path = os.path.join(d, name)
        if os.path.isfile(path):
            return path
    return None


def load_toml(path: str) -> dict:
    with open(path, "rb") as f:
        return tomllib.load(f)


def _env_key(role: str, flag: str) -> str:
    clean = flag.lstrip("-").replace(".", "_").replace("-", "_")
    return f"WEED_{role.upper().replace('.', '_')}_{clean.upper()}"


def apply_env_defaults(subparsers: dict, environ=None) -> list[str]:
    """Rewrite each subparser's argument DEFAULTS from matching
    WEED_* env vars.  Returns the applied `ROLE.flag=value` list (for
    a startup log line).  Type conversion follows the argument's
    declared type; booleans accept true/1/yes."""
    environ = environ if environ is not None else os.environ
    applied = []
    for role, parser in subparsers.items():
        for action in parser._actions:          # noqa: SLF001
            if not action.option_strings:
                continue
            flag = action.option_strings[0]
            if flag in ("-h", "--help"):
                continue
            val = environ.get(_env_key(role, flag))
            if val is None:
                continue
            if isinstance(action.const, bool) or \
                    action.__class__.__name__ == "_StoreTrueAction":
                action.default = val.lower() in ("1", "true", "yes",
                                                 "on")
            elif action.type is int:
                action.default = int(val)
            elif action.type is float:
                action.default = float(val)
            else:
                action.default = val
            applied.append(f"{role}{flag}={val}")
    return applied


# -- filer.toml (command/scaffold/filer.toml shape) ------------------------

# reference store section -> our archetype; every leveldb flavor maps
# onto the embedded LSM, redis flavors onto the RESP store
_STORE_SECTIONS = {
    "sqlite": "sqlite",
    "leveldb2": "lsm", "leveldb3": "lsm", "leveldb": "lsm",
    "rocksdb": "lsm",
    "redis2": "redis", "redis": "redis", "redis_cluster2": "redis",
    "elastic7": "elastic", "elastic": "elastic",
}


def filer_store_from_toml(path: str) -> "tuple[str, str] | None":
    """(store_type, store_path) from the first enabled store section,
    or None.  Path fields per section shape: sqlite `dbFile`,
    leveldb* `dir`, redis* `address`."""
    doc = load_toml(path)
    for section, archetype in _STORE_SECTIONS.items():
        cfg = doc.get(section)
        if not cfg or not cfg.get("enabled", False):
            continue
        if archetype == "sqlite":
            return "sqlite", cfg.get("dbFile",
                                     cfg.get("dbfile", "filer.db"))
        if archetype == "lsm":
            return "lsm", cfg.get("dir", "./filerldb2")
        if archetype == "elastic":
            servers = cfg.get("servers",
                              cfg.get("address", "localhost:9200"))
            first = servers[0] if isinstance(servers, list) \
                else str(servers)
            return "elastic", first.removeprefix("http://")
        return "redis", cfg.get("address", "localhost:6379")
    return None


# -- notification.toml (command/scaffold/notification.toml) ----------------

def notification_from_toml(path: str) -> str:
    """First enabled [notification.*] sink -> our -notification spec
    (webhook:URL, kafka:host:port/topic, logfile:PATH,
    mq:broker/ns/topic)."""
    doc = load_toml(path).get("notification", {})
    wh = doc.get("webhook", {})
    if wh.get("enabled"):
        return "webhook:" + wh.get("url", "")
    kf = doc.get("kafka", {})
    if kf.get("enabled"):
        hosts = kf.get("hosts", ["localhost:9092"])
        host = hosts[0] if isinstance(hosts, list) else str(hosts)
        return f"kafka:{host}/{kf.get('topic', 'seaweedfs_meta')}"
    lg = doc.get("log", {}) or doc.get("logfile", {})
    if lg.get("enabled"):
        return "logfile:" + lg.get("path", "filer_events.log")
    mq = doc.get("mq", {})
    if mq.get("enabled"):
        return (f"mq:{mq.get('broker', 'localhost:17777')}/"
                f"{mq.get('namespace', 'notifications')}/"
                f"{mq.get('topic', 'filer_meta')}")
    return ""


# -- replication.toml (command/scaffold/replication.toml) ------------------

def replication_sink_from_toml(path: str) -> "tuple[str, dict] | None":
    """(sink_kind, config) from the first enabled [sink.*] section —
    the filer.backup CLI consumes this (sink kinds: local, s3, gcs,
    azure, b2 — our filer/*_sink.py family)."""
    doc = load_toml(path).get("sink", {})
    for kind in ("local", "s3", "gcs", "azure", "backblaze", "b2"):
        cfg = doc.get(kind, {})
        if cfg.get("enabled"):
            return ("b2" if kind == "backblaze" else kind), dict(cfg)
    return None
