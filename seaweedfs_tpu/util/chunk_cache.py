"""Tiered chunk cache (weed/util/chunk_cache/chunk_cache.go):
a memory LRU in front of a bounded on-disk cache, used by the mount's
read path so repeated reads of hot file blocks never re-cross the
network (the reference mounts read chunks through the same two tiers,
chunk_cache.go:113 ReadChunkAt — memory first, then disk layers).

Keys are opaque strings (the mount uses "<path>@<block>"); per-path
key tracking supports invalidation when a file changes under the
cache (the mount's meta-event subscription drives this, the analog of
the reference wiping its chunk cache on metadata updates)."""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict


class MemChunkCache:
    """Byte-bounded LRU (chunk_cache_in_memory.go)."""

    def __init__(self, limit_bytes: int = 64 << 20):
        self.limit = limit_bytes
        self._m: "OrderedDict[str, bytes]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    def get(self, key: str) -> "bytes | None":
        with self._lock:
            data = self._m.get(key)
            if data is not None:
                self._m.move_to_end(key)
            return data

    def set(self, key: str, data: bytes) -> None:
        if len(data) > self.limit:
            return
        with self._lock:
            old = self._m.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._m[key] = data
            self._bytes += len(data)
            while self._bytes > self.limit and self._m:
                _k, v = self._m.popitem(last=False)
                self._bytes -= len(v)

    def delete(self, key: str) -> None:
        with self._lock:
            old = self._m.pop(key, None)
            if old is not None:
                self._bytes -= len(old)


class DiskChunkCache:
    """Bounded on-disk tier (chunk_cache_on_disk.go, simplified to one
    layer): chunk files under a cache dir, LRU-evicted by in-process
    access order.  Survives nothing — it's a cache; a fresh process
    starts cold and stray files from a previous run are clipped by the
    same eviction."""

    def __init__(self, dir_path: str, limit_bytes: int = 1 << 30):
        self.dir = dir_path
        self.limit = limit_bytes
        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.Lock()
        self._order: "OrderedDict[str, int]" = OrderedDict()
        self._bytes = 0
        for name in os.listdir(dir_path):  # adopt leftovers
            p = os.path.join(dir_path, name)
            if os.path.isfile(p):
                sz = os.path.getsize(p)
                self._order[name] = sz
                self._bytes += sz
        self._evict_locked()

    def _fname(self, key: str) -> str:
        return hashlib.sha256(key.encode()).hexdigest()[:40]

    def get(self, key: str) -> "bytes | None":
        name = self._fname(key)
        with self._lock:
            if name not in self._order:
                return None
            self._order.move_to_end(name)
        try:
            with open(os.path.join(self.dir, name), "rb") as f:
                return f.read()
        except OSError:
            with self._lock:
                self._bytes -= self._order.pop(name, 0)
            return None

    def set(self, key: str, data: bytes) -> None:
        if len(data) > self.limit:
            return
        name = self._fname(key)
        tmp = os.path.join(self.dir, f".{name}.{os.getpid()}")
        try:
            with open(tmp, "w+b") as f:
                f.write(data)
            os.replace(tmp, os.path.join(self.dir, name))
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return
        with self._lock:
            self._bytes -= self._order.pop(name, 0)
            self._order[name] = len(data)
            self._bytes += len(data)
            self._evict_locked()

    def delete(self, key: str) -> None:
        name = self._fname(key)
        with self._lock:
            self._bytes -= self._order.pop(name, 0)
        try:
            os.remove(os.path.join(self.dir, name))
        except OSError:
            pass

    def _evict_locked(self) -> None:
        while self._bytes > self.limit and self._order:
            name, sz = self._order.popitem(last=False)
            self._bytes -= sz
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass


class TieredChunkCache:
    """Memory in front of optional disk (chunk_cache.go
    TieredChunkCache).  Tracks keys per group (file path) so a changed
    file invalidates all of its cached blocks at once."""

    # bounds on the group index itself: the data tiers evict by bytes,
    # but key-name bookkeeping would otherwise grow with every file
    # ever read
    MAX_GROUPS = 4096
    MAX_KEYS_PER_GROUP = 8192

    def __init__(self, mem_limit: int = 64 << 20,
                 disk_dir: "str | None" = None,
                 disk_limit: int = 1 << 30):
        self.mem = MemChunkCache(mem_limit)
        self.disk = DiskChunkCache(disk_dir, disk_limit) \
            if disk_dir else None
        self._groups: "OrderedDict[str, set]" = OrderedDict()
        self._glock = threading.Lock()

    def get(self, key: str) -> "bytes | None":
        data = self.mem.get(key)
        if data is not None:
            return data
        if self.disk is not None:
            data = self.disk.get(key)
            if data is not None:
                self.mem.set(key, data)  # promote
        return data

    def set(self, key: str, data: bytes, group: str = "") -> None:
        self.mem.set(key, data)
        if self.disk is not None:
            self.disk.set(key, data)
        if group:
            evict: "list[str]" = []
            with self._glock:
                keys = self._groups.get(group)
                if keys is None:
                    keys = self._groups[group] = set()
                else:
                    self._groups.move_to_end(group)
                keys.add(key)
                # evicted bookkeeping must drop its cached data too,
                # or a group forgotten by the index could serve stale
                # blocks with no way to invalidate them
                if len(keys) > self.MAX_KEYS_PER_GROUP:
                    evict.extend(keys)
                    self._groups.pop(group, None)
                while len(self._groups) > self.MAX_GROUPS:
                    _g, old_keys = self._groups.popitem(last=False)
                    evict.extend(old_keys)
            for k in evict:
                self.mem.delete(k)
                if self.disk is not None:
                    self.disk.delete(k)

    def invalidate_group(self, group: str) -> None:
        with self._glock:
            keys = self._groups.pop(group, set())
        for key in keys:
            self.mem.delete(key)
            if self.disk is not None:
                self.disk.delete(key)
