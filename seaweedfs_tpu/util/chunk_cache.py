"""Tiered chunk cache (weed/util/chunk_cache/chunk_cache.go):
a memory LRU in front of a bounded on-disk cache.  Originally the
mount's private read helper; now the SHARED hot-data cache of the
whole read plane — the volume server's hot-needle cache and the
filer's chunk-body cache are the same two tiers under different key
schemes (the reference serves mount reads through TieredChunkCache
the same way, chunk_cache.go:113 ReadChunkAt — memory first, then
disk layers).

Keys are opaque strings (the mount uses "<path>@<block>", the volume
server "<vid>.g<gen>.<fid>", the filer a chunk fid); per-group key
tracking supports invalidation when a file/needle changes under the
cache (the mount's meta-event subscription and the volume server's
write/delete hooks drive this, the analog of the reference wiping its
chunk cache on metadata updates).

Instrumented caches (``name=`` set) count hits/misses/evictions into
the shared stats.PROCESS registry, so every role's /metrics exposes
``seaweedfs_tpu_read_cache_{hits,misses,evictions}_total{cache=...}``
plus ``read_cache_bytes{cache=...,tier=...}`` occupancy gauges —
cluster.top renders the hit ratio from exactly these counters."""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict


def read_cache_mb(default: int = 64) -> int:
    """The shared knob for the server-side caches'  memory tier
    (``SEAWEEDFS_TPU_READ_CACHE_MB``, 0 disables)."""
    try:
        return int(os.environ.get("SEAWEEDFS_TPU_READ_CACHE_MB", "")
                   or default)
    except ValueError:
        return default


def read_cache_disk() -> "tuple[str | None, int]":
    """(dir, limit_mb) for the optional disk tier
    (``SEAWEEDFS_TPU_READ_CACHE_DIR`` / ``_DISK_MB``)."""
    d = os.environ.get("SEAWEEDFS_TPU_READ_CACHE_DIR", "") or None
    try:
        mb = int(os.environ.get("SEAWEEDFS_TPU_READ_CACHE_DISK_MB", "")
                 or 1024)
    except ValueError:
        mb = 1024
    return d, mb


class _CacheMeter:
    """PROCESS-registry emission for one named cache.  A None name is
    the uninstrumented (zero-overhead beyond a truthiness check) mode
    the mount's original usage keeps."""

    __slots__ = ("name",)

    def __init__(self, name: "str | None"):
        self.name = name

    # literal mint names (SWFS017): the event set is closed, and a
    # typo'd `which` fails loud here instead of minting a new family
    _COUNTERS = {
        "hits": "read_cache_hits_total",
        "misses": "read_cache_misses_total",
        "evictions": "read_cache_evictions_total",
        "invalidations": "read_cache_invalidations_total",
    }

    def count(self, which: str, n: float = 1.0) -> None:
        if not self.name:
            return
        _process().counter_add(
            self._COUNTERS[which], n,
            help_text=f"hot read-cache {which} (shared tier, "
                      f"util/chunk_cache)", cache=self.name)

    def bytes_served(self, n: int) -> None:
        if not self.name or n <= 0:
            return
        _process().counter_add(
            "read_cache_bytes_served_total", float(n),
            help_text="bytes answered from the hot read cache instead "
                      "of disk/network", cache=self.name)

    def occupancy(self, tier: str, nbytes: int) -> None:
        if not self.name:
            return
        _process().gauge_set(
            "read_cache_bytes", float(nbytes),
            help_text="bytes resident in the hot read cache",
            cache=self.name, tier=tier)


def _process():
    from .. import stats
    return stats.PROCESS


class MemChunkCache:
    """Byte-bounded LRU (chunk_cache_in_memory.go)."""

    def __init__(self, limit_bytes: int = 64 << 20,
                 meter: "_CacheMeter | None" = None):
        self.limit = limit_bytes
        self._m: "OrderedDict[str, bytes]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._meter = meter or _CacheMeter(None)

    def get(self, key: str) -> "bytes | None":
        with self._lock:
            data = self._m.get(key)
            if data is not None:
                self._m.move_to_end(key)
            return data

    def set(self, key: str, data: bytes) -> None:
        if len(data) > self.limit:
            return
        evicted = 0
        with self._lock:
            old = self._m.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._m[key] = data
            self._bytes += len(data)
            while self._bytes > self.limit and self._m:
                _k, v = self._m.popitem(last=False)
                self._bytes -= len(v)
                evicted += 1
            nbytes = self._bytes
        if evicted:
            self._meter.count("evictions", evicted)
        self._meter.occupancy("mem", nbytes)

    def delete(self, key: str) -> None:
        with self._lock:
            old = self._m.pop(key, None)
            if old is not None:
                self._bytes -= len(old)

    def set_limit(self, limit_bytes: int) -> None:
        """Runtime resize (SLO autopilot actuator, ISSUE 20): shrink
        evicts LRU-first down to the new bound immediately so the
        memory actually comes back; grow just raises the watermark."""
        evicted = 0
        with self._lock:
            self.limit = max(0, int(limit_bytes))
            while self._bytes > self.limit and self._m:
                _k, v = self._m.popitem(last=False)
                self._bytes -= len(v)
                evicted += 1
            nbytes = self._bytes
        if evicted:
            self._meter.count("evictions", evicted)
        self._meter.occupancy("mem", nbytes)


class DiskChunkCache:
    """Bounded on-disk tier (chunk_cache_on_disk.go, simplified to one
    layer): chunk files under a cache dir, LRU-evicted by in-process
    access order.  Survives nothing — it's a cache; a fresh process
    starts COLD: stray files from a previous run are adopted for byte
    accounting (so the dir never outgrows its bound across restarts)
    but are NEVER servable until re-written by this process.  Serving
    them would be a stale-read hole — the invalidation events that
    covered them died with the old process (the mount's meta-event
    cursor starts at boot time, so a file changed while the mount was
    down would keep serving pre-change blocks forever)."""

    def __init__(self, dir_path: str, limit_bytes: int = 1 << 30,
                 meter: "_CacheMeter | None" = None):
        self.dir = dir_path
        self.limit = limit_bytes
        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.Lock()
        self._order: "OrderedDict[str, int]" = OrderedDict()
        self._bytes = 0
        self._meter = meter or _CacheMeter(None)
        # adopted leftovers: eviction fodder only (see class doc)
        self._stale: set[str] = set()
        for name in os.listdir(dir_path):
            p = os.path.join(dir_path, name)
            if os.path.isfile(p):
                sz = os.path.getsize(p)
                self._order[name] = sz
                self._bytes += sz
                self._stale.add(name)
        self._evict_locked()

    def _fname(self, key: str) -> str:
        return hashlib.sha256(key.encode()).hexdigest()[:40]

    def get(self, key: str) -> "bytes | None":
        name = self._fname(key)
        with self._lock:
            if name not in self._order or name in self._stale:
                return None
            self._order.move_to_end(name)
        try:
            with open(os.path.join(self.dir, name), "rb") as f:
                # bound the read to what set() could have written: a
                # file swapped under the cache must not buffer
                # unbounded bytes through this process (SWFS013 rule)
                return f.read(self.limit)
        except OSError:
            with self._lock:
                self._bytes -= self._order.pop(name, 0)
            return None

    def set(self, key: str, data: bytes) -> None:
        if len(data) > self.limit:
            return
        name = self._fname(key)
        tmp = os.path.join(self.dir, f".{name}.{os.getpid()}")
        try:
            with open(tmp, "w+b") as f:
                f.write(data)
            os.replace(tmp, os.path.join(self.dir, name))
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return
        with self._lock:
            self._stale.discard(name)
            self._bytes -= self._order.pop(name, 0)
            self._order[name] = len(data)
            self._bytes += len(data)
            self._evict_locked()
            nbytes = self._bytes
        self._meter.occupancy("disk", nbytes)

    def delete(self, key: str) -> None:
        name = self._fname(key)
        with self._lock:
            self._bytes -= self._order.pop(name, 0)
            self._stale.discard(name)
        try:
            os.remove(os.path.join(self.dir, name))
        except OSError:
            pass

    def _evict_locked(self) -> None:
        while self._bytes > self.limit and self._order:
            name, sz = self._order.popitem(last=False)
            self._bytes -= sz
            self._stale.discard(name)
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass


class TieredChunkCache:
    """Memory in front of optional disk (chunk_cache.go
    TieredChunkCache).  Tracks keys per group (file path / volume id)
    so a changed file invalidates all of its cached blocks at once.

    `name` arms the hit/miss/eviction meters on stats.PROCESS — the
    server-side caches (volume needle, filer chunk) set it so their
    effectiveness is observable on every /metrics."""

    # bounds on the group index itself: the data tiers evict by bytes,
    # but key-name bookkeeping would otherwise grow with every file
    # ever read
    MAX_GROUPS = 4096
    MAX_KEYS_PER_GROUP = 8192

    def __init__(self, mem_limit: int = 64 << 20,
                 disk_dir: "str | None" = None,
                 disk_limit: int = 1 << 30,
                 name: "str | None" = None):
        self._meter = _CacheMeter(name)
        self.mem = MemChunkCache(mem_limit, meter=self._meter)
        self.disk = DiskChunkCache(disk_dir, disk_limit,
                                   meter=self._meter) \
            if disk_dir else None
        self._groups: "OrderedDict[str, set]" = OrderedDict()
        self._glock = threading.Lock()

    def get(self, key: str) -> "bytes | None":
        data = self.mem.get(key)
        if data is None and self.disk is not None:
            data = self.disk.get(key)
            if data is not None:
                self.mem.set(key, data)  # promote
        if data is None:
            self._meter.count("misses")
        else:
            self._meter.count("hits")
            self._meter.bytes_served(len(data))
        return data

    def set(self, key: str, data: bytes, group: str = "") -> None:
        self.mem.set(key, data)
        if self.disk is not None:
            self.disk.set(key, data)
        if group:
            evict: "list[str]" = []
            with self._glock:
                keys = self._groups.get(group)
                if keys is None:
                    keys = self._groups[group] = set()
                else:
                    self._groups.move_to_end(group)
                keys.add(key)
                # evicted bookkeeping must drop its cached data too,
                # or a group forgotten by the index could serve stale
                # blocks with no way to invalidate them
                if len(keys) > self.MAX_KEYS_PER_GROUP:
                    evict.extend(keys)
                    self._groups.pop(group, None)
                while len(self._groups) > self.MAX_GROUPS:
                    _g, old_keys = self._groups.popitem(last=False)
                    evict.extend(old_keys)
            for k in evict:
                self.mem.delete(k)
                if self.disk is not None:
                    self.disk.delete(k)

    def invalidate_group(self, group: str) -> None:
        with self._glock:
            keys = self._groups.pop(group, set())
        if keys:
            self._meter.count("invalidations", len(keys))
        for key in keys:
            self.mem.delete(key)
            if self.disk is not None:
                self.disk.delete(key)

    # the mount's meta-event subscription speaks paths; group == path
    # there, so give the wiring its natural name
    invalidate_path = invalidate_group

    def delete(self, key: str) -> None:
        """Point invalidation of one key across both tiers (the volume
        server's write/delete hooks target exactly one needle)."""
        self.mem.delete(key)
        if self.disk is not None:
            self.disk.delete(key)

    def set_mem_limit(self, limit_bytes: int) -> None:
        """Runtime resize of the memory tier (SLO autopilot actuator,
        ISSUE 20) — an autopilot-controlled knob; mutate only through
        the actuator registry (devtools rule SWFS021).  The disk tier
        keeps its boot-time bound: its cost is spindle bytes, not the
        RSS the controller is trading against hit value."""
        self.mem.set_limit(limit_bytes)
