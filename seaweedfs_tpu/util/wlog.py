"""Leveled, structured logging — the glog analog
(reference: weed/glog/glog.go V-levels + severities, glog_json.go
structured output, glog_file.go file sinks, glog_ctx.go request-id
context).

Design, tpu-framework style rather than a Go port:

- severities INFO < WARNING < ERROR < FATAL map onto the stdlib
  logging hierarchy (one root logger "weed", real handlers, no
  custom file format machinery);
- `V(n)` verbosity gates *debug* detail exactly like glog: `if
  wlog.V(2): wlog.info(...)` or the sugar `wlog.v(2, "...")`.
  Verbosity comes from `-v N` on every CLI role (or WEED_V);
- every line carries the active request id (util/request_id
  contextvar) when one is set, so a single request can be traced
  across gateway -> filer -> volume hops;
- `-logtostderr` is the default (tests, containers); `set_output`
  adds a file sink with size-based rotation (glog_file.go role);
- `json_format(True)` switches to one-JSON-object-per-line
  (glog_json.go) for log shippers.
"""

from __future__ import annotations

import io
import json
import logging
import os
import sys
import threading
import time

_logger = logging.getLogger("weed")
_logger.setLevel(logging.INFO)
_logger.propagate = False
_verbosity = int(os.environ.get("WEED_V", "0") or 0)
_lock = threading.Lock()
_json = False


class _Formatter(logging.Formatter):
    """glog line shape: `I0131 15:04:05.123456 component] msg`
    (severity letter + MMDD HH:MM:SS.micros), with rid= appended
    when a request id is active."""

    def format(self, record: logging.LogRecord) -> str:
        t = time.localtime(record.created)
        micros = int((record.created % 1) * 1e6)
        rid = current_request_id()
        if _json:
            doc = {"severity": record.levelname,
                   "time": time.strftime("%Y-%m-%dT%H:%M:%S",
                                         t) + f".{micros:06d}",
                   "message": record.getMessage()}
            if getattr(record, "component", ""):
                doc["component"] = record.component
            if rid:
                doc["requestId"] = rid
            return json.dumps(doc)
        letter = record.levelname[0]
        stamp = time.strftime("%m%d %H:%M:%S", t)
        comp = getattr(record, "component", "") or record.module
        line = (f"{letter}{stamp}.{micros:06d} {comp}] "
                f"{record.getMessage()}")
        if rid:
            line += f" rid={rid}"
        return line


class _RotatingHandler(logging.Handler):
    """Size-rotated file sink (glog_file.go keeps dated files; a
    simple .1 shift is the same operational contract: bounded disk,
    most-recent-first)."""

    def __init__(self, path: str, max_bytes: int = 64 << 20,
                 backups: int = 3):
        super().__init__()
        self.path = path
        self.max_bytes = max_bytes
        self.backups = backups
        self._f = open(path, "a", buffering=1)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record) + "\n"
            with _lock:
                if self._f.tell() + len(line) > self.max_bytes:
                    self._rotate()
                self._f.write(line)
        except Exception:     # noqa: BLE001,SWFS004 — logging must
            pass              # never raise into the caller

    def _rotate(self) -> None:
        self._f.close()
        for i in range(self.backups - 1, 0, -1):
            src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "a", buffering=1)

    def close(self) -> None:
        with _lock:
            self._f.close()
        super().close()


_stderr_handler = logging.StreamHandler(sys.stderr)
_stderr_handler.setFormatter(_Formatter())
_logger.addHandler(_stderr_handler)
_file_handler: "_RotatingHandler | None" = None


# -- configuration ---------------------------------------------------------

def set_verbosity(v: int) -> None:
    """The -v flag (glog vmodule-less form)."""
    global _verbosity
    _verbosity = int(v)


def get_verbosity() -> int:
    return _verbosity


def json_format(enabled: bool = True) -> None:
    global _json
    _json = bool(enabled)


def set_output(path: str, max_bytes: int = 64 << 20,
               backups: int = 3, also_stderr: bool = True) -> None:
    """Add (or replace) the rotating file sink (-logdir role)."""
    global _file_handler
    with _lock:
        old, _file_handler = _file_handler, None
    if old is not None:
        # close OUTSIDE the module lock: the handler's own emit/close
        # take the same (non-reentrant) lock
        _logger.removeHandler(old)
        old.close()
    with _lock:
        _file_handler = _RotatingHandler(path, max_bytes, backups)
    _file_handler.setFormatter(_Formatter())
    _logger.addHandler(_file_handler)
    if not also_stderr:
        _logger.removeHandler(_stderr_handler)


# -- emission --------------------------------------------------------------

class _VGate:
    """`wlog.V(2)` is truthy when verbosity >= 2 and exposes the
    severity methods, so both glog idioms work:
        if wlog.V(2): wlog.info("...")
        wlog.V(2).info("...")"""

    def __init__(self, level: int):
        self.level = level

    def __bool__(self) -> bool:
        return _verbosity >= self.level

    def info(self, msg: str, *args, component: str = "") -> None:
        if self:
            _log(logging.INFO, msg, args, component)

    infof = info


def V(level: int) -> _VGate:            # noqa: N802 — glog name
    return _VGate(level)


def _log(level: int, msg: str, args, component: str) -> None:
    _logger.log(level, msg, *args,
                extra={"component": component} if component else None)


def info(msg: str, *args, component: str = "") -> None:
    _log(logging.INFO, msg, args, component)


def v(level: int, msg: str, *args, component: str = "") -> None:
    if _verbosity >= level:
        _log(logging.INFO, msg, args, component)


def warning(msg: str, *args, component: str = "") -> None:
    _log(logging.WARNING, msg, args, component)


def error(msg: str, *args, component: str = "") -> None:
    _log(logging.ERROR, msg, args, component)


def fatal(msg: str, *args, component: str = "") -> None:
    """glog.Fatal: log then exit(255)."""
    _log(logging.CRITICAL, msg, args, component)
    sys.exit(255)


def exception(msg: str, *args, component: str = "") -> None:
    """error + current traceback (the glog.Errorf("%v", err) +
    debug.PrintStack pattern)."""
    import traceback
    buf = io.StringIO()
    traceback.print_exc(file=buf)
    _log(logging.ERROR, msg + "\n" + buf.getvalue(), args, component)


# -- request-id bridge (util/request_id + glog_ctx.go) ---------------------

def current_request_id() -> str:
    try:
        from .request_id import get_request_id
        return get_request_id()
    except ImportError:         # pragma: no cover
        return ""
