"""Per-request deadline plane: one budget, every hop.

A slow or wedged peer must cost a request its *budget*, never minutes:
before this module every outbound hop in the client funnel carried an
independent fixed timeout (30s control, 600s bulk), nothing told a
downstream server how long the caller was still willing to wait, and
work kept executing long after the client had given up.  This module
is the shared vocabulary the whole request path speaks instead:

* a **Deadline** (monotonic expiry) rides a contextvar, stamped at
  every ingress — the threaded httpd front, the asyncio front, the
  gRPC servicer wrappers, and the shell's command dispatch — from the
  caller's `X-Weed-Deadline-Ms` header (remaining milliseconds at send
  time), gRPC's native `grpc-timeout`, or the operator default
  `SEAWEEDFS_TPU_DEADLINE_DEFAULT_MS`;

* every outbound hop forwards the REMAINING budget as the same header
  (`stamp_headers`) and derives its socket/connect/read timeout from
  it (`io_timeout`): the budget only ever shrinks across hops, so the
  deepest hop in a gateway -> filer -> volume chain can never out-wait
  the edge;

* an **expired** budget fails fast: `io_timeout` raises
  `DeadlineExceeded` (an OSError — every transport-failure handler
  already knows what to do) *before* dialing, and the server fronts
  answer 504 + Retry-After *before* dispatching the handler — work is
  shed at the cheapest point, never after queueing (`util/retry`
  additionally refuses any retry whose backoff + minimum useful
  timeout exceeds what is left).

Contextvars do not follow worker-pool threads; code that fans a
request out (the filer's chunk-upload pool, hedged reads) captures
`get()` and re-binds with `use(...)` — the same pattern as
profiling.use_track.

Observability (shared stats.PROCESS registry, on every /metrics):
`deadline_exceeded_total{site}` counts every fail-fast (ingress and
client sites), `deadline_remaining_seconds{site}` is the
remaining-budget histogram observed at each ingress hop — a shrinking
per-hop profile is the plane working; a flat one means a hop is not
forwarding.  `cluster.top` renders the exceeded/hedge counters.
"""

from __future__ import annotations

import contextlib
import contextvars
import math
import os
import time

# remaining budget in integer milliseconds at the moment the request
# left the sender (the only clock both ends share is "duration")
HEADER = "X-Weed-Deadline-Ms"

# the minimum useful socket timeout: below this a dial/recv cannot
# plausibly succeed, so a derived timeout is floored here and a
# remaining budget smaller than it is treated as already spent by the
# retry policy's doomed-attempt check
MIN_TIMEOUT = 0.05


class DeadlineExceeded(OSError):
    """The request's budget is spent.  An OSError so transport-failure
    handling (unwind, error bodies) applies — but deterministic for
    the retry policy: a budget only shrinks, so re-issuing can never
    change the verdict."""

    def __init__(self, site: str = ""):
        super().__init__(
            f"request deadline exceeded{f' at {site}' if site else ''}")
        self.site = site


class Deadline:
    """Monotonic expiry; cheap to query, immutable.  `budget` keeps
    the ORIGINAL grant so the flight recorder can report what this
    hop was given at ingress, not just what was left at the end."""

    __slots__ = ("expires_at", "budget")

    def __init__(self, budget_s: float):
        self.budget = max(float(budget_s), 0.0)
        self.expires_at = time.monotonic() + self.budget

    def remaining(self) -> float:
        return max(0.0, self.expires_at - time.monotonic())

    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at

    def header_value(self) -> str:
        """Remaining budget as the wire header value (whole ms,
        rounded down — the receiver must never think it has more time
        than the sender does)."""
        return str(int(self.remaining() * 1e3))


_current: "contextvars.ContextVar[Deadline | None]" = \
    contextvars.ContextVar("weed_deadline", default=None)


def get() -> "Deadline | None":
    return _current.get()


def remaining() -> "float | None":
    """Seconds left, or None when no deadline is armed."""
    d = _current.get()
    return None if d is None else d.remaining()


def bind(deadline: "Deadline | None") -> "contextvars.Token":
    return _current.set(deadline)


def restore(token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def use(deadline: "Deadline | None"):
    """Re-bind a captured deadline on another thread (worker pools:
    the filer's chunk-upload fan-out, hedge workers).  Always sets —
    including None — because pooled threads otherwise carry the
    PREVIOUS request's deadline forever."""
    token = _current.set(deadline)
    try:
        yield deadline
    finally:
        _current.reset(token)


@contextlib.contextmanager
def scope(budget_s: float):
    """Mint a fresh deadline for a local operation (tests, shell
    commands, tools)."""
    token = _current.set(Deadline(budget_s))
    try:
        yield _current.get()
    finally:
        _current.reset(token)


def default_budget() -> float:
    """Operator default applied at ingress when the caller sent no
    budget (SEAWEEDFS_TPU_DEADLINE_DEFAULT_MS, 0 = no default — the
    plane is header-driven only)."""
    try:
        ms = float(os.environ.get(
            "SEAWEEDFS_TPU_DEADLINE_DEFAULT_MS", "") or 0.0)
    except ValueError:
        ms = 0.0
    if not math.isfinite(ms):
        ms = 0.0
    return max(ms, 0.0) / 1e3


def parse_header(value: "str | None") -> "Deadline | None":
    """The wire header -> a Deadline (None for absent/malformed —
    a garbled budget must not take the request down, it just rides
    un-deadlined like before the plane existed)."""
    if not value:
        return None
    try:
        ms = float(value)
    except ValueError:
        return None
    if not math.isfinite(ms):
        # 'inf' would overflow int(remaining()*1e3) at the next span
        # tag, and Deadline(nan) is never expired() yet has zero
        # remaining() — both are malformed, both ride un-deadlined
        return None
    if ms < 0:
        ms = 0.0
    return Deadline(ms / 1e3)


def adopt(header_value: "str | None", site: str = "",
          allow_default: bool = True) -> "Deadline | None":
    """Ingress stamping: adopt the caller's budget (or mint the
    operator default), ALWAYS (re)setting the contextvar — handler
    threads are reused across requests and a stale deadline from the
    previous request must never govern this one.  Observes the
    remaining-budget histogram for the hop when armed.

    `allow_default=False` skips the operator-default minting (an
    EXPLICIT caller budget is always honored): the fronts pass it for
    the /admin/ and /debug/ maintenance planes, whose bulk operations
    (a 30GB volume copy, an EC rebuild) legitimately outlive any
    tenant-facing default — a cluster-wide default must not 504 the
    repair pipeline mid-pull."""
    d = parse_header(header_value)
    if d is None and allow_default:
        budget = default_budget()
        if budget > 0:
            d = Deadline(budget)
    return adopt_deadline(d, site)


def adopt_budget(budget_s: "float | None",
                 site: str = "") -> "Deadline | None":
    """Ingress stamping for transports that already decoded the
    remaining budget into seconds (gRPC's `context.time_remaining()`
    instead of the HTTP header).  Same contract as `adopt`: always
    (re)binds, observes the ingress histogram when armed."""
    return adopt_deadline(
        Deadline(budget_s) if budget_s is not None else None, site)


def adopt_deadline(d: "Deadline | None",
                   site: str = "") -> "Deadline | None":
    _current.set(d)
    if d is not None:
        # per-site observers resolved once (stats.Metrics.observer,
        # ROADMAP 1d): ingress stamping runs on every budgeted request
        m = _metrics()
        obs = m.obs_memo.get(("deadline_remaining_seconds", site))
        if obs is None:
            obs = m.obs_memo[("deadline_remaining_seconds", site)] = \
                m.observer(
                    "deadline_remaining_seconds",
                    help_text="request budget remaining at ingress, "
                              "per hop", site=site or "?")
        obs(d.remaining())
    return d


def stamp_headers(headers: dict) -> dict:
    """Forward the remaining budget on an outbound hop (explicit
    caller header wins).  Returns `headers` untouched when no deadline
    is armed — the unarmed path costs one contextvar read."""
    d = _current.get()
    if d is None or HEADER in headers:
        return headers
    headers = dict(headers)
    headers[HEADER] = d.header_value()
    return headers


def io_timeout(default: float, site: str = "") -> float:
    """Derive a socket/connect/read timeout from the remaining budget:
    min(default, remaining) floored at MIN_TIMEOUT.  An already-spent
    budget raises DeadlineExceeded (counted per site) BEFORE the dial
    — failing fast is the point.  Unarmed requests keep `default`."""
    d = _current.get()
    if d is None:
        return default
    rem = d.remaining()
    if rem <= 0.0:
        note_exceeded(site)
        raise DeadlineExceeded(site)
    return min(default, max(rem, MIN_TIMEOUT))


def reraise_if_expired(site: str) -> None:
    """For transport-failure (`except OSError`) handlers on the
    client funnel: when the armed budget is (now) spent, the failure
    in hand is the BUDGET's verdict — a budget-capped socket timeout
    on a healthy-but-slower peer, or a DeadlineExceeded raised
    mid-call — so count it and re-raise as DeadlineExceeded instead
    of returning, letting the caller mark a healthy peer
    down/failed-over/plane-less for the client's clock.  No-op when
    no deadline is armed or budget remains (a real peer failure:
    handle as before)."""
    d = _current.get()
    if d is not None and d.expired():
        note_exceeded(site)
        raise DeadlineExceeded(site) from None


def note_exceeded(site: str) -> None:
    _metrics().counter_add(
        "deadline_exceeded_total", 1.0,
        help_text="requests/hops refused because the budget was spent",
        site=site or "?")


def expired_response(site: str) -> "tuple[int, tuple]":
    """The uniform server-front answer for a request that arrived
    (or queued) past its budget: 504 + Retry-After before any handler
    work.  Retry-After 1s: the client's next attempt carries a fresh
    budget; there is nothing server-side to wait out."""
    note_exceeded(site)
    body = b'{"error": "deadline exceeded before dispatch"}'
    return 504, (body, {"Retry-After": "1",
                        "Content-Type": "application/json"})


def handler_exceeded_response() -> "tuple[int, tuple]":
    """The fronts' answer when the budget dies MID-handler (an
    outbound hop's `io_timeout` raised — that site already counted the
    exceed, so this helper deliberately does not): the honest status
    is 504, not a generic 500.  Retry-After 1s, as above."""
    body = b'{"error": "deadline exceeded"}'
    return 504, (body, {"Retry-After": "1",
                        "Content-Type": "application/json"})


def _metrics():
    from .. import stats
    return stats.PROCESS
