"""In-memory log page with threshold flush
(weed/util/log_buffer/log_buffer.go).

The reference buffers appended log entries in memory pages and flushes
a page to its sink when it fills or a timer fires, while readers merge
the in-memory tail with flushed storage (log_read.go).  This is that
primitive: `add` accumulates records, an overflowing page invokes
`flush_fn` synchronously (in append order), and `snapshot` exposes the
unflushed tail for merged reads.  The MQ partition log composes it
with its stamp clock and filer-segment sink; the caller provides
locking (both the broker and the reference hold the partition lock
across stamp assignment + buffer append, so the buffer itself stays
lock-free)."""

from __future__ import annotations


class LogBuffer:
    def __init__(self, flush_fn, flush_bytes: int = 256 * 1024):
        """flush_fn(records: list[dict]) -> None — must persist or
        raise; on success the page resets."""
        self.flush_fn = flush_fn
        self.flush_bytes = flush_bytes
        self._recs: list[dict] = []
        self._bytes = 0

    def __len__(self) -> int:
        return len(self._recs)

    @property
    def pending_bytes(self) -> int:
        return self._bytes

    def add(self, rec: dict, nbytes: int) -> None:
        """Append one record (approximate size `nbytes`); flushes the
        page when it crosses the threshold."""
        self._recs.append(rec)
        self._bytes += nbytes
        if self._bytes >= self.flush_bytes:
            self.flush()

    def flush(self) -> None:
        if not self._recs:
            return
        self.flush_fn(self._recs)
        self._recs = []
        self._bytes = 0

    def snapshot(self) -> "list[dict]":
        """The unflushed tail, for merged reads (log_read.go
        ReadFromBuffer role)."""
        return list(self._recs)

    def first(self) -> "dict | None":
        return self._recs[0] if self._recs else None

    def last(self) -> "dict | None":
        return self._recs[-1] if self._recs else None
