"""Request-id propagation (reference: weed/util/request_id — a
context key set by middleware and forwarded on outbound calls as the
`X-Request-ID` header).

A contextvar follows the request across the thread handling it; the
HTTP server sets it from the inbound header (or mints one), the
shared HTTP client helpers attach it to outbound hops, and wlog
appends it to every line — one id traces gateway -> filer -> volume.
Contextvars propagate into `threading.Thread` only via
`contextvars.copy_context()`; the data plane handles each request on
one thread, which is the path that matters.
"""

from __future__ import annotations

import contextvars
import itertools
import os

HEADER = "X-Request-ID"

_request_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "weed_request_id", default="")

# fast minting: ids need process-lifetime uniqueness and log
# greppability, not unpredictability — secrets.token_hex per request
# was a measurable slice of the write-path profile.  12 random hex
# chars pin the process, a C-level counter (atomic under the GIL)
# distinguishes requests.
_RID_PREFIX = os.urandom(6).hex()
_rid_counter = itertools.count(int.from_bytes(os.urandom(2), "big"))


def new_request_id() -> str:
    return f"{_RID_PREFIX}{next(_rid_counter) & 0xFFFFFFFF:04x}"


def get_request_id() -> str:
    return _request_id.get()


def set_request_id(rid: str) -> "contextvars.Token":
    return _request_id.set(rid)


def ensure_request_id(inbound: "str | None") -> str:
    """Adopt the caller's id or mint one (request_id middleware
    semantics: ids are created at the edge and preserved through
    every internal hop)."""
    rid = inbound or new_request_id()
    _request_id.set(rid)
    return rid


def reset_request_id(token) -> None:
    _request_id.reset(token)
