"""Image manipulation on the read path (weed/images/: resizing.go
Resized + orientation.go FixJpgOrientation).

The reference resizes with modes "" (shrink-to-fit preserving aspect),
"fit" (cover+crop to exact box) and "fill" (pad to exact box), and
applies EXIF orientation to JPEGs before serving
(volume_server_handlers_read.go:353 hook).  Implemented over PIL.
"""

from __future__ import annotations

import io


def is_image_mime(mime: str) -> bool:
    return mime.startswith("image/")


def resized(data: bytes, mime: str, width: int, height: int,
            mode: str = "") -> bytes:
    """images/resizing.go:18 Resized.  Returns the original bytes when
    no work applies (not an image, no dims, already small enough)."""
    if (width == 0 and height == 0) or not is_image_mime(mime):
        return data
    try:
        from PIL import Image, ImageOps
        img = Image.open(io.BytesIO(data))
        fmt = img.format or "PNG"  # BEFORE transpose: the transposed
        # copy has format=None, which would re-encode JPEGs as PNG
        # under a Content-Type that still says image/jpeg
        img = ImageOps.exif_transpose(img)  # orientation.go analog
        w0, h0 = img.size
        if not ((width and w0 > width) or (height and h0 > height)):
            return data  # never upscale (resizing.go:26)
        if mode == "fit":
            # exact box, crop overflow (imaging.Fill Center)
            out = ImageOps.fit(img, (width or w0, height or h0))
        elif mode == "fill":
            # exact box, pad (imaging.Fit then letterbox)
            img.thumbnail((width or w0, height or h0))
            out = ImageOps.pad(img, (width or w0, height or h0))
        else:
            if width and height:
                if width == height and w0 != h0:
                    out = ImageOps.fit(img, (width, height))
                else:
                    out = img.resize((width, height))
            else:
                # one dimension: scale preserving aspect
                ratio = (width / w0) if width else (height / h0)
                out = img.resize((max(1, round(w0 * ratio)),
                                  max(1, round(h0 * ratio))))
        buf = io.BytesIO()
        if fmt == "JPEG" and out.mode not in ("RGB", "L"):
            out = out.convert("RGB")
        out.save(buf, format=fmt)
        return buf.getvalue()
    except Exception:  # noqa: BLE001 — malformed image: serve as-is,
        return data    # exactly the reference's fallback behavior
