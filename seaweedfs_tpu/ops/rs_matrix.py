"""Reed-Solomon coding matrices, bit-identical to the reference stack.

Matrix construction mirrors `reed-solomon-erasure`'s `build_matrix`
(reference: seaweed-volume/vendor/reed-solomon-erasure/src/core.rs:430-436)
which is itself wire-compatible with `klauspost/reedsolomon` used by the Go
EC paths (weed/storage/erasure_coding/ec_context.go:35):

    V = vandermonde(total, data) with V[r][c] = exp(r, c)
    G = V @ inv(V[:data, :data])

The top `data` rows of G are the identity, so "encoding" all `total` shards
equals copying the data shards and computing the parity rows; decoding picks
any `data` surviving rows of G and inverts that submatrix.
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """V[r][c] = exp(r, c) (reference matrix.rs:263-276)."""
    out = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            out[r, c] = gf256.gf_exp(r, c)
    return out


def identity(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


def gf_invert_matrix(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination
    (reference matrix.rs gaussian_elim).  Raises ValueError if singular."""
    n, n2 = m.shape
    assert n == n2
    work = np.concatenate([m.copy(), identity(n)], axis=1)
    for r in range(n):
        if work[r, r] == 0:
            # find a row below with a non-zero in this column and swap
            for r_below in range(r + 1, n):
                if work[r_below, r] != 0:
                    work[[r, r_below]] = work[[r_below, r]]
                    break
        if work[r, r] == 0:
            raise ValueError("singular matrix")
        # scale row to make pivot 1
        if work[r, r] != 1:
            scale = gf256.gf_inv(int(work[r, r]))
            work[r] = gf256.gf_mul_vec(scale, work[r])
        # eliminate column r from all other rows
        for r_other in range(n):
            if r_other != r and work[r_other, r] != 0:
                scale = int(work[r_other, r])
                work[r_other] ^= gf256.gf_mul_vec(scale, work[r])
    return work[:, n:].copy()


@functools.lru_cache(maxsize=64)
def _build_matrix_cached(data_shards: int, total_shards: int) -> bytes:
    v = vandermonde(total_shards, data_shards)
    top = v[:data_shards, :data_shards]
    g = gf256.gf_matmul(v, gf_invert_matrix(top))
    return g.tobytes()


def build_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Full [total, data] coding matrix; rows [:data] are identity."""
    if data_shards <= 0 or total_shards <= data_shards:
        raise ValueError("need 0 < data_shards < total_shards")
    if total_shards > 256:
        raise ValueError("too many shards for GF(2^8)")
    g = np.frombuffer(
        _build_matrix_cached(data_shards, total_shards), dtype=np.uint8
    ).reshape(total_shards, data_shards)
    return g.copy()


def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """[parity, data] generator rows used by encode."""
    g = build_matrix(data_shards, data_shards + parity_shards)
    return g[data_shards:].copy()


def decode_matrix(data_shards: int, parity_shards: int,
                  present: "list[bool] | np.ndarray"
                  ) -> "tuple[np.ndarray, list[int]]":
    """Matrix reconstructing ALL data shards from the first `data_shards`
    present shards.

    `present` is a total_shards-length presence mask.  Returns
    (M [data, data], survivor_row_indices) with data = M @ survivors,
    where survivors are the first `data` present shards in index order
    (the reference's reconstruct_internal picks survivors in index order).
    Raises ValueError if fewer than data_shards shards are present.
    """
    present = list(present)
    total = data_shards + parity_shards
    if len(present) != total:
        raise ValueError(
            f"presence mask length {len(present)} != total shards {total}")
    g = build_matrix(data_shards, total)
    rows = [i for i in range(total) if present[i]][:data_shards]
    if len(rows) < data_shards:
        raise ValueError("too few shards present to reconstruct")
    sub = g[rows, :]                      # [data, data]
    return gf_invert_matrix(sub), rows


def reconstruction_matrix(data_shards: int, parity_shards: int,
                          present: "list[bool] | np.ndarray",
                          targets: "list[int]") -> "tuple[np.ndarray, list[int]]":
    """Matrix producing the `targets` shard rows (any indices, data or
    parity) from the first `data_shards` surviving shards.

    Returns (M [len(targets), data], survivor_row_indices)."""
    inv, rows = decode_matrix(data_shards, parity_shards, present)
    total = data_shards + parity_shards
    g = build_matrix(data_shards, total)
    m = gf256.gf_matmul(g[list(targets), :], inv)
    return m, rows


@functools.lru_cache(maxsize=256)
def cached_reconstruction_matrix(data_shards: int, parity_shards: int,
                                 present: "tuple[bool, ...]",
                                 targets: "tuple[int, ...]"
                                 ) -> "tuple[np.ndarray, tuple[int, ...]]":
    """LRU-cached reconstruction matrix keyed on the presence pattern.

    Degraded reads repeat the same loss pattern for every needle on a
    volume; the reference caches the decode matrix for the same reason
    (reed-solomon-erasure core.rs data_decode_matrix_cache)."""
    m, rows = reconstruction_matrix(
        data_shards, parity_shards, list(present), list(targets))
    m.setflags(write=False)
    return m, tuple(rows)
