"""Windowed, double-buffered host->device staging for the encode path
(ROADMAP item 2: the end-to-end multi-chip TPU encode).

The GF kernel sustains 43.5 GB/s/chip but the one-shot ``device_put``
it used to sit behind measured 0.03 GB/s on the tunneled chip and
*serialized* the whole h2d plane against the kernel: nothing computed
while bytes moved, nothing moved while the kernel ran.  This module
replaces that with a staging pipeline in which three planes run
concurrently:

    host buffer N+1 --copy+device_put--> device   (staging thread)
    device window N --kernel----------> parity    (async dispatch)
    device window N-1 --fetch---------> sinks     (consumer thread)

* The batch ([K, W] packed uint32 words — 4 GF bytes per word, see
  ops.rs_jax) is split into COLUMN windows of ~``h2d window MB``
  staged bytes.  GF constant-matrix apply is byte-column-independent,
  so window boundaries never change an output byte.
* A dedicated staging thread copies each window into a REUSED host
  staging buffer (module-level pool — the copy target is stable,
  warm memory, never a fresh multi-MB allocation per window), issues
  ``jax.device_put`` and fences ONLY ITSELF (``block_until_ready`` on
  the staging thread yields an honest per-window h2d wall without
  stalling dispatch or fetch), then dispatches the kernel for that
  window — so window N+1's transfer overlaps window N's kernel.
* In-flight windows are bounded by a semaphore (default 2 = classic
  double buffering); each window's staging buffer is released back to
  the pool only after that window's OUTPUT is on the host — the
  aliasing-safe recycle point on backends where ``device_put`` may
  alias host memory (CPU).
* With more than one visible device the window is placed with
  ``NamedSharding(Mesh(jax.devices(), ("batch",)),
  PartitionSpec(None, "batch"))`` — the packed-words batch axis is
  split across the mesh and the jitted kernel runs SPMD with no
  collectives (the apply is columnwise).  A single-device box (or
  ``SEAWEEDFS_TPU_ENCODE_MESH=0``) falls back to plain placement.

Knobs:
  SEAWEEDFS_TPU_H2D_WINDOW_MB   staged bytes per window (default 32;
                                0 disables windowing -> legacy
                                one-shot device_put)
  SEAWEEDFS_TPU_H2D_INFLIGHT    staged windows in flight (default 2)
  SEAWEEDFS_TPU_ENCODE_MESH     1/0 force mesh sharding on/off
                                (default: on when >1 device)

Telemetry: per-window ``device_note``/``kernel_note`` (profiling.py)
plus a per-launch overlap fraction — 0 when the three planes ran
serially, 1 when the wall equals the slowest single plane — surfaced
as the ``device_h2d_overlap_fraction`` gauge (cluster.top) and a
process-wide aggregate snapshot() the bench JSON records.
"""

from __future__ import annotations

import os
import queue
import threading
import time

import numpy as np

DEFAULT_WINDOW_MB = 32.0
DEFAULT_INFLIGHT = 2


def window_bytes() -> int:
    """Staged bytes per window; 0 disables windowing entirely."""
    raw = os.environ.get("SEAWEEDFS_TPU_H2D_WINDOW_MB", "")
    try:
        mb = float(raw) if raw else DEFAULT_WINDOW_MB
    except ValueError:
        mb = DEFAULT_WINDOW_MB
    return max(0, int(mb * (1 << 20)))


def inflight_depth() -> int:
    try:
        d = int(os.environ.get("SEAWEEDFS_TPU_H2D_INFLIGHT",
                               str(DEFAULT_INFLIGHT)))
    except ValueError:
        d = DEFAULT_INFLIGHT
    return max(1, d)


def mesh_enabled() -> bool:
    return os.environ.get("SEAWEEDFS_TPU_ENCODE_MESH", "") != "0"


_shardings_lock = threading.Lock()
_shardings_cache: "dict[tuple, tuple]" = {}


def encode_shardings() -> "tuple[object | None, object | None, int]":
    """(batch_sharding, replicated_sharding, n_devices) for mesh
    placement of [K, W] windows, or (None, None, 1) on the
    single-device fallback (``len(jax.devices()) == 1`` or the mesh
    knob off).  batch_sharding splits axis 1 (the packed-words batch
    axis) across every device; replicated_sharding is for the small
    constant matrix.  Cached: the device set never changes in-process.
    """
    import jax
    devs = jax.devices()
    key = (len(devs), mesh_enabled())
    if len(devs) == 1 or not mesh_enabled():
        return None, None, 1
    with _shardings_lock:
        hit = _shardings_cache.get(key)
        if hit is not None:
            return hit
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        mesh = Mesh(np.asarray(devs), ("batch",))
        out = (NamedSharding(mesh, PartitionSpec(None, "batch")),
               NamedSharding(mesh, PartitionSpec()), len(devs))
        _shardings_cache[key] = out
        return out


def plan_windows(k: int, w: int, ndev: int
                 ) -> "list[tuple[int, int, int]]":
    """Column-window schedule over a [k, w] packed-words batch:
    [(w0, real_words, padded_words)] tiling [0, w) in order.  Window
    width targets ``window_bytes()`` total staged bytes; padded_words
    rounds the (possibly short tail) window up to a multiple of ndev
    so the batch axis always divides the mesh."""
    wb = window_bytes()
    if wb <= 0 or w == 0:
        return []
    win = max(1, wb // (4 * max(k, 1)))
    win = -(-win // ndev) * ndev
    out = []
    pos = 0
    while pos < w:
        n = min(win, w - pos)
        out.append((pos, n, -(-n // ndev) * ndev))
        pos += n
    return out


# -- reused host staging buffers ------------------------------------------

_pool_lock = threading.Lock()
_buf_pool: "list[np.ndarray]" = []
_POOL_CAP_BUFS = 8
_POOL_CAP_BYTES = 256 << 20


def _take_buf(shape: "tuple[int, int]") -> np.ndarray:
    with _pool_lock:
        for i, b in enumerate(_buf_pool):
            if b.shape == shape:
                return _buf_pool.pop(i)
    return np.empty(shape, dtype=np.uint32)


def _give_buf(buf: np.ndarray) -> None:
    """Return a staging buffer to the pool, bounded GLOBALLY (count
    and bytes) with FIFO eviction — tail-window shapes vary per
    volume, so a per-shape cap alone would grow RSS without bound in
    a long-lived EC worker.  Recently returned buffers are the likely
    active shape; the oldest entries are the stale shapes to drop."""
    with _pool_lock:
        _buf_pool.append(buf)
        total = sum(b.nbytes for b in _buf_pool)
        while _buf_pool and (len(_buf_pool) > _POOL_CAP_BUFS or
                             total > _POOL_CAP_BYTES):
            total -= _buf_pool.pop(0).nbytes


# -- per-process staging accounting ---------------------------------------

class StagingStats:
    """One launch's staging ledger (a launch = one parity_lazy /
    apply_matrix_lazy batch)."""

    __slots__ = ("windows", "h2d_bytes", "h2d_seconds", "d2h_bytes",
                 "d2h_seconds", "start", "end", "overlap_fraction",
                 "overlap_numer", "overlap_denom")

    def __init__(self):
        self.windows = 0
        self.h2d_bytes = 0
        self.h2d_seconds = 0.0
        self.d2h_bytes = 0
        self.d2h_seconds = 0.0
        self.start = 0.0
        self.end = 0.0
        self.overlap_fraction = 0.0
        self.overlap_numer = 0.0
        self.overlap_denom = 0.0

    def finish(self) -> None:
        """Compute the overlap fraction: 0 = the h2d plane and the
        consume plane (kernel remainder + d2h fetch — the only fence
        async backends offer is the host-side fetch) ran strictly
        serially (wall == sum of both), 1 = fully overlapped (wall ==
        the slower plane alone).  numer/denom are kept so the process
        aggregate can weight launches without re-deriving the math."""
        wall = self.end - self.start
        busy = self.h2d_seconds + self.d2h_seconds
        headroom = busy - max(self.h2d_seconds, self.d2h_seconds)
        if headroom > 1e-9:
            self.overlap_numer = max(0.0, min(busy - wall, headroom))
            self.overlap_denom = headroom
            self.overlap_fraction = self.overlap_numer / headroom
        else:
            self.overlap_numer = self.overlap_denom = 0.0
            self.overlap_fraction = 0.0


_agg_lock = threading.Lock()
_agg = {"launches": 0, "windows": 0, "h2d_bytes": 0,
        "h2d_seconds": 0.0, "d2h_bytes": 0, "d2h_seconds": 0.0,
        "overlap_numer": 0.0, "overlap_denom": 0.0}


def reset_aggregate() -> None:
    with _agg_lock:
        for k in _agg:
            _agg[k] = 0 if isinstance(_agg[k], int) else 0.0


def _note_launch(s: StagingStats) -> None:
    """Fold one finish()ed launch into the process aggregate (the
    overlap numer/denom come from finish() — one definition)."""
    with _agg_lock:
        _agg["launches"] += 1
        _agg["windows"] += s.windows
        _agg["h2d_bytes"] += s.h2d_bytes
        _agg["h2d_seconds"] += s.h2d_seconds
        _agg["d2h_bytes"] += s.d2h_bytes
        _agg["d2h_seconds"] += s.d2h_seconds
        _agg["overlap_numer"] += s.overlap_numer
        _agg["overlap_denom"] += s.overlap_denom


def snapshot() -> dict:
    """Process-wide aggregate across every windowed launch since the
    last reset_aggregate() — what the bench records next to the e2e
    number (windows staged, achieved staged-h2d GB/s, byte-weighted
    overlap fraction)."""
    with _agg_lock:
        a = dict(_agg)
    a["h2d_gbps"] = round(
        a["h2d_bytes"] / a["h2d_seconds"] / 1e9, 3) \
        if a["h2d_seconds"] > 0 else 0.0
    a["d2h_gbps"] = round(
        a["d2h_bytes"] / a["d2h_seconds"] / 1e9, 3) \
        if a["d2h_seconds"] > 0 else 0.0
    a["overlap_fraction"] = round(
        a["overlap_numer"] / a["overlap_denom"], 3) \
        if a["overlap_denom"] > 0 else 0.0
    return a


# -- the windowed launch ---------------------------------------------------

class _StagingError(Exception):
    """Internal: the launch was aborted before all windows staged."""


class _Stager:
    """The staging thread's whole world: plan, input batch, queues,
    stats.  Deliberately a SEPARATE object from the consumer-facing
    WindowedLaunch so the running thread holds no reference to the
    handle — a handle dropped unconsumed (pipeline unwind) becomes
    garbage, its weakref.finalize fires, and the parked thread exits
    on its next 0.2s tick instead of leaking forever (a thread whose
    target is a bound method of the handle would pin the handle alive
    and the finalizer/__del__ could never run)."""

    def __init__(self, mat, flat32: np.ndarray, kernel, sharding):
        self.mat = mat
        self.flat = flat32
        self.kernel = kernel
        self.sharding = sharding
        self.slots = threading.Semaphore(inflight_depth())
        self.ready: "queue.Queue" = queue.Queue()
        self.stop = threading.Event()
        self.errors: "list[BaseException]" = []
        self.stats = StagingStats()
        self.stats.start = time.perf_counter()

    def run(self, plan) -> None:
        import jax

        from .. import profiling
        k = self.flat.shape[0]
        try:
            for (w0, n, npad) in plan:
                while not self.slots.acquire(timeout=0.2):
                    if self.stop.is_set():
                        raise _StagingError()
                buf = _take_buf((k, npad))
                t0 = time.perf_counter()
                np.copyto(buf[:, :n], self.flat[:, w0:w0 + n])
                # pad columns (mesh divisibility) are left dirty on
                # purpose: the GF apply is column-independent and the
                # consumer slices them off, so stale pool bytes can
                # never reach an output byte.
                dev = jax.device_put(buf, self.sharding) \
                    if self.sharding is not None else \
                    jax.device_put(buf)
                dev.block_until_ready()
                dt = time.perf_counter() - t0
                self.stats.windows += 1
                self.stats.h2d_bytes += buf.nbytes
                self.stats.h2d_seconds += dt
                profiling.device_note("h2d", buf.nbytes, dt)
                t_dispatch = time.perf_counter()
                out = self.kernel(self.mat, dev)
                self.ready.put((w0, n, out, buf, t_dispatch))
        except _StagingError:
            pass
        except BaseException as e:  # noqa: BLE001 — re-raised by the
            self.errors.append(e)   # consumer
        finally:
            self.ready.put(None)


class WindowedLaunch:
    """One double-buffered staged kernel launch over a [K, W] packed
    batch.

    ``kernel(mat_dev, window_dev) -> out32`` is dispatched per window
    by the staging thread as soon as that window's transfer fences, so
    dispatch is never gated on the consumer.  ``windows()`` yields
    ``(byte0, uint8[rows, real_bytes])`` in order; the fetch of window
    k overlaps the staging of k+1 and k+2 (depth permitting).

    Aliasing contract (same as rs_jax.*_lazy): the caller may recycle
    ``flat32`` only after the final window is consumed — windows() /
    materialize() returning implies every host->device copy is done.
    """

    def __init__(self, mat, flat32: np.ndarray, kernel, out_rows: int,
                 nbytes: int, op: str = "encode"):
        import weakref
        batch_sh, repl_sh, ndev = encode_shardings()
        k, w = flat32.shape
        self._rows = out_rows
        self._nbytes = nbytes
        self._op = op  # telemetry label: "encode" vs "rebuild"
        self._consumed = False
        if repl_sh is not None:
            # the constant matrix must be REPLICATED across the mesh:
            # a single-device-committed mat + a mesh-sharded window
            # would be "incompatible devices" to jit
            import jax
            mat = jax.device_put(np.asarray(mat), repl_sh)
        self._s = _Stager(mat, flat32, kernel, batch_sh)
        # dropped-handle backstop: stop the stager when the handle is
        # collected (the thread itself only references the _Stager)
        weakref.finalize(self, self._s.stop.set)
        self._t = threading.Thread(target=self._s.run,
                                   args=(plan_windows(k, w, ndev),),
                                   daemon=True, name="h2d-stager")
        self._t.start()

    @property
    def stats(self) -> StagingStats:
        return self._s.stats

    def windows(self):
        """Yield (byte0, uint8[rows, real_bytes]) in launch order.
        Always drains fully (a partial drain would recycle staging
        buffers the stager still reads); raises the stager's error
        after the drain if it died."""
        from .. import profiling
        if self._consumed:
            raise RuntimeError("WindowedLaunch consumed twice")
        self._consumed = True
        s = self._s
        try:
            while True:
                item = s.ready.get()
                if item is None:
                    break
                w0, n, out, buf, t_dispatch = item
                t0 = time.perf_counter()
                host = np.asarray(out)  # the backend's only fence:
                # waits out any kernel remainder + the d2h transfer
                dt = time.perf_counter() - t0
                _give_buf(buf)
                s.slots.release()
                s.stats.d2h_bytes += host.nbytes
                s.stats.d2h_seconds += dt
                profiling.device_note("d2h", host.nbytes, dt)
                profiling.kernel_note("gf_apply_matrix",
                                      t0 + dt - t_dispatch,
                                      host.nbytes)
                byte0 = 4 * w0
                real = min(self._nbytes - byte0, 4 * n)
                yield byte0, host.view(np.uint8).reshape(
                    self._rows, -1)[:, :real]
            if s.errors:
                raise s.errors[0]
            s.stats.end = time.perf_counter()
            s.stats.finish()
            profiling.overlap_note(s.stats.overlap_fraction,
                                   s.stats.windows, op=self._op)
            _note_launch(s.stats)
        finally:
            s.stop.set()

    def materialize(self) -> np.ndarray:
        """Drain every window into one [rows, nbytes] uint8 array."""
        out = np.empty((self._rows, self._nbytes), dtype=np.uint8)
        for byte0, chunk in self.windows():
            out[:, byte0:byte0 + chunk.shape[1]] = chunk
        return out

    def abort(self) -> None:
        """Stop the stager promptly (error unwind path); the parked
        thread exits on its next timeout tick."""
        self._s.stop.set()
