"""Native (C++/AVX2) Reed-Solomon codec — the latency-path engine.

Same API as ReedSolomonCPU/ReedSolomonJax; the GF math runs in
seaweedfs_tpu/native/gf_rs.cc (our klauspost-equivalent).  Use
`available()` before constructing; callers fall back to the numpy twin.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .. import native
from . import rs_matrix


def available() -> bool:
    return native.available()


def _row_ptrs(arr2d: np.ndarray) -> "ctypes.Array":
    n = arr2d.shape[0]
    ptrs = (ctypes.c_void_p * n)()
    base = arr2d.ctypes.data
    stride = arr2d.strides[0]
    for i in range(n):
        ptrs[i] = base + i * stride
    return ptrs


class ReedSolomonNative:
    def __init__(self, data_shards: int, parity_shards: int):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native GF library unavailable")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = rs_matrix.build_matrix(data_shards,
                                             self.total_shards)
        self.parity_rows = np.ascontiguousarray(
            self.matrix[data_shards:])

    def _apply(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        data = np.ascontiguousarray(data, dtype=np.uint8)
        r, k = mat.shape
        assert data.shape[0] == k
        # accumulate=0: the kernel overwrites, so np.empty avoids a
        # full zero-fill pass over the output rows
        out = np.empty((r, data.shape[1]), dtype=np.uint8)
        self._lib.gf_matrix_apply(
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            r, k, _row_ptrs(data), _row_ptrs(out), data.shape[1], 0)
        return out

    # -- API-compatible surface (see rs_cpu.ReedSolomonCPU) --------------

    def apply_matrix(self, mat: np.ndarray, data: np.ndarray
                     ) -> np.ndarray:
        """out[r] = XOR_k mat[r,k] * data[k] — public generic apply, the
        primitive the staged rebuild pipeline drives directly."""
        return self._apply(mat, data)

    def parity(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        if data.ndim != 2 or data.shape[0] != self.data_shards:
            raise ValueError(f"expected [{self.data_shards}, B], "
                             f"got {data.shape}")
        return self._apply(self.parity_rows, data)

    def encode(self, shards: np.ndarray) -> np.ndarray:
        shards = np.asarray(shards, dtype=np.uint8)
        out = shards.copy()
        out[self.data_shards:] = self.parity(
            shards[: self.data_shards])
        return out

    def verify(self, shards: np.ndarray) -> bool:
        shards = np.asarray(shards, dtype=np.uint8)
        return bool(np.array_equal(
            self.parity(shards[: self.data_shards]),
            shards[self.data_shards:]))

    def reconstruct(self, shards: np.ndarray, present,
                    data_only: bool = False) -> np.ndarray:
        shards = np.asarray(shards, dtype=np.uint8)
        present = list(present)
        missing_data = [i for i in range(self.data_shards)
                        if not present[i]]
        missing_parity = [i for i in
                          range(self.data_shards, self.total_shards)
                          if not present[i]]
        out = shards.copy()
        if missing_data:
            m, rows = rs_matrix.cached_reconstruction_matrix(
                self.data_shards, self.parity_shards, tuple(present),
                tuple(missing_data))
            out[missing_data] = self._apply(m, shards[list(rows)])
        if missing_parity and not data_only:
            sel = self.parity_rows[
                [i - self.data_shards for i in missing_parity]]
            out[missing_parity] = self._apply(
                sel, out[: self.data_shards])
        return out
