"""JAX/TPU Reed-Solomon kernels: GF(2^8) constant-matrix apply as an
XOR network over bit-planes.

This is the TPU-native re-expression of the reference's hot loop
(weed/storage/erasure_coding/ec_encoder.go:265 enc.Encode,
:360 enc.Reconstruct, weed/storage/store_ec.go:435 ReconstructData —
klauspost/reedsolomon SIMD on CPU).

Math: GF(2^8) multiplication by a constant c is GF(2)-linear over the
bits of the input byte:  c*x = XOR_b [bit_b(x) ? c*(2^b) : 0].
So a parity row  out[r] = XOR_k mat[r,k] * data[k]  becomes a fused
select/XOR network with 8*K terms per output row — pure integer VPU work,
bit-exact on every backend (CPU tests == TPU production), and entirely
fusible by XLA into a single HBM-bandwidth-bound elementwise kernel.
No bf16/MXU is used for the GF math itself: exactness is mandatory
(bit-identical shards vs the CPU reference path).

All public entry points accept/return uint8 arrays; the constant matrix is
a *traced* argument so one compiled kernel serves every (d, p) scheme and
every reconstruction pattern of the same shape.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256, rs_matrix


def _windowed_wanted(flat: np.ndarray) -> bool:
    """Take the windowed double-buffered staging path (ops.staging)?
    Yes whenever windowing is enabled AND either the batch spans more
    than one window (there is something to pipeline) or a device mesh
    is up (mesh placement always rides the launch).  A one-window
    single-device batch gains nothing from a staging thread, so it
    keeps the legacy one-shot device_put."""
    from . import staging
    wb = staging.window_bytes()
    if wb <= 0:
        return False
    _batch_sh, _repl_sh, ndev = staging.encode_shardings()
    return ndev > 1 or flat.nbytes > wb


def _staged_h2d(flat: np.ndarray) -> jax.Array:
    """Stage a packed host buffer onto the default device and record
    the h2d window (profiling.device_note).  Fencing policy matters:
    on the CPU backend device_put is effectively a synchronous copy,
    so blocking costs nothing and yields an honest window.  On async
    backends (TPU) a fence here would serialize the transfer against
    the compute thread's next-window prep — exactly the overlap the
    lazy-parity pipeline exists to provide — so there we record bytes
    only and let the transfer wall fold into the dispatch->fetch
    kernel window that _PendingParity.materialize times (the host-side
    fetch is the only fence that backend offers anyway)."""
    from .. import profiling
    t0 = time.perf_counter()
    dev = jax.device_put(flat)
    if jax.default_backend() == "cpu":
        dev.block_until_ready()
        profiling.device_note("h2d", flat.nbytes,
                              time.perf_counter() - t0)
    else:
        profiling.device_note("h2d", flat.nbytes, None)
    return dev

def _expand_tables(mat: jax.Array) -> jax.Array:
    """[R, K] constant matrix -> [R, K, 8] per-bit multiply tables.

    MUL_BY_POW2 ([256, 8] uint8: c * 2^b in GF(2^8)) is embedded as a
    trace-time constant rather than a module-level device array: a
    module-level device_put would initialize the default JAX backend
    at IMPORT time — on a box whose tunneled-TPU platform is wedged,
    merely importing this module would hang even for callers that then
    pin the CPU platform (graft dryrun, tests)."""
    return jnp.asarray(gf256.MUL_BY_POW2)[mat]


def expand_tables_u32(mat: jax.Array) -> jax.Array:
    """[R, K] constant matrix -> [R, K, 8] uint32 per-bit multiply tables
    (the form `_packed_xor_network` consumes); shared by every caller so
    the table layout has a single definition."""
    return _expand_tables(mat).astype(jnp.uint32)


def _packed_xor_network(tables: jax.Array, data32: jax.Array) -> jax.Array:
    """Packed-word GF constant-matrix apply.

    tables: [R, K, 8] uint32 per-bit multiply constants (< 256)
    data32: [K, W] uint32 — 4 data bytes per word
    returns [R, W] uint32.

    Per word: mask = (d >> b) & 0x01010101 isolates bit b of each of the 4
    bytes in place; mask * c multiplies each byte by the constant without
    cross-byte carries (products are < 256).  4x fewer VPU lane-ops than a
    per-byte formulation.  Byte order inside the word cancels out between
    pack and unpack, so results are platform-independent.
    """
    r, k = tables.shape[0], tables.shape[1]
    lane_mask = jnp.uint32(0x01010101)
    accs = [jnp.zeros_like(data32[0]) for _ in range(r)]
    for ki in range(k):
        d = data32[ki]
        for b in range(8):
            mask = (d >> jnp.uint32(b)) & lane_mask
            for ri in range(r):
                accs[ri] = accs[ri] ^ (mask * tables[ri, ki, b])
    return jnp.stack(accs)


@jax.jit
def gf_apply_matrix_words(mat: jax.Array, data32: jax.Array) -> jax.Array:
    """Fast path: mat [R, K] uint8 (traced), data32 [K, W] uint32 (4 GF
    bytes per word) -> [R, W] uint32.

    This is the production entry point for bulk encode/rebuild: callers
    keep shard buffers as uint32 words (a free numpy `.view` on the host)
    so no uint8 relayout ever happens on device.  Eager uint8 reshapes of
    multi-GB arrays were observed to pad 12.8x on TPU (layout {0,1}
    T(8,128)(4,1)) and OOM — words in, words out avoids the entire issue.
    """
    tables = expand_tables_u32(mat)
    return _packed_xor_network(tables, data32)


def pack_words(data: np.ndarray, multiple: int = 4) -> np.ndarray:
    """Host-side [K, B] uint8 -> [K, ceil(B/4)] uint32 (pads B up to
    `multiple` bytes; multiple must itself be a multiple of 4)."""
    assert multiple % 4 == 0
    data = np.ascontiguousarray(data)
    k, b = data.shape
    pad = (-b) % multiple
    if pad:
        data = np.pad(data, ((0, 0), (0, pad)))
    return data.view(np.uint32)


def unpack_words(data32: np.ndarray, b: int) -> np.ndarray:
    """Host-side [R, W] uint32 -> [R, b] uint8."""
    return np.ascontiguousarray(data32).view(np.uint8)[:, :b]


def gf_apply_matrix(mat, data) -> jax.Array:
    """out[r] = XOR_k mat[r,k] * data[k] over GF(2^8).

    mat: [R, K] uint8 (traced; any coding/decoding matrix)
    data: [K, B] uint8 (B is padded to a word multiple internally)
    returns [R, B]: numpy uint8 for numpy input (host word-packing fast
    path, no device relayout or re-upload), device uint8 otherwise.

    Convenience byte-in/byte-out wrapper; for multi-GB streams prefer
    gf_apply_matrix_words with host-packed uint32 buffers.
    """
    mat = jnp.asarray(mat, dtype=jnp.uint8)
    k = data.shape[0]
    batch_shape = data.shape[1:]
    if isinstance(data, np.ndarray):
        flat = pack_words(data.reshape(k, -1).astype(np.uint8, copy=False))
        b = int(np.prod(batch_shape))
        out32 = gf_apply_matrix_words(mat, jnp.asarray(flat))
        out = unpack_words(np.asarray(out32), b)
        return out.reshape((mat.shape[0],) + batch_shape)
    data = jnp.asarray(data, dtype=jnp.uint8)
    flat = data.reshape(k, -1)
    b = flat.shape[1]
    pad = (-b) % 4
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    flat32 = jax.lax.bitcast_convert_type(
        flat.reshape(k, (b + pad) // 4, 4), jnp.uint32)
    out32 = gf_apply_matrix_words(mat, flat32)
    out = jax.lax.bitcast_convert_type(out32, jnp.uint8).reshape(
        mat.shape[0], -1)
    if pad:
        out = out[:, :b]
    return out.reshape((mat.shape[0],) + batch_shape)


class _PendingParity:
    """An in-flight device parity launch (see ReedSolomonJax.parity_lazy)."""

    def __init__(self, out32: jax.Array, nbytes: int,
                 dispatched_at: float = 0.0):
        self._out32 = out32
        self._nbytes = nbytes
        self._dispatched_at = dispatched_at

    def materialize(self) -> np.ndarray:
        """Block until the launch completes; returns uint8 [R, B].

        Device telemetry (profiling.py): the fetch wall is the d2h
        staging window the pipeline's writer thread actually waits on
        (it includes any remaining kernel time — the only fence this
        backend offers is the host-side fetch), and dispatch->fetch
        is the per-launch kernel wall `cluster.top` shows as
        device_kernel_last_ms."""
        import time as _time
        from .. import profiling
        t0 = _time.perf_counter()
        host = np.asarray(self._out32)
        fetch = _time.perf_counter() - t0
        out = unpack_words(host, self._nbytes)
        profiling.device_note("d2h", host.nbytes, fetch)
        if self._dispatched_at:
            profiling.kernel_note(
                "gf_apply_matrix", t0 + fetch - self._dispatched_at,
                host.nbytes)
        return out


class ReedSolomonJax:
    """TPU encoder/decoder for RS(data, parity), API-compatible with the
    CPU twin (`rs_cpu.ReedSolomonCPU`)."""

    def __init__(self, data_shards: int, parity_shards: int):
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = rs_matrix.build_matrix(data_shards, self.total_shards)
        self._parity_rows = jnp.asarray(self.matrix[data_shards:])

    def _check(self, arr, rows: int):
        """Validate without converting: numpy stays numpy so the host
        word-packing fast path in gf_apply_matrix is taken (device-side
        eager uint8 relayout of huge arrays pads 12.8x and OOMs)."""
        if not hasattr(arr, "dtype"):
            arr = np.asarray(arr, dtype=np.uint8)
        if arr.dtype != np.uint8:
            raise TypeError(f"shards must be uint8, got {arr.dtype}")
        if arr.ndim != 2 or arr.shape[0] != rows:
            raise ValueError(
                f"expected [{rows}, B] shard array, got {arr.shape}")
        return arr

    # -- encode ------------------------------------------------------------

    def parity(self, data) -> jax.Array:
        """data: [data_shards, B] uint8 -> parity [parity_shards, B]."""
        data = self._check(data, self.data_shards)
        return gf_apply_matrix(self._parity_rows, data)

    def parity_lazy(self, data) -> "_PendingParity":
        """Dispatch the parity launch WITHOUT waiting for the result.

        Returns a handle whose .materialize() blocks on the device and
        yields the [parity_shards, B] uint8 numpy array.  This lets a
        pipeline overlap the D2H fetch of launch k with the H2D+kernel
        of launch k+1 (the encode staging pipeline materializes in its
        writer thread while the compute thread dispatches ahead).

        Aliasing contract: `data` may be a recycled buffer, but only
        AFTER materialize() returns — on backends where jnp.asarray
        aliases host memory (CPU), the kernel has consumed the input by
        the time the output is fetchable.

        The default path is the windowed double-buffered staging
        pipeline (ops.staging): the batch is split into column
        windows, a staging thread overlaps window N+1's h2d with
        window N's kernel, and the handle additionally exposes
        .windows() so the encode writer can push each parity window to
        its shard sink while later windows are still in flight.
        SEAWEEDFS_TPU_H2D_WINDOW_MB=0 restores the one-shot
        device_put.
        """
        data = self._check(data, self.data_shards)
        b = data.shape[1]
        flat = pack_words(np.ascontiguousarray(data))
        if _windowed_wanted(flat):
            from . import staging
            return staging.WindowedLaunch(
                self._parity_rows, flat, gf_apply_matrix_words,
                self.parity_shards, b)
        dev = _staged_h2d(flat)
        t_dispatch = time.perf_counter()
        out32 = gf_apply_matrix_words(self._parity_rows, dev)
        return _PendingParity(out32, b, dispatched_at=t_dispatch)

    def apply_matrix(self, mat, data) -> np.ndarray:
        """out[r] = XOR_k mat[r,k] * data[k] — public generic apply
        (numpy in, numpy out via the host word-packing fast path)."""
        return gf_apply_matrix(jnp.asarray(mat, dtype=jnp.uint8), data)

    def apply_matrix_lazy(self, mat, data) -> "_PendingParity":
        """Async generic apply: dispatch without waiting (same contract
        as parity_lazy) so a staged pipeline can overlap D2H of launch k
        with H2D+kernel of k+1; windowed/mesh-staged exactly like
        parity_lazy."""
        data = np.ascontiguousarray(data)
        b = data.shape[1]
        flat = pack_words(data)
        if _windowed_wanted(flat):
            from . import staging
            return staging.WindowedLaunch(
                np.asarray(mat, dtype=np.uint8), flat,
                gf_apply_matrix_words, len(mat), b, op="rebuild")
        dev = _staged_h2d(flat)
        t_dispatch = time.perf_counter()
        out32 = gf_apply_matrix_words(
            jnp.asarray(mat, dtype=jnp.uint8), dev)
        return _PendingParity(out32, b, dispatched_at=t_dispatch)

    def encode(self, shards) -> jax.Array:
        """shards: [total, B] with data rows filled; returns full array with
        parity rows computed."""
        shards = self._check(shards, self.total_shards)
        par = gf_apply_matrix(self._parity_rows, shards[: self.data_shards])
        return jnp.concatenate([shards[: self.data_shards], par], axis=0)

    def verify(self, shards) -> bool:
        shards = self._check(shards, self.total_shards)
        par = gf_apply_matrix(self._parity_rows, shards[: self.data_shards])
        return bool(jnp.array_equal(par, shards[self.data_shards:]))

    # -- reconstruct -------------------------------------------------------

    def reconstruct_onto(self, survivors, survivor_indices, present,
                         targets) -> jax.Array:
        """Compute shard rows `targets` from surviving shards.

        survivors: [data_shards, B] uint8 shard rows, in the order named by
        survivor_indices.  survivor_indices must be the first `data_shards`
        present shard ids in ascending index order (the order the decode
        matrix is built for); anything else raises rather than silently
        producing corrupt output.
        present: total-length bool mask. targets: list of shard ids to
        produce (data and/or parity).
        """
        m, rows = rs_matrix.cached_reconstruction_matrix(
            self.data_shards, self.parity_shards,
            tuple(bool(x) for x in present), tuple(int(t) for t in targets))
        if tuple(int(i) for i in survivor_indices) != rows:
            raise ValueError(
                f"survivors must be shards {list(rows)} in that order, "
                f"got {list(survivor_indices)}")
        survivors = self._check(survivors, self.data_shards)
        return gf_apply_matrix(jnp.asarray(m), survivors)

    def reconstruct(self, shards, present, data_only: bool = False
                    ) -> np.ndarray:
        """Fill missing rows of `shards` (host array in, host array out);
        mirrors rs_cpu.ReedSolomonCPU.reconstruct."""
        shards = np.asarray(shards, dtype=np.uint8)
        present = [bool(x) for x in present]
        if shards.shape[0] != self.total_shards or \
                len(present) != self.total_shards:
            raise ValueError("bad shard array / presence mask")
        survivor_rows = [i for i in range(self.total_shards) if present[i]]
        if len(survivor_rows) < self.data_shards:
            raise ValueError("too few shards present to reconstruct")
        survivor_rows = survivor_rows[: self.data_shards]
        targets = [i for i in range(self.total_shards) if not present[i]]
        if data_only:
            targets = [i for i in targets if i < self.data_shards]
        if not targets:
            return shards.copy()
        rec = self.reconstruct_onto(
            shards[survivor_rows], survivor_rows, present, targets)
        out = shards.copy()
        out[targets] = np.asarray(rec)
        return out
