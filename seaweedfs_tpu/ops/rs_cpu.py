"""CPU (numpy) Reed-Solomon twin of the TPU kernels.

Serves three roles, mirroring how the reference keeps a CPU path everywhere
(klauspost/reedsolomon in Go, reed-solomon-erasure in Rust):
  * golden reference for bit-identity tests of the JAX/TPU kernels,
  * the latency path for small degraded reads (weed/storage/store_ec.go:366
    reconstructs single needles on the fly — batch TPU economics don't fit),
  * fallback when no accelerator is present.
"""

from __future__ import annotations

import numpy as np

from . import gf256, rs_matrix


class ReedSolomonCPU:
    """Encoder/decoder for RS(data, parity) over GF(2^8), numpy-based.

    API mirrors the reference encoder surface used by the EC pipeline
    (ec_encoder.go:265 Encode, :360 Reconstruct, store_ec.go:435
    ReconstructData) with shards as uint8 arrays of equal length.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = rs_matrix.build_matrix(data_shards, self.total_shards)
        self.parity_rows = self.matrix[data_shards:].copy()

    # -- encode ------------------------------------------------------------

    def _check_shards(self, shards: np.ndarray, rows: int) -> np.ndarray:
        shards = np.asarray(shards)
        if shards.dtype != np.uint8:
            raise TypeError(f"shards must be uint8, got {shards.dtype}")
        if shards.ndim != 2 or shards.shape[0] != rows:
            raise ValueError(
                f"expected [{rows}, B] shard array, got {shards.shape}")
        return shards

    def encode(self, shards: np.ndarray) -> np.ndarray:
        """shards: [total, B] uint8 with data in rows [:data]; returns a new
        array with parity rows filled in."""
        shards = self._check_shards(shards, self.total_shards)
        out = shards.copy()
        out[self.data_shards:] = gf256.gf_apply_matrix(
            self.parity_rows, shards[: self.data_shards])
        return out

    def parity(self, data: np.ndarray) -> np.ndarray:
        """data: [data, B] -> parity [parity, B]."""
        data = self._check_shards(data, self.data_shards)
        return gf256.gf_apply_matrix(self.parity_rows, data)

    def apply_matrix(self, mat: np.ndarray, data: np.ndarray
                     ) -> np.ndarray:
        """out[r] = XOR_k mat[r,k] * data[k] — public generic apply, the
        primitive the staged rebuild pipeline drives directly."""
        return gf256.gf_apply_matrix(mat, data)

    # -- verify ------------------------------------------------------------

    def verify(self, shards: np.ndarray) -> bool:
        shards = self._check_shards(shards, self.total_shards)
        expected = self.parity(shards[: self.data_shards])
        return bool(np.array_equal(expected, shards[self.data_shards:]))

    # -- reconstruct -------------------------------------------------------

    def reconstruct(self, shards: np.ndarray, present, data_only: bool = False
                    ) -> np.ndarray:
        """Fill missing rows of `shards` given presence mask `present`.

        shards: [total, B]; rows where present[i] is False are ignored on
        input and overwritten on output.  data_only mirrors the reference's
        ReconstructData (store_ec.go:435): parity rows are left untouched.
        """
        shards = self._check_shards(shards, self.total_shards)
        present = list(present)
        if len(present) != self.total_shards:
            raise ValueError("presence mask length must equal total shards")
        missing_data = [i for i in range(self.data_shards) if not present[i]]
        missing_parity = [i for i in range(self.data_shards, self.total_shards)
                          if not present[i]]
        out = shards.copy()
        if missing_data:
            m, rows = rs_matrix.cached_reconstruction_matrix(
                self.data_shards, self.parity_shards, tuple(present),
                tuple(missing_data))
            survivors = shards[list(rows)]
            out[missing_data] = gf256.gf_apply_matrix(m, survivors)
        if missing_parity and not data_only:
            rows_needed = self.parity_rows[
                [i - self.data_shards for i in missing_parity]]
            out[missing_parity] = gf256.gf_apply_matrix(
                rows_needed, out[: self.data_shards])
        return out
