"""Compute kernels: GF(2^8) field math and Reed-Solomon codecs.

`gf256` / `rs_matrix` are the exact-math foundation (numpy, tiny);
`rs_cpu` is the CPU twin used for golden tests and latency-path reads.
"""
