"""Pallas TPU kernel for the GF(2^8) constant-matrix apply (EC hot loop).

Same math as `rs_jax._packed_xor_network` (packed uint32 bit-select XOR
network) but with explicit VMEM tiling so the whole accumulation chain
stays on-chip: one HBM read of the data tile, one HBM write of the output
tile, all 8*K*R select/mul/XOR terms fused in VMEM.  This is the TPU
equivalent of the reference's SIMD assembly in klauspost/reedsolomon
(invoked at weed/storage/erasure_coding/ec_encoder.go:265).

The coding matrix rides in SMEM as scalars, so ONE compiled kernel serves
every coding/decoding matrix of the same [R, K] shape — encode, decode,
and every rebuild loss-pattern reuse the same binary.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf256

# Words (uint32) per grid step along the stream axis. 8192 words = 32KiB
# per shard row per tile; with RS(10,4) that is ~448KiB of VMEM live per
# step — small enough to double-buffer comfortably in 16MiB VMEM.
TILE_WORDS = 8192


def _rs_kernel(tab_ref, data_ref, out_ref, *, r: int, k: int):
    """data_ref: [K, S, 128] uint32 tile; out_ref: [R, S, 128] uint32;
    tab_ref: [R*K*8] uint32 in SMEM."""
    lane_mask = jnp.uint32(0x01010101)
    accs = [jnp.zeros(data_ref.shape[1:], dtype=jnp.uint32)
            for _ in range(r)]
    for ki in range(k):
        d = data_ref[ki]
        for b in range(8):
            mask = (d >> jnp.uint32(b)) & lane_mask
            for ri in range(r):
                c = tab_ref[(ri * k + ki) * 8 + b]
                accs[ri] = accs[ri] ^ (mask * c)
    for ri in range(r):
        out_ref[ri] = accs[ri]


@functools.partial(jax.jit, static_argnames=("interpret",))
def gf_apply_matrix_pallas_words(tables_flat: jax.Array, data32: jax.Array,
                                 interpret: bool = False) -> jax.Array:
    """tables_flat [R*K*8] uint32 (from `expand_tables`); data32 [K, W]
    uint32 with W % TILE_WORDS == 0.  Returns [R, W] uint32."""
    k, w = data32.shape
    r = tables_flat.shape[0] // (k * 8)
    assert w % TILE_WORDS == 0
    lanes = 128
    s = TILE_WORDS // lanes
    grid = (w // TILE_WORDS,)
    d3 = data32.reshape(k, w // lanes, lanes)
    kernel = functools.partial(_rs_kernel, r=r, k=k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((k, s, lanes), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, s, lanes), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, w // lanes, lanes), jnp.uint32),
        interpret=interpret,
    )(tables_flat, d3)
    return out.reshape(r, w)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def expand_tables(mat: np.ndarray) -> np.ndarray:
    """[R, K] uint8 coding matrix -> flat [R*K*8] uint32 bit tables."""
    return gf256.MUL_BY_POW2[np.asarray(mat, dtype=np.uint8)].astype(
        np.uint32).reshape(-1)


def gf_apply_matrix_pallas(mat, data) -> jax.Array:
    """Byte-in/byte-out wrapper over the Pallas kernel (for tests and
    small inputs; bulk callers use gf_apply_matrix_pallas_words with
    host-packed uint32 buffers).

    mat: [R, K] uint8; data: [K, B] uint8 numpy -> [R, B] uint8."""
    from . import rs_jax

    mat = np.asarray(mat, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    r, k = mat.shape
    if data.shape[0] != k:
        raise ValueError(f"matrix k={k} vs data rows {data.shape[0]}")
    batch_shape = data.shape[1:]
    flat = data.reshape(k, -1)
    b = flat.shape[1]
    data32 = rs_jax.pack_words(flat, multiple=TILE_WORDS * 4)
    out32 = gf_apply_matrix_pallas_words(
        jnp.asarray(expand_tables(mat)), jnp.asarray(data32),
        interpret=_use_interpret())
    out = rs_jax.unpack_words(np.asarray(out32), b)
    return jnp.asarray(out).reshape((r,) + batch_shape)
