"""GF(2^8) arithmetic, wire-compatible with the reference's Reed-Solomon stack.

The reference SeaweedFS uses `klauspost/reedsolomon` (Go) and the
`reed-solomon-erasure` crate (Rust volume server).  Both operate over
GF(2^8) with generating polynomial 29 (full reduction polynomial
0x11D = x^8 + x^4 + x^3 + x^2 + 1) and identical log/exp tables
(reference: seaweed-volume/vendor/reed-solomon-erasure/build.rs:11-41,
src/galois_8.rs:90-102).  Bit-identical shard output requires exactly
these tables and the exact `exp` edge cases reproduced here.

All tables are precomputed as numpy arrays at import time; they are tiny
(<=64KiB) and shared by the CPU twin and the JAX/TPU kernels.
"""

from __future__ import annotations

import numpy as np

FIELD_SIZE = 256
GENERATING_POLYNOMIAL = 29  # low bits of 0x11D


def _gen_log_table(polynomial: int) -> np.ndarray:
    result = np.zeros(FIELD_SIZE, dtype=np.uint8)
    b = 1
    for log in range(FIELD_SIZE - 1):
        result[b] = log
        b <<= 1
        if b >= FIELD_SIZE:
            b = (b - FIELD_SIZE) ^ polynomial
    return result


LOG_TABLE = _gen_log_table(GENERATING_POLYNOMIAL)

# EXP_TABLE has 510 entries so that exp[log_a + log_b] needs no modular
# reduction (log sums are < 510); matches the reference's layout.
EXP_TABLE_SIZE = FIELD_SIZE * 2 - 2


def _gen_exp_table(log_table: np.ndarray) -> np.ndarray:
    result = np.zeros(EXP_TABLE_SIZE, dtype=np.uint8)
    for i in range(1, FIELD_SIZE):
        log = int(log_table[i])
        result[log] = i
        result[log + FIELD_SIZE - 1] = i
    return result


EXP_TABLE = _gen_exp_table(LOG_TABLE)


def _gen_mul_table() -> np.ndarray:
    a = np.arange(FIELD_SIZE)
    log_a = LOG_TABLE[a].astype(np.int32)
    log_sum = log_a[:, None] + log_a[None, :]
    table = EXP_TABLE[log_sum]
    table[0, :] = 0
    table[:, 0] = 0
    return table.astype(np.uint8)


# MUL_TABLE[a, b] = a * b in GF(2^8).
MUL_TABLE = _gen_mul_table()


def gf_mul(a: int, b: int) -> int:
    """Scalar GF multiply (mirrors galois_8::mul)."""
    return int(MUL_TABLE[a, b])


def gf_add(a: int, b: int) -> int:
    return a ^ b


def gf_div(a: int, b: int) -> int:
    """Scalar GF divide (mirrors galois_8::div): 0/b = 0, panics on /0."""
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    log_result = int(LOG_TABLE[a]) - int(LOG_TABLE[b])
    if log_result < 0:
        log_result += 255
    return int(EXP_TABLE[log_result])


def gf_exp(a: int, n: int) -> int:
    """a**n in GF(2^8) with the reference's edge cases
    (galois_8.rs:90-102): exp(a,0)=1 for all a, exp(0,n)=0 for n>0."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    log_result = int(LOG_TABLE[a]) * n
    log_result %= 255
    return int(EXP_TABLE[log_result])


def gf_inv(a: int) -> int:
    return gf_div(1, a)


def gf_mul_vec(c: int, x: np.ndarray) -> np.ndarray:
    """Multiply every byte of `x` by the constant `c` (mul_slice)."""
    assert x.dtype == np.uint8
    return MUL_TABLE[c][x]


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product of uint8 matrices a [m,k] @ b [k,n].

    XOR-accumulated table-lookup products; used for the (tiny) matrix
    algebra — the bulk data path uses gf_apply_matrix below.
    """
    assert a.dtype == np.uint8 and b.dtype == np.uint8
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    out = np.zeros((m, n), dtype=np.uint8)
    for i in range(k):
        # out ^= outer-ish product of column i of a with row i of b
        out ^= MUL_TABLE[a[:, i][:, None], b[i][None, :]]
    return out


def gf_apply_matrix(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Apply an [r, k] GF constant matrix to data rows [k, B] -> [r, B].

    This is the CPU twin of the TPU kernel: out[r] = XOR_i mat[r,i]*data[i].
    Exact and vectorized via per-constant 256-entry lookup rows.
    """
    assert mat.dtype == np.uint8 and data.dtype == np.uint8
    r, k = mat.shape
    k2 = data.shape[0]
    assert k == k2
    out = np.zeros((r,) + data.shape[1:], dtype=np.uint8)
    for i in range(k):
        for j in range(r):
            c = mat[j, i]
            if c == 0:
                continue
            out[j] ^= MUL_TABLE[c][data[i]]
    return out


# ---------------------------------------------------------------------------
# Bit-plane decomposition of GF-multiply-by-constant, used by the TPU kernel.
#
# GF(2^8) multiplication by a constant c is linear over GF(2): for a byte
# x = sum_b bit_b(x) * 2^b,  c*x = XOR_b [bit_b(x) ? c*(2^b) : 0].
# MUL_BY_POW2[c, b] = c * 2^b precomputed for all constants.
# ---------------------------------------------------------------------------

def _gen_mul_by_pow2() -> np.ndarray:
    out = np.zeros((FIELD_SIZE, 8), dtype=np.uint8)
    for b in range(8):
        out[:, b] = MUL_TABLE[:, 1 << b]
    return out


MUL_BY_POW2 = _gen_mul_by_pow2()
