"""SeaweedMQ analog: topic/partition model, filer-backed log store,
broker server (weed/mq/)."""

from .topic import Partition, Topic, split_ring, partition_slot  # noqa: F401
from .broker import BrokerServer  # noqa: F401
