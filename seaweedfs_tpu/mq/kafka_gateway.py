"""Kafka wire-protocol gateway over the MQ broker (reference:
weed/mq/kafka/gateway/server.go + protocol/handler.go).

Speaks the public Kafka binary protocol on a TCP port and maps it
onto the broker's topics/partitions:

    ApiVersions(18) Metadata(3) CreateTopics(19) Produce(0) Fetch(1)
    ListOffsets(2) FindCoordinator(10) OffsetCommit(8) OffsetFetch(9)
    JoinGroup(11) Heartbeat(12) LeaveGroup(13) SyncGroup(14)

Kafka topics live in the fixed namespace "kafka" (the reference
gateway does the same); Kafka partition index i is the i-th ring
partition of the topic's layout; Kafka offsets ARE our tsNs message
offsets (monotonic int64 — exactly what the protocol requires; they
are sparse, which clients don't mind: the next fetch offset is
last_offset+1 and fetches return everything >= it).

Consumer groups support the FULL rebalance dance (kafka_groups.py
coordinator: join rounds, leader-side assignors, heartbeat-driven
rebalance signals) in addition to manual assignment.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

from .client import MQClient
from .kafka_groups import GroupCoordinator
from .kafka_wire import (BatchError, Reader, decode_record_batches,
                         enc_array, enc_bytes, enc_i8, enc_i16,
                         enc_i32, enc_i64, enc_string,
                         encode_single_record_batch)

NAMESPACE = "kafka"

# error codes (protocol/errors.go)
NONE = 0
UNKNOWN_SERVER_ERROR = -1
OFFSET_OUT_OF_RANGE = 1
CORRUPT_MESSAGE = 2
UNKNOWN_TOPIC_OR_PARTITION = 3
UNSUPPORTED_VERSION = 35
TOPIC_ALREADY_EXISTS = 36
INVALID_REQUEST = 42

# Per-API version RANGES (round 5: breadth covering what kafka-python
# and librdkafka negotiate down to — both pick the highest version in
# the intersection of client and broker support, so every version in
# these ranges must be byte-exact, not just the max).
API_VERSIONS = {
    0: (3, 5),    # Produce (record batches v2 only; v5 +log_start)
    1: (4, 7),    # Fetch (v5 +log_start_offset, v7 +sessions)
    2: (1, 2),    # ListOffsets (v2 +isolation/throttle)
    3: (1, 5),    # Metadata (v2 +cluster_id, v3 +throttle, v5 +offline)
    8: (2, 3),    # OffsetCommit (v3 +throttle)
    9: (1, 3),    # OffsetFetch (v2 +error_code, v3 +throttle)
    10: (0, 1),   # FindCoordinator (v1 +key_type/error_message)
    11: (0, 2),   # JoinGroup (v1 +rebalance_timeout, v2 +throttle)
    12: (0, 2),   # Heartbeat (v1 +throttle)
    13: (0, 1),   # LeaveGroup (v1 +throttle)
    14: (0, 2),   # SyncGroup (v1 +throttle)
    15: (0, 1),   # DescribeGroups (v1 +throttle)
    16: (0, 1),   # ListGroups (v1 +throttle)
    18: (0, 2),   # ApiVersions (v1 +throttle)
    19: (0, 2),   # CreateTopics (v1 +validate_only, v2 +throttle)
    20: (0, 1),   # DeleteTopics (v1 +throttle)
    17: (1, 1),   # SaslHandshake (v1 = framed authenticate flow
                  #   only; v0's raw-token exchange is not spoken)
    22: (0, 1),   # InitProducerId (idempotent-producer bootstrap)
    36: (0, 1),   # SaslAuthenticate (framed PLAIN)
    32: (0, 1),   # DescribeConfigs (v1 +include_synonyms/sources)
    37: (0, 1),   # CreatePartitions (v1 same wire, bumped for parity)
    42: (0, 1),   # DeleteGroups (v1 +throttle)
}

GROUP_ID_NOT_FOUND = 69
NON_EMPTY_GROUP = 68
COORDINATOR_NOT_AVAILABLE = 15
UNSUPPORTED_SASL_MECHANISM = 33
SASL_AUTHENTICATION_FAILED = 58


class KafkaGateway:
    def __init__(self, broker: str, host: str = "127.0.0.1",
                 port: int = 0,
                 users: "dict[str, str] | None" = None):
        # SASL/PLAIN credential map (mq/kafka gateway auth role):
        # when set, every connection must SaslHandshake +
        # SaslAuthenticate before any data API (ApiVersions is
        # allowed pre-auth, as real brokers permit for negotiation)
        self.users = users
        self.mq = MQClient(broker)
        self.host = host
        self.port = port
        self._sock = None
        self._stopping = False
        # topic layouts cache: name -> (partition count, expires) —
        # TTL'd so broker-side reconfiguration/deletion is noticed
        # without a gateway restart
        self._layouts: dict[str, tuple[int, float]] = {}
        self._layout_ttl = 10.0
        self._lock = threading.Lock()
        self.groups = GroupCoordinator()

    def start(self) -> "KafkaGateway":
        self._sock = socket.create_server((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept_loop,
                         name="kafka-accept", daemon=True).start()
        return self

    def stop(self) -> None:
        self._stopping = True
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    # -- framing -----------------------------------------------------------

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(120)
            buf = b""
            authed = self.users is None
            sasl_state = {"mechanism": ""}
            while True:
                while len(buf) < 4:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                size = struct.unpack(">i", buf[:4])[0]
                if not 0 < size <= 64 * 1024 * 1024:
                    return
                while len(buf) < 4 + size:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                frame, buf = buf[4:4 + size], buf[4 + size:]
                if not authed:
                    resp, authed, close = self._handle_preauth(
                        frame, sasl_state)
                    if resp is None:
                        return          # unauthenticated data API
                    conn.sendall(struct.pack(">i", len(resp)) + resp)
                    if close:
                        return          # failed auth: drop the conn
                    continue
                resp = self._handle_frame(frame)
                if resp is not None:
                    conn.sendall(struct.pack(">i", len(resp)) + resp)
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_preauth(self, frame: bytes, state: dict
                        ) -> "tuple[bytes | None, bool, bool]":
        """Pre-auth gate (SASL listener semantics): serve ApiVersions
        (18), SaslHandshake (17) and SaslAuthenticate (36); close the
        connection on anything else — a real broker's SASL port does
        the same rather than leak an unauthenticated data plane.
        Returns (response, now_authenticated, close_after_send)."""
        r = Reader(frame)
        api_key = r.i16()
        api_version = r.i16()
        correlation_id = r.i32()
        r.string()                       # client_id
        header = enc_i32(correlation_id)
        # the SAME version-range gate the authed dispatch applies:
        # without it a v3+ flexible-encoding ApiVersions request
        # would get a non-flexible body it cannot parse
        lo_hi = API_VERSIONS.get(api_key)
        if api_key in (17, 18, 36) and (
                lo_hi is None or
                not lo_hi[0] <= api_version <= lo_hi[1]):
            if api_key == 18:
                return (header + enc_i16(UNSUPPORTED_VERSION) +
                        enc_i32(0), False, False)
            return (header + enc_i16(UNSUPPORTED_VERSION),
                    False, False)
        if api_key == 18:
            return (header + self._api_versions(r, api_version),
                    False, False)
        if api_key == 17:
            mech = r.string() or ""
            if mech.upper() != "PLAIN":
                return (header +
                        enc_i16(UNSUPPORTED_SASL_MECHANISM) +
                        enc_array([enc_string("PLAIN")]),
                        False, False)
            state["mechanism"] = "PLAIN"
            return (header + enc_i16(NONE) +
                    enc_array([enc_string("PLAIN")]), False, False)
        if api_key == 36 and state.get("mechanism") == "PLAIN":
            auth = r.bytes_() or b""
            # RFC 4616: [authzid] \0 authcid \0 passwd
            parts = auth.split(b"\x00")
            ok = False
            if len(parts) == 3:
                import hmac as _hmac
                user = parts[1].decode("utf-8", "replace")
                pw = parts[2].decode("utf-8", "replace")
                # constant-time compare: == would leak a prefix
                # timing side channel on a network-facing auth path
                ok = _hmac.compare_digest(
                    self.users.get(user, ""), pw) and \
                    user in self.users
            if not ok:
                # answer, then DROP the connection: keeping it open
                # would hand an attacker free in-connection password
                # retries (real brokers close on auth failure too)
                return (header +
                        enc_i16(SASL_AUTHENTICATION_FAILED) +
                        enc_string("authentication failed") +
                        enc_bytes(b"") +
                        (enc_i64(0) if api_version >= 1 else b""),
                        False, True)
            return (header + enc_i16(NONE) + enc_string(None) +
                    enc_bytes(b"") +
                    (enc_i64(0) if api_version >= 1 else b""),
                    True, False)
        return None, False, True         # close: unauthenticated

    def _handle_frame(self, frame: bytes) -> "bytes | None":
        r = Reader(frame)
        api_key = r.i16()
        api_version = r.i16()
        correlation_id = r.i32()
        r.string()                       # client_id
        header = enc_i32(correlation_id)
        lo_hi = API_VERSIONS.get(api_key)
        if lo_hi is None or not lo_hi[0] <= api_version <= lo_hi[1]:
            if api_key == 18:
                # ApiVersions version negotiation: answer v0-shaped
                # with UNSUPPORTED_VERSION so the client downgrades
                return header + enc_i16(UNSUPPORTED_VERSION) + \
                    enc_i32(0)
            return header + enc_i16(UNSUPPORTED_VERSION)
        if api_key == 17:
            mech = r.string() or ""
            code = NONE if mech.upper() == "PLAIN" or \
                self.users is None else UNSUPPORTED_SASL_MECHANISM
            return header + enc_i16(code) + \
                enc_array([enc_string("PLAIN")])
        if api_key == 36:
            r.bytes_()
            return (header + enc_i16(NONE) + enc_string(None) +
                    enc_bytes(b"") +
                    (enc_i64(0) if api_version >= 1 else b""))
        fn = {0: self._produce, 1: self._fetch, 2: self._list_offsets,
              3: self._metadata, 8: self._offset_commit,
              9: self._offset_fetch, 10: self._find_coordinator,
              11: self._join_group, 12: self._heartbeat,
              13: self._leave_group, 14: self._sync_group,
              15: self._describe_groups, 16: self._list_groups,
              18: self._api_versions, 19: self._create_topics,
              20: self._delete_topics, 22: self._init_producer_id,
              32: self._describe_configs,
              37: self._create_partitions,
              42: self._delete_groups}[api_key]
        body = fn(r, api_version)
        return None if body is None else header + body

    # -- topic helpers -----------------------------------------------------

    def _partition_count(self, topic: str) -> "int | None":
        now = time.monotonic()
        with self._lock:
            hit = self._layouts.get(topic)
            if hit is not None and now < hit[1]:
                return hit[0]
        try:
            parts = self.mq.lookup(NAMESPACE, topic)
        except (RuntimeError, OSError, LookupError):
            return None
        with self._lock:
            self._layouts[topic] = (len(parts),
                                    now + self._layout_ttl)
        return len(parts)

    def _all_topics(self) -> list[str]:
        try:
            return self.mq.list_topics(NAMESPACE)
        except (RuntimeError, OSError, AttributeError):
            with self._lock:
                return sorted(self._layouts)

    # -- API handlers ------------------------------------------------------

    def _api_versions(self, r: Reader, v: int = 0) -> bytes:
        entries = [enc_i16(k) + enc_i16(lo) + enc_i16(hi)
                   for k, (lo, hi) in sorted(API_VERSIONS.items())]
        out = enc_i16(NONE) + enc_array(entries)
        if v >= 1:
            out += enc_i32(0)            # throttle_time_ms
        return out

    def _metadata(self, r: Reader, v: int = 1) -> bytes:
        n = r.i32()
        # v1 semantics: null array (-1) = all topics, empty array =
        # NO topics (broker-info-only refresh) — v0's empty-means-all
        # does not apply here
        wanted = None if n < 0 else [r.string() for _ in range(n)]
        if v >= 4 and r.remaining() >= 1:
            r.i8()                       # allow_auto_topic_creation
        broker = (enc_i32(0) + enc_string(self.host) +
                  enc_i32(self.port) + enc_string(None))
        names = wanted if wanted is not None else self._all_topics()
        topics = []
        for name in names:
            count = self._partition_count(name)
            if count is None:
                topics.append(enc_i16(UNKNOWN_TOPIC_OR_PARTITION) +
                              enc_string(name) + enc_i8(0) +
                              enc_array([]))
                continue
            parts = [enc_i16(NONE) + enc_i32(i) + enc_i32(0) +
                     enc_array([enc_i32(0)]) +
                     enc_array([enc_i32(0)]) +
                     (enc_array([]) if v >= 5 else b"")  # offline
                     for i in range(count)]
            topics.append(enc_i16(NONE) + enc_string(name) +
                          enc_i8(0) + enc_array(parts))
        out = b""
        if v >= 3:
            out += enc_i32(0)            # throttle_time_ms
        out += enc_array([broker])
        if v >= 2:
            out += enc_string("seaweedfs-tpu")   # cluster_id
        out += enc_i32(0)                # controller_id
        out += enc_array(topics)
        return out

    def _create_topics(self, r: Reader, v: int = 0) -> bytes:
        # parse the WHOLE request before acting: v1's validate_only
        # flag trails the topic list, and a dry-run request must not
        # mutate the broker
        n = r.i32()
        wanted = []
        for _ in range(n):
            name = r.string()
            num_partitions = r.i32()
            r.i16()                      # replication_factor
            for _ in range(r.i32()):     # manual assignments
                r.i32()
                cnt = r.i32()
                for _ in range(cnt):
                    r.i32()
            for _ in range(r.i32()):     # configs
                r.string()
                r.string()
            wanted.append((name, num_partitions))
        if r.remaining() >= 4:
            r.i32()                      # timeout_ms
        validate_only = False
        if v >= 1 and r.remaining() >= 1:
            validate_only = bool(r.i8())
        results = []
        for name, num_partitions in wanted:
            code = NONE
            if self._partition_count(name) is not None:
                code = TOPIC_ALREADY_EXISTS
            elif not validate_only:
                try:
                    self.mq.configure_topic(
                        NAMESPACE, name,
                        max(1, num_partitions))
                    with self._lock:
                        self._layouts[name] = (
                            max(1, num_partitions),
                            time.monotonic() + self._layout_ttl)
                except (RuntimeError, OSError) as e:
                    code = INVALID_REQUEST if "name" in str(e) \
                        else UNKNOWN_SERVER_ERROR
            results.append(enc_string(name) + enc_i16(code) +
                           (enc_string(None) if v >= 1 else b""))
        return (enc_i32(0) if v >= 2 else b"") + enc_array(results)

    def _delete_topics(self, r: Reader, v: int = 0) -> bytes:
        """DeleteTopics (key 20): each named topic is removed from the
        broker entirely (messages + layout + schema)."""
        names = [r.string() for _ in range(r.i32())]
        if r.remaining() >= 4:
            r.i32()                      # timeout_ms
        results = []
        for name in names:
            if self._partition_count(name) is None:
                results.append(enc_string(name) +
                               enc_i16(UNKNOWN_TOPIC_OR_PARTITION))
                continue
            code = NONE
            try:
                self.mq.delete_topic(NAMESPACE, name)
            except (RuntimeError, OSError):
                code = UNKNOWN_SERVER_ERROR
            with self._lock:
                self._layouts.pop(name, None)
            results.append(enc_string(name) + enc_i16(code))
        return (enc_i32(0) if v >= 1 else b"") + enc_array(results)

    def _create_partitions(self, r: Reader, v: int = 0) -> bytes:
        """CreatePartitions (key 37): Kafka's only partition-growth
        verb, mapped onto the broker's fenced repartition (messages
        re-hash onto the new ring, order preserved per key)."""
        wanted = []
        for _ in range(r.i32()):
            name = r.string()
            count = r.i32()
            n_assign = r.i32()           # manual broker assignments
            if n_assign > 0:
                for _ in range(n_assign):
                    for _ in range(r.i32()):
                        r.i32()
            wanted.append((name, count))
        if r.remaining() >= 4:
            r.i32()                      # timeout_ms
        validate_only = False
        if r.remaining() >= 1:
            validate_only = bool(r.i8())
        results = []
        for name, count in wanted:
            have = self._partition_count(name)
            if have is None:
                results.append(
                    enc_string(name) +
                    enc_i16(UNKNOWN_TOPIC_OR_PARTITION) +
                    enc_string("unknown topic"))
                continue
            if count <= have:
                results.append(
                    enc_string(name) + enc_i16(INVALID_REQUEST) +
                    enc_string(f"partition count must grow "
                               f"(have {have})"))
                continue
            code, msg = NONE, None
            if not validate_only:
                try:
                    self.mq.repartition(NAMESPACE, name, count)
                    with self._lock:
                        self._layouts.pop(name, None)
                except (RuntimeError, OSError) as e:
                    code, msg = UNKNOWN_SERVER_ERROR, str(e)[:120]
            results.append(enc_string(name) + enc_i16(code) +
                           enc_string(msg))
        return enc_i32(0) + enc_array(results)

    def _list_groups(self, r: Reader, v: int = 0) -> bytes:
        groups = self.groups.list_groups()
        out = enc_i32(0) if v >= 1 else b""
        out += enc_i16(NONE)
        out += enc_array([enc_string(gid) + enc_string(ptype)
                          for gid, ptype in groups])
        return out

    def _describe_groups(self, r: Reader, v: int = 0) -> bytes:
        names = [r.string() for _ in range(r.i32())]
        results = []
        for gid in names:
            d = self.groups.describe(gid)
            if d is None or not d["members"]:
                # Kafka: UNKNOWN group -> Dead; a known group whose
                # members all left -> Empty (its offsets still exist,
                # cleanup tooling treats the two differently)
                state = "Empty" if d is not None else "Dead"
                results.append(
                    enc_i16(NONE) + enc_string(gid) +
                    enc_string(state) + enc_string("") +
                    enc_string("") + enc_array([]))
                continue
            members = [
                enc_string(m["id"]) + enc_string("") +
                enc_string("/127.0.0.1") +
                enc_bytes(m["metadata"]) +
                enc_bytes(m["assignment"])
                for m in d["members"]]
            results.append(
                enc_i16(NONE) + enc_string(gid) +
                enc_string(d["state"]) +
                enc_string(d["protocol_type"]) +
                enc_string(d["protocol"]) + enc_array(members))
        return (enc_i32(0) if v >= 1 else b"") + enc_array(results)

    def _init_producer_id(self, r: Reader, v: int = 0) -> bytes:
        """API 22 (mq/kafka/protocol InitProducerId role): newer
        librdkafka/kafka-python producers bootstrap an idempotent
        producer id before their first Produce.  We have no
        transaction log — ids are process-monotonic and the epoch is
        always 0, which satisfies clients that only need a non-error
        answer to proceed."""
        r.string()                       # transactional_id (unused)
        r.i32()                          # transaction_timeout_ms
        with self._lock:
            self._next_pid = getattr(self, "_next_pid", 0) + 1
            pid = self._next_pid
        return (enc_i32(0) +             # throttle_time_ms
                enc_i16(NONE) + enc_i64(pid) + enc_i16(0))

    def _delete_groups(self, r: Reader, v: int = 0) -> bytes:
        """API 42: remove consumer groups — refuses groups with live
        members (NON_EMPTY_GROUP, like the reference coordinator),
        deletes committed offsets through the broker otherwise."""
        names = [r.string() for _ in range(r.i32())]
        results = []
        for gid in names:
            d = self.groups.describe(gid)
            if d is not None and d["members"]:
                results.append(enc_string(gid) +
                               enc_i16(NON_EMPTY_GROUP))
                continue
            known = d is not None
            try:
                had_offsets = self.mq.delete_group_offsets(gid)
            except (RuntimeError, OSError):
                # the broker couldn't confirm offset removal: say so
                # and KEEP coordinator state — reporting success here
                # would let a rejoining consumer resume from offsets
                # that were supposed to be gone
                results.append(enc_string(gid) +
                               enc_i16(COORDINATOR_NOT_AVAILABLE))
                continue
            self.groups.drop(gid)
            code = NONE if (known or had_offsets) \
                else GROUP_ID_NOT_FOUND
            results.append(enc_string(gid) + enc_i16(code))
        return (enc_i32(0) if v >= 1 else b"") + enc_array(results)

    # the static per-topic config surface DescribeConfigs exposes —
    # our engine's actual behaviors (no size/time retention yet;
    # delete-on-request only)
    _TOPIC_CONFIGS = {"cleanup.policy": "delete",
                      "retention.ms": "-1",
                      "retention.bytes": "-1",
                      "max.message.bytes": str(16 << 20)}

    def _describe_configs(self, r: Reader, v: int = 0) -> bytes:
        resources = []
        for _ in range(r.i32()):
            rtype = r.i8()
            rname = r.string()
            n = r.i32()
            wanted = None if n < 0 else [r.string()
                                         for _ in range(n)]
            resources.append((rtype, rname, wanted))
        if v >= 1 and r.remaining() >= 1:
            r.i8()                       # include_synonyms
        results = []
        for rtype, rname, wanted in resources:
            if rtype != 2:               # only TOPIC resources exist
                results.append(
                    enc_i16(INVALID_REQUEST) +
                    enc_string(f"unsupported resource type {rtype}") +
                    enc_i8(rtype) + enc_string(rname) +
                    enc_array([]))
                continue
            if self._partition_count(rname) is None:
                results.append(
                    enc_i16(UNKNOWN_TOPIC_OR_PARTITION) +
                    enc_string("unknown topic") + enc_i8(rtype) +
                    enc_string(rname) + enc_array([]))
                continue
            entries = []
            for key, value in sorted(self._TOPIC_CONFIGS.items()):
                if wanted is not None and key not in wanted:
                    continue
                e = enc_string(key) + enc_string(value) + enc_i8(1)
                # v0: is_default bool; v1: config_source int8
                e += enc_i8(5 if v >= 1 else 1)   # 5 = DEFAULT_CONFIG
                e += enc_i8(0)                    # is_sensitive
                if v >= 1:
                    e += enc_array([])            # synonyms
                entries.append(e)
            results.append(enc_i16(NONE) + enc_string(None) +
                           enc_i8(rtype) + enc_string(rname) +
                           enc_array(entries))
        return enc_i32(0) + enc_array(results)

    def _produce(self, r: Reader, v: int = 3) -> "bytes | None":
        if v >= 3:
            r.string()                   # transactional_id
        acks = r.i16()
        r.i32()                          # timeout_ms
        topics_out = []
        for _ in range(r.i32()):
            name = r.string()
            parts_out = []
            for _ in range(r.i32()):
                idx = r.i32()
                record_set = r.bytes_() or b""
                code, base_offset = NONE, -1
                count = self._partition_count(name)
                if count is None or not 0 <= idx < count:
                    code = UNKNOWN_TOPIC_OR_PARTITION
                else:
                    try:
                        records = decode_record_batches(record_set)
                        # one atomic broker call per batch: a retried
                        # batch must never duplicate a committed
                        # prefix (Kafka per-partition batch guarantee)
                        stamps = self.mq.publish_batch(
                            NAMESPACE, name, idx,
                            [(rec["key"] or b"", rec["value"] or b"")
                             for rec in records])
                        if stamps:
                            base_offset = stamps[0]
                    except BatchError:
                        code = CORRUPT_MESSAGE
                    except (RuntimeError, OSError):
                        code = UNKNOWN_SERVER_ERROR
                part = enc_i32(idx) + enc_i16(code) + \
                    enc_i64(base_offset)
                if v >= 2:
                    part += enc_i64(-1)          # log_append_time
                if v >= 5:
                    part += enc_i64(0)           # log_start_offset
                parts_out.append(part)
            topics_out.append(enc_string(name) + enc_array(parts_out))
        if acks == 0:
            # fire-and-forget: the protocol REQUIRES no response (a
            # stray one would desynchronize the client's correlation)
            return None
        out = enc_array(topics_out)
        if v >= 1:
            out += enc_i32(0)                    # throttle_time
        return out

    def _fetch(self, r: Reader, v: int = 4) -> bytes:
        r.i32()                          # replica_id
        r.i32()                          # max_wait_ms (no long poll)
        r.i32()                          # min_bytes
        r.i32()                          # max_bytes
        r.i8()                           # isolation_level
        session_id = 0
        if v >= 7:
            session_id = r.i32()
            r.i32()                      # session_epoch (no sessions:
            # we answer full fetches, session_id 0 = sessionless)
        topics_out = []
        for _ in range(r.i32()):
            name = r.string()
            parts_out = []
            for _ in range(r.i32()):
                idx = r.i32()
                fetch_offset = r.i64()
                if v >= 5:
                    r.i64()              # log_start_offset (replicas)
                max_part_bytes = r.i32()
                code, hwm, batches = NONE, 0, b""
                count = self._partition_count(name)
                if count is None or not 0 <= idx < count:
                    code = UNKNOWN_TOPIC_OR_PARTITION
                else:
                    try:
                        msgs, hwm_ns = self.mq.subscribe_full(
                            NAMESPACE, name, idx,
                            since_ns=fetch_offset - 1, limit=500)
                        # log-end-offset convention (0 when empty)
                        hwm = hwm_ns + 1 if hwm_ns else 0
                        total = 0
                        out = []
                        for m in msgs:
                            b = encode_single_record_batch(
                                m.ts_ns, m.ts_ns // 1_000_000,
                                m.key or None, m.value)
                            total += len(b)
                            if out and total > max(1024,
                                                   max_part_bytes):
                                break
                            out.append(b)
                        batches = b"".join(out)
                    except (RuntimeError, OSError):
                        code = UNKNOWN_SERVER_ERROR
                part = enc_i32(idx) + enc_i16(code) + \
                    enc_i64(hwm) + \
                    enc_i64(hwm)                   # last_stable_offset
                if v >= 5:
                    part += enc_i64(0)             # log_start_offset
                part += enc_i32(0)                 # aborted txns: none
                part += enc_bytes(batches)
                parts_out.append(part)
            topics_out.append(enc_string(name) + enc_array(parts_out))
        if v >= 7:
            # drain forgotten_topics_data (sessionless: ignored)
            for _ in range(max(r.i32(), 0) if r.remaining() >= 4
                           else 0):
                r.string()
                for _ in range(max(r.i32(), 0)):
                    r.i32()
        out = enc_i32(0)                           # throttle_time
        if v >= 7:
            out += enc_i16(NONE) + enc_i32(0)      # error, session_id
        return out + enc_array(topics_out)

    def _list_offsets(self, r: Reader, v: int = 1) -> bytes:
        r.i32()                          # replica_id
        if v >= 2:
            r.i8()                       # isolation_level
        topics_out = []
        for _ in range(r.i32()):
            name = r.string()
            parts_out = []
            for _ in range(r.i32()):
                idx = r.i32()
                ts = r.i64()
                code, offset = NONE, 0
                count = self._partition_count(name)
                if count is None or not 0 <= idx < count:
                    code = UNKNOWN_TOPIC_OR_PARTITION
                elif ts == -1:           # latest = log end offset
                    try:
                        _, hwm_ns = self.mq.subscribe_full(
                            NAMESPACE, name, idx, since_ns=1 << 62,
                            limit=1)
                        offset = hwm_ns + 1 if hwm_ns else 0
                    except (RuntimeError, OSError):
                        code = UNKNOWN_SERVER_ERROR
                # ts == -2 (earliest) or a timestamp: offset 0 serves
                # both — our offsets are timestamps, so a fetch from
                # the requested ts itself is also valid
                elif ts >= 0:
                    offset = ts * 1_000_000   # ms -> ns offset space
                parts_out.append(enc_i32(idx) + enc_i16(code) +
                                 enc_i64(-1) + enc_i64(offset))
            topics_out.append(enc_string(name) + enc_array(parts_out))
        return (enc_i32(0) if v >= 2 else b"") + enc_array(topics_out)

    def _find_coordinator(self, r: Reader, v: int = 0) -> bytes:
        r.string()                       # key (group id): we
        if v >= 1 and r.remaining() >= 1:
            r.i8()                       # key_type
        out = b""
        if v >= 1:
            out += enc_i32(0)            # throttle_time
        out += enc_i16(NONE)
        if v >= 1:
            out += enc_string(None)      # error_message
        return out + (enc_i32(0) + enc_string(self.host) +
                      enc_i32(self.port))

    def _offset_commit(self, r: Reader, v: int = 2) -> bytes:
        group = r.string() or ""
        r.i32()                          # generation_id
        r.string()                       # member_id
        r.i64()                          # retention_time
        topics_out = []
        for _ in range(r.i32()):
            name = r.string()
            parts_out = []
            for _ in range(r.i32()):
                idx = r.i32()
                offset = r.i64()
                r.string()               # metadata
                code = NONE
                try:
                    # kafka commits "next offset to read"; our broker
                    # stores "last consumed tsNs" — same resume point
                    self.mq.commit_offset(group, NAMESPACE, name, idx,
                                          offset - 1)
                except (RuntimeError, OSError):
                    code = UNKNOWN_SERVER_ERROR
                parts_out.append(enc_i32(idx) + enc_i16(code))
            topics_out.append(enc_string(name) + enc_array(parts_out))
        return (enc_i32(0) if v >= 3 else b"") + enc_array(topics_out)

    def _offset_fetch(self, r: Reader, v: int = 1) -> bytes:
        group = r.string() or ""
        topics_out = []
        for _ in range(r.i32()):
            name = r.string()
            parts_out = []
            for _ in range(r.i32()):
                idx = r.i32()
                code, offset = NONE, -1
                try:
                    ts, committed = self.mq.fetch_offset_full(
                        group, NAMESPACE, name, idx)
                    # committed value is "next offset to read" - 1;
                    # a commit at position 0 stores -1 and must NOT
                    # read back as "no offset"
                    offset = ts + 1 if committed else -1
                except (RuntimeError, OSError):
                    code = UNKNOWN_SERVER_ERROR
                parts_out.append(enc_i32(idx) + enc_i64(offset) +
                                 enc_string("") + enc_i16(code))
            topics_out.append(enc_string(name) + enc_array(parts_out))
        out = (enc_i32(0) if v >= 3 else b"") + enc_array(topics_out)
        if v >= 2:
            out += enc_i16(NONE)         # top-level error_code
        return out

    # -- consumer groups (protocol/joingroup.go; kafka_groups.py) ----------

    def _join_group(self, r: Reader, v: int = 0) -> bytes:
        group = r.string() or ""
        session_timeout = r.i32() / 1000.0
        if v >= 1:
            r.i32()                      # rebalance_timeout_ms
        member_id = r.string() or ""
        r.string()                       # protocol_type ("consumer")
        protocols = []
        for _ in range(r.i32()):
            name = r.string() or ""
            protocols.append((name, r.bytes_() or b""))
        code, resp = self.groups.join(group, member_id,
                                      session_timeout, protocols)
        throttle = enc_i32(0) if v >= 2 else b""
        if code:
            return (throttle + enc_i16(code) + enc_i32(0) +
                    enc_string("") + enc_string("") +
                    enc_string(member_id) + enc_array([]))
        return (throttle + enc_i16(0) + enc_i32(resp["generation"]) +
                enc_string(resp["protocol"]) +
                enc_string(resp["leader"]) +
                enc_string(resp["member_id"]) +
                enc_array([enc_string(mid) + enc_bytes(meta)
                           for mid, meta in resp["members"]]))

    def _sync_group(self, r: Reader, v: int = 0) -> bytes:
        group = r.string() or ""
        generation = r.i32()
        member_id = r.string() or ""
        assignments = {}
        for _ in range(r.i32()):
            mid = r.string() or ""
            assignments[mid] = r.bytes_() or b""
        code, assignment = self.groups.sync(group, member_id,
                                            generation, assignments)
        return (enc_i32(0) if v >= 1 else b"") + enc_i16(code) + \
            enc_bytes(assignment)

    def _heartbeat(self, r: Reader, v: int = 0) -> bytes:
        group = r.string() or ""
        generation = r.i32()
        member_id = r.string() or ""
        return (enc_i32(0) if v >= 1 else b"") + \
            enc_i16(self.groups.heartbeat(group, member_id,
                                          generation))

    def _leave_group(self, r: Reader, v: int = 0) -> bytes:
        group = r.string() or ""
        member_id = r.string() or ""
        return (enc_i32(0) if v >= 1 else b"") + \
            enc_i16(self.groups.leave(group, member_id))
