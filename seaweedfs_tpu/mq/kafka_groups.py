"""Kafka group coordinator (reference:
weed/mq/kafka/protocol/joingroup.go + gateway/coordinator_registry.go).

Implements the classic consumer-group rebalance dance:

  JoinGroup(11): members enter a join round; it closes when every
      known live member has rejoined (stragglers get up to the
      rebalance timeout).  The FIRST member id in sort order becomes
      leader and receives everyone's subscription metadata.
  SyncGroup(14): the leader submits per-member assignments (the
      broker treats them as opaque bytes — client-side assignors,
      exactly Kafka's model); followers block until they arrive.
  Heartbeat(12): liveness + the rebalance-needed signal
      (REBALANCE_IN_PROGRESS tells members to rejoin).
  LeaveGroup(13): immediate rebalance trigger.

Members that stop heartbeating past their session timeout are expired
lazily, triggering a rebalance for the survivors."""

from __future__ import annotations

import threading
import time
import uuid

# error codes (protocol/errors.go)
NONE = 0
UNKNOWN_MEMBER_ID = 25
ILLEGAL_GENERATION = 22
REBALANCE_IN_PROGRESS = 27
INCONSISTENT_GROUP_PROTOCOL = 23

REBALANCE_TIMEOUT = 30.0   # how long known live members get to rejoin
SYNC_TIMEOUT = 10.0


class _Member:
    def __init__(self, member_id: str, session_timeout: float):
        self.id = member_id
        self.session_timeout = session_timeout
        self.last_seen = time.monotonic()
        self.metadata = b""
        self.protocols: list[tuple[str, bytes]] = []
        self.joined_round = -1

    @property
    def expired(self) -> bool:
        return time.monotonic() - self.last_seen > self.session_timeout


class _Group:
    def __init__(self, group_id: str):
        self.id = group_id
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.generation = 0
        self.members: dict[str, _Member] = {}
        self.leader = ""
        self.protocol = ""
        self.state = "Empty"      # Empty|Joining|AwaitSync|Stable
        self.round = 0            # join-round sequence
        self.round_opened = 0.0
        self.assignments: dict[str, bytes] = {}


class GroupCoordinator:
    def __init__(self):
        self._groups: dict[str, _Group] = {}
        self._lock = threading.Lock()

    def _group(self, group_id: str) -> _Group:
        with self._lock:
            g = self._groups.get(group_id)
            if g is None:
                g = self._groups[group_id] = _Group(group_id)
            return g

    @staticmethod
    def _expire_locked(g: _Group) -> None:
        dead = [m for m in g.members.values() if m.expired]
        for m in dead:
            del g.members[m.id]
        if dead and g.state == "Stable":
            # open a GENUINE new round — reusing the old round number
            # would let the first rejoiner close it instantly and
            # elect a leader that never rejoined
            g.state = "Joining"
            g.round += 1
            g.round_opened = time.monotonic()
            g.assignments = {}

    # -- JoinGroup ---------------------------------------------------------

    def join(self, group_id: str, member_id: str,
             session_timeout: float,
             protocols: "list[tuple[str, bytes]]"
             ) -> "tuple[int, dict]":
        g = self._group(group_id)
        with g.cond:
            self._expire_locked(g)
            if member_id and member_id not in g.members:
                return UNKNOWN_MEMBER_ID, {}
            if not member_id:
                member_id = f"member-{uuid.uuid4().hex[:12]}"
                g.members[member_id] = _Member(member_id,
                                               session_timeout)
            m = g.members[member_id]
            m.last_seen = time.monotonic()
            m.protocols = protocols
            m.metadata = protocols[0][1] if protocols else b""
            if g.state in ("Empty", "Stable", "AwaitSync"):
                # open a new join round
                g.state = "Joining"
                g.round += 1
                g.round_opened = time.monotonic()
                g.assignments = {}
                g.cond.notify_all()
            m.joined_round = g.round
            this_round = g.round
            # the round closes as soon as every live member has
            # rejoined it; known LIVE members get up to
            # REBALANCE_TIMEOUT to show up (a short door would expel
            # members whose heartbeat cadence is slower than it —
            # spurious rebalances).  A joiner arriving just after a
            # close simply opens the next round
            hard_deadline = g.round_opened + REBALANCE_TIMEOUT
            while g.state == "Joining" and g.round == this_round:
                missing = [x for x in g.members.values()
                           if x.joined_round != this_round and
                           not x.expired]
                if not missing or time.monotonic() >= hard_deadline:
                    break
                g.cond.wait(timeout=0.05)
            if g.round != this_round:
                # a newer round superseded us mid-wait: caller rejoins
                return REBALANCE_IN_PROGRESS, {}
            if g.state == "Joining":
                # first thread out closes the round
                for stale in [x.id for x in g.members.values()
                              if x.joined_round != this_round]:
                    del g.members[stale]
                g.generation += 1
                ordered = sorted(g.members)
                g.leader = ordered[0]
                g.protocol = self._pick_protocol(g)
                if g.protocol is None:
                    g.state = "Empty"
                    g.cond.notify_all()
                    return INCONSISTENT_GROUP_PROTOCOL, {}
                g.state = "AwaitSync"
                g.cond.notify_all()
            resp = {
                "generation": g.generation,
                "protocol": g.protocol,
                "leader": g.leader,
                "member_id": member_id,
                "members": [(x.id, x.metadata)
                            for x in g.members.values()]
                if member_id == g.leader else [],
            }
            return NONE, resp

    @staticmethod
    def _pick_protocol(g: _Group) -> "str | None":
        """First protocol supported by every member."""
        if not g.members:
            return None
        first = next(iter(g.members.values()))
        for name, _ in first.protocols:
            if all(any(n == name for n, _ in m.protocols)
                   for m in g.members.values()):
                return name
        return None

    # -- SyncGroup ---------------------------------------------------------

    def sync(self, group_id: str, member_id: str, generation: int,
             assignments: "dict[str, bytes]"
             ) -> "tuple[int, bytes]":
        g = self._group(group_id)
        with g.cond:
            if member_id not in g.members:
                return UNKNOWN_MEMBER_ID, b""
            if generation != g.generation:
                return ILLEGAL_GENERATION, b""
            if g.state == "Joining":
                return REBALANCE_IN_PROGRESS, b""
            g.members[member_id].last_seen = time.monotonic()
            if member_id == g.leader and assignments:
                g.assignments = dict(assignments)
                g.state = "Stable"
                g.cond.notify_all()
            deadline = time.monotonic() + SYNC_TIMEOUT
            while g.state == "AwaitSync" and \
                    generation == g.generation:
                if time.monotonic() >= deadline:
                    return REBALANCE_IN_PROGRESS, b""
                g.cond.wait(timeout=0.05)
            if generation != g.generation or g.state != "Stable":
                # a new join round opened while we waited (join() or
                # leave() during AwaitSync): an empty assignment with
                # code 0 would read as "stable, own nothing"
                return REBALANCE_IN_PROGRESS, b""
            return NONE, g.assignments.get(member_id, b"")

    # -- Heartbeat / LeaveGroup -------------------------------------------

    def heartbeat(self, group_id: str, member_id: str,
                  generation: int) -> int:
        g = self._group(group_id)
        with g.cond:
            self._expire_locked(g)
            if member_id not in g.members:
                return UNKNOWN_MEMBER_ID
            g.members[member_id].last_seen = time.monotonic()
            if generation != g.generation:
                return ILLEGAL_GENERATION
            if g.state in ("Joining", "AwaitSync"):
                return REBALANCE_IN_PROGRESS
            return NONE

    def list_groups(self) -> "list[tuple[str, str]]":
        """(group_id, protocol_type) pairs for ListGroups (the
        coordinator's protocol type is always "consumer" here)."""
        with self._lock:
            groups = list(self._groups.values())
        out = []
        for g in groups:
            with g.cond:
                self._expire_locked(g)
                if g.members:
                    out.append((g.id, "consumer"))
        return sorted(out)

    def describe(self, group_id: str) -> "dict | None":
        """Full group view for DescribeGroups: state, protocol, and
        each member's subscription metadata + current assignment."""
        with self._lock:
            g = self._groups.get(group_id)
        if g is None:
            return None
        with g.cond:
            self._expire_locked(g)
            return {
                "state": g.state, "protocol": g.protocol,
                "protocol_type": "consumer" if g.members else "",
                "members": [{
                    "id": m.id,
                    "metadata": next(
                        (meta for name, meta in m.protocols
                         if name == g.protocol), m.metadata),
                    "assignment": g.assignments.get(m.id, b""),
                } for m in g.members.values()],
            }

    def drop(self, group_id: str) -> bool:
        """DeleteGroups coordinator side: forget the group entirely
        (caller has already checked it is member-less)."""
        with self._lock:
            return self._groups.pop(group_id, None) is not None

    def leave(self, group_id: str, member_id: str) -> int:
        g = self._group(group_id)
        with g.cond:
            if member_id not in g.members:
                return UNKNOWN_MEMBER_ID
            del g.members[member_id]
            if g.members:
                g.state = "Joining"
                g.round += 1
                g.round_opened = time.monotonic()
                g.assignments = {}
            else:
                g.state = "Empty"
                g.generation += 1
            g.cond.notify_all()
            return NONE
