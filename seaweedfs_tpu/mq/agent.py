"""MQ agent: a session facade in front of the broker cluster
(weed/mq/agent/agent_server.go; mq_agent.proto StartPublishSession /
PublishRecord / SubscribeRecord).

Clients talk to ONE local agent with a trivial session API instead of
carrying broker-routing, partition, and offset logic themselves — the
agent owns the MQClient (ownership redirects, partitioning) and the
per-session subscribe cursors with explicit acks (at-least-once:
un-acked records are redelivered after their lease lapses).

HTTP surface (the JSON twin of the agent gRPC service):
    POST /agent/sessions/publish    {namespace, topic}       -> {sessionId}
    POST /agent/publish             {sessionId, key, value}  -> {tsNs}
    POST /agent/sessions/subscribe  {namespace, topic}       -> {sessionId, partitions}
    GET  /agent/subscribe?sessionId=&maxRecords=&waitSec=    -> {records}
    POST /agent/ack                 {sessionId, partition, tsNs}
    POST /agent/sessions/close      {sessionId}
"""

from __future__ import annotations

import base64
import threading
import time
import uuid

from ..server.httpd import HttpServer, Request
from .client import MQClient

ACK_LEASE_SEC = 30.0


class _SubSession:
    def __init__(self, namespace: str, topic: str, partitions: int):
        self.namespace = namespace
        self.topic = topic
        self.partitions = partitions
        # committed offset per partition (acked); records after it may
        # be redelivered
        self.acked = {p: 0 for p in range(partitions)}
        # in-flight leases: partition -> (delivered_up_to, expires)
        self.leases: dict[int, tuple[int, float]] = {}
        self.lock = threading.Lock()


class AgentServer:
    def __init__(self, broker: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.client = MQClient(broker)
        self.http = HttpServer(host, port)
        self._sessions: dict[str, dict] = {}
        self._subs: dict[str, _SubSession] = {}
        self._lock = threading.Lock()
        r = self.http.route
        r("POST", "/agent/sessions/publish", self._start_publish)
        r("POST", "/agent/publish", self._publish)
        r("POST", "/agent/sessions/subscribe", self._start_subscribe)
        r("GET", "/agent/subscribe", self._subscribe)
        r("POST", "/agent/ack", self._ack)
        r("POST", "/agent/sessions/close", self._close)

    def start(self) -> "AgentServer":
        self.http.start()
        # the reference agent is a gRPC service (mq_agent.proto
        # SeaweedMessagingAgent); serve it beside the JSON-HTTP twin
        self.grpc_server, self.grpc_port = None, 0
        try:
            from ..pb.mq_service import start_agent_grpc
            self.grpc_server, self.grpc_port = start_agent_grpc(
                self, host=self.http.host)
        except ImportError:     # grpcio absent: HTTP-only mode
            pass
        except Exception as e:  # pragma: no cover — a real defect
            import sys
            print(f"agent {self.url}: gRPC plane failed to start: "
                  f"{e!r}", file=sys.stderr)
        return self

    def stop(self) -> None:
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop(grace=0.5).wait()
            self.grpc_server = None
        self.http.stop()

    @property
    def url(self) -> str:
        return self.http.url

    # -- publish sessions ----------------------------------------------

    def _start_publish(self, req: Request):
        b = req.json()
        ns, topic = b["namespace"], b["topic"]
        try:
            self.client.configure_topic(
                ns, topic, int(b.get("partitionCount", 4)))
        except RuntimeError:
            try:  # already configured (by a peer) is fine
                self.client.lookup(ns, topic)
            except RuntimeError as e:
                return 503, {"error": str(e)}
        sid = uuid.uuid4().hex
        with self._lock:
            self._sessions[sid] = {"kind": "publish",
                                   "namespace": ns, "topic": topic}
        return 200, {"sessionId": sid}

    def _publish(self, req: Request):
        b = req.json()
        with self._lock:
            sess = self._sessions.get(b.get("sessionId", ""))
        if sess is None or sess["kind"] != "publish":
            return 404, {"error": "unknown publish session"}
        try:
            ts = self.client.publish(
                sess["namespace"], sess["topic"],
                base64.b64decode(b.get("key", "")),
                base64.b64decode(b.get("value", "")))
        except RuntimeError as e:
            return 503, {"error": str(e)}
        return 200, {"tsNs": ts}

    # -- subscribe sessions --------------------------------------------

    def _start_subscribe(self, req: Request):
        b = req.json()
        ns, topic = b["namespace"], b["topic"]
        try:
            parts = self.client.lookup(ns, topic)
        except RuntimeError as e:
            return 404, {"error": str(e)}
        sid = uuid.uuid4().hex
        sub = _SubSession(ns, topic, len(parts))
        with self._lock:
            self._sessions[sid] = {"kind": "subscribe"}
            self._subs[sid] = sub
        return 200, {"sessionId": sid, "partitions": len(parts)}

    def _subscribe(self, req: Request):
        sid = req.query.get("sessionId", "")
        with self._lock:
            sub = self._subs.get(sid)
        if sub is None:
            return 404, {"error": "unknown subscribe session"}
        max_records = int(req.query.get("maxRecords", 100))
        deadline = time.time() + min(
            float(req.query.get("waitSec", 0)), 30.0)
        while True:
            records = self._collect(sub, max_records)
            if records or time.time() >= deadline:
                return 200, {"records": records}
            time.sleep(0.15)

    def _collect(self, sub: _SubSession, max_records: int
                 ) -> "list[dict]":
        out: list[dict] = []
        now = time.time()
        for p in range(sub.partitions):
            if len(out) >= max_records:
                break
            with sub.lock:
                lease = sub.leases.get(p)
                if lease is not None and lease[1] > now:
                    continue  # in flight, lease still valid
                since = sub.acked[p]
            try:
                msgs = self.client.subscribe(
                    sub.namespace, sub.topic, p, since_ns=since,
                    limit=max_records - len(out))
            except RuntimeError:
                continue
            if not msgs:
                with sub.lock:
                    sub.leases.pop(p, None)
                continue
            with sub.lock:
                sub.leases[p] = (msgs[-1].ts_ns,
                                 now + ACK_LEASE_SEC)
            for m in msgs:
                out.append({
                    "partition": p, "tsNs": m.ts_ns,
                    "key": base64.b64encode(m.key).decode(),
                    "value": base64.b64encode(m.value).decode(),
                })
        return out

    def _ack(self, req: Request):
        b = req.json()
        with self._lock:
            sub = self._subs.get(b.get("sessionId", ""))
        if sub is None:
            return 404, {"error": "unknown subscribe session"}
        p = int(b["partition"])
        ts = int(b["tsNs"])
        with sub.lock:
            if p in sub.acked and ts > sub.acked[p]:
                sub.acked[p] = ts
            lease = sub.leases.get(p)
            if lease is not None and ts >= lease[0]:
                sub.leases.pop(p, None)  # batch fully acked
        return 200, {}

    def _close(self, req: Request):
        sid = req.json().get("sessionId", "")
        with self._lock:
            self._sessions.pop(sid, None)
            self._subs.pop(sid, None)
        return 200, {}
