"""MQ client SDK (the analog of weed/mq/client/ pub_client/sub_client):
thin typed wrapper over the broker's JSON-HTTP surface."""

from __future__ import annotations

import base64
import urllib.parse
from dataclasses import dataclass

from ..server.httpd import http_json


def _q(**params) -> str:
    return urllib.parse.urlencode(params)


@dataclass
class Message:
    key: bytes
    value: bytes
    ts_ns: int


class MQClient:
    """Follows multi-broker partition ownership transparently: a
    broker answering {"error": "not owner", "owner": addr} gets the
    request re-dialed to the owner (pub_client's
    LookupTopicBrokers-then-connect, collapsed into redirects)."""

    MAX_HOPS = 8

    def __init__(self, broker: str):
        self.broker = broker

    def _call(self, method: str, path_qs: str,
              body: "dict | None" = None) -> dict:
        """Request against the seed broker, following ownership
        redirects.  A redirect target that turns out dead (crashed
        between the seed's liveness snapshot and our dial) falls back
        to the seed, which will take the partition over once its
        1s-TTL registry cache expires."""
        import time as _time
        target = self.broker
        deadline = _time.monotonic() + 8.0
        hops = 0
        r = {"error": "unreachable"}
        while _time.monotonic() < deadline:
            try:
                r = http_json(method, f"{target}{path_qs}", body)
            except OSError:
                if target == self.broker:
                    raise          # seed itself is down: surface it
                target = self.broker
                _time.sleep(0.4)   # let the seed notice the death
                continue
            if r.get("error") == "not owner" and r.get("owner"):
                hops += 1
                if r["owner"] == target or hops > self.MAX_HOPS:
                    return r       # ping-pong: give up with the error
                target = r["owner"]
                continue
            return r
        return r

    def configure_topic(self, namespace: str, topic: str,
                        partition_count: int = 4) -> int:
        r = http_json("POST", f"{self.broker}/topics/configure",
                      {"namespace": namespace, "topic": topic,
                       "partitionCount": partition_count})
        if "error" in r:
            raise RuntimeError(f"configure {namespace}.{topic}: "
                               f"{r['error']}")
        return len(r["partitions"])

    def lookup(self, namespace: str, topic: str) -> list[dict]:
        r = http_json("GET", f"{self.broker}/topics/lookup?" +
                      _q(namespace=namespace, topic=topic))
        if "error" in r:
            raise RuntimeError(r["error"])
        return r["assignments"]

    def publish(self, namespace: str, topic: str, key: bytes,
                value: bytes, partition: "int | None" = None) -> int:
        """Returns the message offset (tsNs).  `partition` pins an
        explicit partition index instead of key-hash routing (Kafka
        gateway semantics)."""
        body = {"namespace": namespace, "topic": topic,
                "key": base64.b64encode(key).decode(),
                "value": base64.b64encode(value).decode()}
        if partition is not None:
            body["partition"] = partition
        r = self._call("POST", "/topics/publish", body)
        if "error" in r:
            raise RuntimeError(f"publish: {r['error']}")
        return int(r["tsNs"])

    def subscribe(self, namespace: str, topic: str, partition: int,
                  since_ns: int = 0, limit: int = 1000
                  ) -> "list[Message]":
        return self.subscribe_full(namespace, topic, partition,
                                   since_ns, limit)[0]

    def publish_batch(self, namespace: str, topic: str,
                      partition: int,
                      messages: "list[tuple[bytes, bytes]]"
                      ) -> list[int]:
        """Atomic multi-publish to one partition; returns the
        assigned offsets in order."""
        r = self._call("POST", "/topics/publish_batch", {
            "namespace": namespace, "topic": topic,
            "partition": partition,
            "messages": [{"key": base64.b64encode(k).decode(),
                          "value": base64.b64encode(v).decode()}
                         for k, v in messages]})
        if "error" in r:
            raise RuntimeError(f"publish_batch: {r['error']}")
        return [int(t) for t in r["tsNs"]]

    def subscribe_full(self, namespace: str, topic: str,
                       partition: int, since_ns: int = 0,
                       limit: int = 1000
                       ) -> "tuple[list[Message], int]":
        """Like subscribe, but also returns the partition's
        high-water-mark tsNs (the Kafka gateway's fetch response
        needs it)."""
        r = self._call("GET", "/topics/subscribe?" +
                       _q(namespace=namespace, topic=topic,
                          partition=partition, sinceNs=since_ns,
                          limit=limit))
        if "error" in r:
            raise RuntimeError(f"subscribe: {r['error']}")
        msgs = [Message(base64.b64decode(m.get("key", "")),
                        base64.b64decode(m.get("value", "")),
                        int(m["tsNs"]))
                for m in r["messages"]]
        return msgs, int(r.get("highWaterMarkNs", 0))

    def list_topics(self, namespace: str) -> "list[str]":
        r = http_json("GET", f"{self.broker}/topics/list?" +
                      _q(namespace=namespace))
        if "error" in r:
            raise RuntimeError(f"list topics: {r['error']}")
        return r["topics"]

    def flush(self, namespace: str, topic: str) -> None:
        http_json("POST", f"{self.broker}/topics/flush",
                  {"namespace": namespace, "topic": topic})

    def delete_topic(self, namespace: str, topic: str) -> None:
        r = http_json("POST", f"{self.broker}/topics/delete",
                      {"namespace": namespace, "topic": topic})
        if "error" in r:
            raise RuntimeError(f"delete topic: {r['error']}")

    def repartition(self, namespace: str, topic: str,
                    partition_count: int) -> None:
        r = http_json("POST", f"{self.broker}/topics/repartition",
                      {"namespace": namespace, "topic": topic,
                       "partitionCount": partition_count},
                      timeout=60.0)
        if "error" in r:
            raise RuntimeError(f"repartition: {r['error']}")

    def commit_offset(self, group: str, namespace: str, topic: str,
                      partition: int, ts_ns: int) -> None:
        r = http_json("POST", f"{self.broker}/offsets/commit", {
            "group": group, "namespace": namespace, "topic": topic,
            "partition": partition, "tsNs": ts_ns})
        if "error" in r:
            raise RuntimeError(f"commit offset: {r['error']}")

    def delete_group_offsets(self, group: str) -> bool:
        """Kafka DeleteGroups backend: drop every committed offset of
        the group.  Returns whether any existed."""
        r = http_json("POST",
                      f"{self.broker}/offsets/delete_group",
                      {"group": group})
        if "error" in r:
            raise RuntimeError(f"delete group offsets: {r['error']}")
        return bool(r.get("existed"))

    def fetch_offset(self, group: str, namespace: str, topic: str,
                     partition: int) -> int:
        return self.fetch_offset_full(group, namespace, topic,
                                      partition)[0]

    def fetch_offset_full(self, group: str, namespace: str,
                          topic: str, partition: int
                          ) -> "tuple[int, bool]":
        """(tsNs, committed) — committed=False means no offset was
        ever stored (distinct from a commit at 0/-1)."""
        r = http_json("GET", f"{self.broker}/offsets/fetch?" +
                      _q(group=group, namespace=namespace,
                         topic=topic, partition=partition))
        if "error" in r:
            # an offset-store error must surface, not read as "start
            # from 0" (that would reprocess the whole partition)
            raise RuntimeError(f"fetch offset: {r['error']}")
        return int(r.get("tsNs", 0)), bool(r.get("committed", True))
