"""MQ client SDK (the analog of weed/mq/client/ pub_client/sub_client):
thin typed wrapper over the broker's JSON-HTTP surface."""

from __future__ import annotations

import base64
import urllib.parse
from dataclasses import dataclass

from ..server.httpd import http_json


def _q(**params) -> str:
    return urllib.parse.urlencode(params)


@dataclass
class Message:
    key: bytes
    value: bytes
    ts_ns: int


class MQClient:
    def __init__(self, broker: str):
        self.broker = broker

    def configure_topic(self, namespace: str, topic: str,
                        partition_count: int = 4) -> int:
        r = http_json("POST", f"{self.broker}/topics/configure",
                      {"namespace": namespace, "topic": topic,
                       "partitionCount": partition_count})
        if "error" in r:
            raise RuntimeError(f"configure {namespace}.{topic}: "
                               f"{r['error']}")
        return len(r["partitions"])

    def lookup(self, namespace: str, topic: str) -> list[dict]:
        r = http_json("GET", f"{self.broker}/topics/lookup?" +
                      _q(namespace=namespace, topic=topic))
        if "error" in r:
            raise RuntimeError(r["error"])
        return r["assignments"]

    def publish(self, namespace: str, topic: str, key: bytes,
                value: bytes) -> int:
        """Returns the message offset (tsNs)."""
        r = http_json("POST", f"{self.broker}/topics/publish", {
            "namespace": namespace, "topic": topic,
            "key": base64.b64encode(key).decode(),
            "value": base64.b64encode(value).decode()})
        if "error" in r:
            raise RuntimeError(f"publish: {r['error']}")
        return int(r["tsNs"])

    def subscribe(self, namespace: str, topic: str, partition: int,
                  since_ns: int = 0, limit: int = 1000
                  ) -> "list[Message]":
        r = http_json("GET", f"{self.broker}/topics/subscribe?" +
                      _q(namespace=namespace, topic=topic,
                         partition=partition, sinceNs=since_ns,
                         limit=limit))
        if "error" in r:
            raise RuntimeError(f"subscribe: {r['error']}")
        return [Message(base64.b64decode(m.get("key", "")),
                        base64.b64decode(m.get("value", "")),
                        int(m["tsNs"]))
                for m in r["messages"]]

    def flush(self, namespace: str, topic: str) -> None:
        http_json("POST", f"{self.broker}/topics/flush",
                  {"namespace": namespace, "topic": topic})

    def commit_offset(self, group: str, namespace: str, topic: str,
                      partition: int, ts_ns: int) -> None:
        r = http_json("POST", f"{self.broker}/offsets/commit", {
            "group": group, "namespace": namespace, "topic": topic,
            "partition": partition, "tsNs": ts_ns})
        if "error" in r:
            raise RuntimeError(f"commit offset: {r['error']}")

    def fetch_offset(self, group: str, namespace: str, topic: str,
                     partition: int) -> int:
        r = http_json("GET", f"{self.broker}/offsets/fetch?" +
                      _q(group=group, namespace=namespace,
                         topic=topic, partition=partition))
        if "error" in r:
            # an offset-store error must surface, not read as "start
            # from 0" (that would reprocess the whole partition)
            raise RuntimeError(f"fetch offset: {r['error']}")
        return int(r.get("tsNs", 0))
