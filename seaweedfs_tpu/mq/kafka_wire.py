"""Kafka wire primitives (reference: weed/mq/kafka/protocol/record.go
+ produce.go record-batch handling).

Implements the public Kafka protocol encodings this gateway speaks:
big-endian primitives, (nullable) strings/bytes, zigzag varints, the
CRC32C checksum, and the v2 RecordBatch on-disk/wire format — parsed
on Produce, emitted on Fetch.

One deliberate shape choice: Fetch responses emit ONE RecordBatch per
message.  Our partition offsets are timestamps (nanoseconds — sparse
and far apart), so in-batch offset deltas could overflow the int32
delta field; single-record batches keep every delta zero and are
fully legal Kafka framing (clients routinely see them from
compacted/re-batched logs)."""

from __future__ import annotations

import struct


# -- CRC32C (Castagnoli, reflected poly 0x82F63B78) ------------------------

def _make_crc32c_table():
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- primitives ------------------------------------------------------------

def enc_i8(v):
    return struct.pack(">b", v)


def enc_i16(v):
    return struct.pack(">h", v)


def enc_i32(v):
    return struct.pack(">i", v)


def enc_i64(v):
    return struct.pack(">q", v)


def enc_u32(v):
    return struct.pack(">I", v)


def enc_string(s: "str | None") -> bytes:
    if s is None:
        return enc_i16(-1)
    b = s.encode()
    return enc_i16(len(b)) + b


def enc_bytes(b: "bytes | None") -> bytes:
    if b is None:
        return enc_i32(-1)
    return enc_i32(len(b)) + b


def enc_array(items: list[bytes]) -> bytes:
    return enc_i32(len(items)) + b"".join(items)


def zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def enc_varint(n: int) -> bytes:
    """Zigzag varint (the record-level integer encoding)."""
    u = zigzag(n) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("kafka message truncated")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def i8(self):
        return struct.unpack(">b", self._take(1))[0]

    def i16(self):
        return struct.unpack(">h", self._take(2))[0]

    def i32(self):
        return struct.unpack(">i", self._take(4))[0]

    def i64(self):
        return struct.unpack(">q", self._take(8))[0]

    def u32(self):
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> "str | None":
        n = self.i16()
        return None if n < 0 else self._take(n).decode()

    def bytes_(self) -> "bytes | None":
        n = self.i32()
        return None if n < 0 else self._take(n)

    def varint(self) -> int:
        shift = u = 0
        while True:
            b = self._take(1)[0]
            u |= (b & 0x7F) << shift
            if not b & 0x80:
                return unzigzag(u)
            shift += 7
            if shift > 70:
                raise ValueError("varint overflow")

    def remaining(self) -> int:
        return len(self.data) - self.pos


# -- RecordBatch v2 --------------------------------------------------------

class BatchError(ValueError):
    pass


def decode_record_batches(data: bytes) -> list[dict]:
    """Parse a Produce record_set: one or more v2 RecordBatches.
    Returns [{key: bytes|None, value: bytes|None, ts_ms: int}] in
    order.  CRC and magic are verified — a corrupt batch must be
    rejected, not half-applied (produce.go CORRUPT_MESSAGE path)."""
    out = []
    r = Reader(data)
    while r.remaining() > 0:
        if r.remaining() < 61:
            raise BatchError("truncated record batch header")
        r.i64()                          # baseOffset (client fills 0)
        batch_len = r.i32()
        batch_body = Reader(r._take(batch_len))
        batch_body.i32()                 # partitionLeaderEpoch
        magic = batch_body.i8()
        if magic != 2:
            raise BatchError(f"unsupported magic {magic} (only v2)")
        crc = batch_body.u32()
        crc_bytes = batch_body.data[batch_body.pos:]
        if crc32c(crc_bytes) != crc:
            raise BatchError("record batch CRC mismatch")
        attributes = batch_body.i16()
        if attributes & 0x07:
            raise BatchError("compressed batches not supported")
        batch_body.i32()                 # lastOffsetDelta
        base_ts = batch_body.i64()
        batch_body.i64()                 # maxTimestamp
        batch_body.i64()                 # producerId
        batch_body.i16()                 # producerEpoch
        batch_body.i32()                 # baseSequence
        count = batch_body.i32()
        for _ in range(count):
            rec_len = batch_body.varint()
            rec = Reader(batch_body._take(rec_len))
            rec.i8()                     # record attributes
            ts_delta = rec.varint()
            rec.varint()                 # offsetDelta
            klen = rec.varint()
            key = None if klen < 0 else rec._take(klen)
            vlen = rec.varint()
            value = None if vlen < 0 else rec._take(vlen)
            # headers are parsed (framing must stay in sync) and
            # dropped — our MQ records carry key/value only
            for _ in range(rec.varint()):
                hk = rec.varint()
                rec._take(hk)
                hv = rec.varint()
                if hv > 0:
                    rec._take(hv)
            out.append({"key": key, "value": value,
                        "ts_ms": base_ts + ts_delta})
    return out


def encode_single_record_batch(offset: int, ts_ms: int,
                               key: "bytes | None",
                               value: "bytes | None") -> bytes:
    """One message as one v2 RecordBatch (see module docstring)."""
    rec = (enc_i8(0) +                   # attributes
           enc_varint(0) +               # timestampDelta
           enc_varint(0) +               # offsetDelta
           (enc_varint(-1) if key is None else
            enc_varint(len(key)) + key) +
           (enc_varint(-1) if value is None else
            enc_varint(len(value)) + value) +
           enc_varint(0))                # headers
    record = enc_varint(len(rec)) + rec
    after_crc = (enc_i16(0) +            # attributes
                 enc_i32(0) +            # lastOffsetDelta
                 enc_i64(ts_ms) +        # baseTimestamp
                 enc_i64(ts_ms) +        # maxTimestamp
                 enc_i64(-1) +           # producerId
                 enc_i16(-1) +           # producerEpoch
                 enc_i32(-1) +           # baseSequence
                 enc_i32(1) +            # record count
                 record)
    body = (enc_i32(0) +                 # partitionLeaderEpoch
            enc_i8(2) +                  # magic
            enc_u32(crc32c(after_crc)) +
            after_crc)
    return enc_i64(offset) + enc_i32(len(body)) + body
