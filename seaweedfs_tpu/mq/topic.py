"""Topic/partition model (weed/mq/topic/topic.go, partition.go).

A topic's keyspace is a hash ring of RING_SIZE slots (the reference's
`PartitionCount = 4096`, partition.go:10); a partition owns the
half-open slot range [range_start, range_stop).  A message's partition
is found by hashing its key onto the ring — so the partition count can
be chosen per topic while key→partition stays stable for a given
layout.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

RING_SIZE = 4096  # mq/topic/partition.go:10 PartitionCount


@dataclass(frozen=True)
class Topic:
    namespace: str
    name: str

    def __str__(self) -> str:
        return f"{self.namespace}.{self.name}"

    @property
    def dir(self) -> str:
        """Filer directory of this topic (mq/logstore layout:
        /topics/<namespace>/<topic>)."""
        return f"/topics/{self.namespace}/{self.name}"


@dataclass(frozen=True)
class Partition:
    range_start: int
    range_stop: int  # exclusive (partition.go:14)
    ring_size: int = RING_SIZE

    def __str__(self) -> str:
        return f"{self.range_start:04d}-{self.range_stop:04d}"

    def covers(self, slot: int) -> bool:
        return self.range_start <= slot < self.range_stop

    def to_json(self) -> dict:
        return {"rangeStart": self.range_start,
                "rangeStop": self.range_stop,
                "ringSize": self.ring_size}

    @classmethod
    def from_json(cls, d: dict) -> "Partition":
        return cls(int(d["rangeStart"]), int(d["rangeStop"]),
                   int(d.get("ringSize", RING_SIZE)))


def split_ring(partition_count: int,
               ring_size: int = RING_SIZE) -> "list[Partition]":
    """Evenly split the ring into partition_count ranges
    (topic.go SplitPartitions)."""
    if not 0 < partition_count <= ring_size:
        raise ValueError(f"bad partition count {partition_count}")
    step = ring_size / partition_count
    out = []
    for i in range(partition_count):
        start = int(i * step)
        stop = int((i + 1) * step) if i < partition_count - 1 \
            else ring_size
        out.append(Partition(start, stop, ring_size))
    return out


def partition_slot(key: bytes, ring_size: int = RING_SIZE) -> int:
    """Stable key→slot hash.  The reference uses util.HashToInt32 %
    ring; any stable hash preserves the contract (same key → same
    partition for a fixed layout) — md5 avoids Python's per-process
    hash randomization."""
    return int.from_bytes(hashlib.md5(key).digest()[:4], "big") % \
        ring_size


def partition_for_key(key: bytes, partitions: "list[Partition]"
                      ) -> Partition:
    slot = partition_slot(key, partitions[0].ring_size)
    for p in partitions:
        if p.covers(slot):
            return p
    raise ValueError(f"slot {slot} uncovered by {partitions}")
