"""Minimal Kafka protocol client (no kafka library exists in the
image), used by tests and tooling to drive the gateway the way the
reference's gateway tests use a real client: every byte crosses a TCP
socket in genuine Kafka framing, including CRC-checked v2 record
batches on produce.

Supports exactly the gateway's advertised API versions; consumers can
use manual partition assignment or the full group rebalance dance
(GroupConsumer below: client-side range assignor, heartbeats,
rejoin-on-rebalance)."""

from __future__ import annotations

import socket
import struct
import threading

from .kafka_wire import (Reader, crc32c, decode_record_batches,
                         enc_array, enc_bytes, enc_i8, enc_i16,
                         enc_i32, enc_i64, enc_string, enc_u32,
                         enc_varint)


class KafkaError(RuntimeError):
    def __init__(self, code: int, where: str):
        super().__init__(f"kafka error {code} in {where}")
        self.code = code


def encode_produce_batch(records: "list[tuple[bytes | None, bytes]]",
                         base_ts_ms: int = 0) -> bytes:
    """A single v2 RecordBatch holding `records` — what a real
    producer sends (deltas are small: sequential indexes)."""
    recs = b""
    for i, (key, value) in enumerate(records):
        body = (enc_i8(0) + enc_varint(0) + enc_varint(i) +
                (enc_varint(-1) if key is None else
                 enc_varint(len(key)) + key) +
                enc_varint(len(value)) + value +
                enc_varint(0))
        recs += enc_varint(len(body)) + body
    after_crc = (enc_i16(0) + enc_i32(len(records) - 1) +
                 enc_i64(base_ts_ms) + enc_i64(base_ts_ms) +
                 enc_i64(-1) + enc_i16(-1) + enc_i32(-1) +
                 enc_i32(len(records)) + recs)
    body = enc_i32(0) + enc_i8(2) + enc_u32(crc32c(after_crc)) + \
        after_crc
    return enc_i64(0) + enc_i32(len(body)) + body


class KafkaClient:
    def __init__(self, host: str, port: int,
                 client_id: str = "seaweedfs-tpu-test",
                 username: str = "", password: str = ""):
        self.sock = socket.create_connection((host, port), timeout=30)
        self.client_id = client_id
        self._corr = 0
        self._lock = threading.Lock()
        if username:
            try:
                self.sasl_plain(username, password)
            except BaseException:
                # the constructor raising means no object escapes:
                # close the socket here or every failed-auth retry
                # leaks a file descriptor
                self.sock.close()
                raise

    def sasl_plain(self, username: str, password: str) -> None:
        """SaslHandshake(17) + SaslAuthenticate(36) with RFC 4616
        PLAIN tokens (the framed flow modern brokers use)."""
        r = self._rpc(17, 1, enc_string("PLAIN"))
        code = r.i16()
        if code:
            raise KafkaError(code, "SaslHandshake")
        token = b"\x00" + username.encode() + b"\x00" + \
            password.encode()
        r = self._rpc(36, 1, enc_bytes(token))
        code = r.i16()
        msg = r.string()
        if code:
            raise KafkaError(code, f"SaslAuthenticate: {msg}")

    def close(self):
        self.sock.close()

    def _rpc(self, api_key: int, api_version: int,
             body: bytes) -> Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            frame = (enc_i16(api_key) + enc_i16(api_version) +
                     enc_i32(corr) + enc_string(self.client_id) +
                     body)
            self.sock.sendall(struct.pack(">i", len(frame)) + frame)
            buf = b""
            while len(buf) < 4:
                chunk = self.sock.recv(65536)
                if not chunk:
                    # peer closed: raising (not spinning on b"") lets
                    # long-running callers (notification sink) re-dial
                    raise OSError("kafka connection closed by peer")
                buf += chunk
            size = struct.unpack(">i", buf[:4])[0]
            while len(buf) < 4 + size:
                chunk = self.sock.recv(65536)
                if not chunk:
                    raise OSError("kafka connection closed mid-frame")
                buf += chunk
        r = Reader(buf[4:4 + size])
        got = r.i32()
        if got != corr:
            raise KafkaError(-1, f"correlation {got} != {corr}")
        return r

    # -- APIs --------------------------------------------------------------

    def api_versions(self) -> dict[int, tuple[int, int]]:
        r = self._rpc(18, 0, b"")
        code = r.i16()
        if code:
            raise KafkaError(code, "ApiVersions")
        return {key: (lo, hi) for key, lo, hi in
                ((r.i16(), r.i16(), r.i16())
                 for _ in range(r.i32()))}

    def metadata(self, topics: "list[str] | None" = None) -> dict:
        body = enc_i32(-1) if topics is None else \
            enc_array([enc_string(t) for t in topics])
        r = self._rpc(3, 1, body)
        brokers = [(r.i32(), r.string(), r.i32(), r.string())
                   for _ in range(r.i32())]
        r.i32()                          # controller id
        out = {"brokers": brokers, "topics": {}}
        for _ in range(r.i32()):
            code = r.i16()
            name = r.string()
            r.i8()                       # is_internal
            parts = []
            for _ in range(r.i32()):
                pcode = r.i16()
                pid = r.i32()
                r.i32()                  # leader
                for _ in range(r.i32()):
                    r.i32()              # replicas
                for _ in range(r.i32()):
                    r.i32()              # isr
                parts.append((pid, pcode))
            out["topics"][name] = {"error": code, "partitions": parts}
        return out

    def create_topic(self, name: str, partitions: int = 4) -> int:
        body = enc_array([
            enc_string(name) + enc_i32(partitions) + enc_i16(1) +
            enc_i32(0) + enc_i32(0)]) + enc_i32(10000)
        r = self._rpc(19, 0, body)
        r.i32()
        r.string()
        return r.i16()

    def delete_topic(self, name: str) -> int:
        """DeleteTopics v0: returns the per-topic error code."""
        body = enc_array([enc_string(name)]) + enc_i32(10000)
        r = self._rpc(20, 0, body)
        r.i32()                          # results count
        r.string()                       # topic name
        return r.i16()

    def create_partitions(self, name: str, count: int,
                          validate_only: bool = False
                          ) -> "tuple[int, str | None]":
        """CreatePartitions v0: (error_code, error_message)."""
        body = (enc_array([enc_string(name) + enc_i32(count) +
                           enc_i32(-1)]) +
                enc_i32(30000) + enc_i8(1 if validate_only else 0))
        r = self._rpc(37, 0, body)
        r.i32()                          # throttle
        r.i32()                          # results count
        r.string()                       # topic
        return r.i16(), r.string()

    def init_producer_id(self) -> "tuple[int, int]":
        """InitProducerId v0: (producer_id, epoch)."""
        body = enc_string(None) + enc_i32(60000)
        r = self._rpc(22, 0, body)
        r.i32()                          # throttle
        code = r.i16()
        if code:
            raise KafkaError(code, "InitProducerId")
        return r.i64(), r.i16()

    def delete_groups(self, groups: "list[str]",
                      version: int = 1) -> "dict[str, int]":
        """DeleteGroups: {group: error_code}."""
        body = enc_array([enc_string(g) for g in groups])
        r = self._rpc(42, version, body)
        if version >= 1:
            r.i32()                      # throttle
        return {r.string(): r.i16() for _ in range(r.i32())}

    def list_groups(self) -> "list[tuple[str, str]]":
        r = self._rpc(16, 0, b"")
        code = r.i16()
        if code:
            raise KafkaError(code, "ListGroups")
        return [(r.string(), r.string()) for _ in range(r.i32())]

    def describe_groups(self, groups: "list[str]") -> list[dict]:
        body = enc_array([enc_string(g) for g in groups])
        r = self._rpc(15, 0, body)
        out = []
        for _ in range(r.i32()):
            code = r.i16()
            d = {"error": code, "group": r.string(),
                 "state": r.string(),
                 "protocol_type": r.string(),
                 "protocol": r.string(), "members": []}
            for _ in range(r.i32()):
                d["members"].append({
                    "id": r.string(), "client_id": r.string(),
                    "host": r.string(),
                    "metadata": r.bytes_() or b"",
                    "assignment": r.bytes_() or b""})
            out.append(d)
        return out

    def describe_configs(self, topic: str) -> "dict[str, str]":
        body = enc_array([enc_i8(2) + enc_string(topic) +
                          enc_i32(-1)])
        r = self._rpc(32, 0, body)
        r.i32()                          # throttle
        n = r.i32()
        assert n == 1
        code = r.i16()
        r.string()                       # error message
        if code:
            raise KafkaError(code, "DescribeConfigs")
        r.i8()                           # resource type
        r.string()                       # resource name
        out = {}
        for _ in range(r.i32()):
            key, value = r.string(), r.string()
            r.i8()                       # read_only
            r.i8()                       # is_default
            r.i8()                       # is_sensitive
            out[key] = value
        return out

    def produce(self, topic: str, partition: int,
                records: "list[tuple[bytes | None, bytes]]") -> int:
        """Returns the base offset; raises on per-partition error."""
        batch = encode_produce_batch(records)
        body = (enc_string(None) + enc_i16(-1) + enc_i32(10000) +
                enc_array([enc_string(topic) + enc_array([
                    enc_i32(partition) + enc_bytes(batch)])]))
        r = self._rpc(0, 3, body)
        base = -1
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                code = r.i16()
                base = r.i64()
                r.i64()
                if code:
                    raise KafkaError(code, "Produce")
        return base

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20
              ) -> "tuple[list[dict], int]":
        """Returns ([{key, value, ts_ms, offset}...], high_watermark).
        Record offsets are the batch base offsets (one record per
        batch from this gateway)."""
        body = (enc_i32(-1) + enc_i32(100) + enc_i32(1) +
                enc_i32(max_bytes) + enc_i8(0) +
                enc_array([enc_string(topic) + enc_array([
                    enc_i32(partition) + enc_i64(offset) +
                    enc_i32(max_bytes)])]))
        r = self._rpc(1, 4, body)
        r.i32()                          # throttle
        msgs, hwm = [], 0
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                code = r.i16()
                hwm = r.i64()
                r.i64()                  # last stable offset
                for _ in range(r.i32()):
                    r.i64()
                    r.i64()              # aborted txns
                record_set = r.bytes_() or b""
                if code:
                    raise KafkaError(code, "Fetch")
                msgs.extend(self._parse_fetch_batches(record_set))
        return msgs, hwm

    @staticmethod
    def _parse_fetch_batches(data: bytes) -> list[dict]:
        out = []
        rr = Reader(data)
        while rr.remaining() > 0:
            base_offset = rr.i64()
            batch_len = rr.i32()
            batch = rr._take(batch_len)
            for rec in decode_record_batches(
                    enc_i64(base_offset) + enc_i32(batch_len) +
                    batch):
                rec["offset"] = base_offset
                out.append(rec)
        return out

    def list_offsets(self, topic: str, partition: int,
                     ts: int = -1) -> int:
        body = (enc_i32(-1) +
                enc_array([enc_string(topic) + enc_array([
                    enc_i32(partition) + enc_i64(ts)])]))
        r = self._rpc(2, 1, body)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                code = r.i16()
                r.i64()
                off = r.i64()
                if code:
                    raise KafkaError(code, "ListOffsets")
                return off
        raise KafkaError(-1, "ListOffsets: empty response")

    def find_coordinator(self, group: str) -> "tuple[str, int]":
        r = self._rpc(10, 0, enc_string(group))
        code = r.i16()
        if code:
            raise KafkaError(code, "FindCoordinator")
        r.i32()
        return r.string(), r.i32()

    def offset_commit(self, group: str, topic: str, partition: int,
                      offset: int) -> None:
        body = (enc_string(group) + enc_i32(-1) + enc_string("") +
                enc_i64(-1) +
                enc_array([enc_string(topic) + enc_array([
                    enc_i32(partition) + enc_i64(offset) +
                    enc_string(None)])]))
        r = self._rpc(8, 2, body)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                code = r.i16()
                if code:
                    raise KafkaError(code, "OffsetCommit")

    def offset_fetch(self, group: str, topic: str,
                     partition: int) -> int:
        body = (enc_string(group) +
                enc_array([enc_string(topic) + enc_array([
                    enc_i32(partition)])]))
        r = self._rpc(9, 1, body)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                off = r.i64()
                r.string()
                code = r.i16()
                if code:
                    raise KafkaError(code, "OffsetFetch")
                return off
        raise KafkaError(-1, "OffsetFetch: empty response")


# -- consumer groups (client side of the rebalance dance) ------------------

def encode_subscription(topics: "list[str]") -> bytes:
    """Consumer protocol subscription v0 (the bytes inside JoinGroup
    protocol metadata)."""
    return (enc_i16(0) +
            enc_array([enc_string(t) for t in topics]) +
            enc_bytes(b""))


def decode_subscription(blob: bytes) -> "list[str]":
    r = Reader(blob)
    r.i16()
    return [r.string() or "" for _ in range(r.i32())]


def encode_assignment(parts: "dict[str, list[int]]") -> bytes:
    """Consumer protocol assignment v0."""
    return (enc_i16(0) +
            enc_array([enc_string(t) +
                       enc_array([enc_i32(p) for p in ps])
                       for t, ps in sorted(parts.items())]) +
            enc_bytes(b""))


def decode_assignment(blob: bytes) -> "dict[str, list[int]]":
    if not blob:
        return {}
    r = Reader(blob)
    r.i16()
    out = {}
    for _ in range(r.i32()):
        t = r.string() or ""
        out[t] = [r.i32() for _ in range(r.i32())]
    return out


class GroupConsumer:
    """subscribe()-style consumer: joins the group, runs the range
    assignor when elected leader, heartbeats, and rejoins on
    rebalance signals — the client half of protocol/joingroup.go."""

    def __init__(self, client: KafkaClient, group: str,
                 topics: "list[str]",
                 session_timeout_ms: int = 10000):
        self.client = client
        self.group = group
        self.topics = list(topics)
        self.session_timeout_ms = session_timeout_ms
        self.member_id = ""
        self.generation = -1
        self.assignment: dict[str, list[int]] = {}

    def join(self) -> "dict[str, list[int]]":
        """(Re)join until a stable assignment lands."""
        for _ in range(20):
            body = (enc_string(self.group) +
                    enc_i32(self.session_timeout_ms) +
                    enc_string(self.member_id) +
                    enc_string("consumer") +
                    enc_array([enc_string("range") + enc_bytes(
                        encode_subscription(self.topics))]))
            r = self.client._rpc(11, 0, body)
            code = r.i16()
            generation = r.i32()
            r.string()                    # protocol
            leader = r.string() or ""
            member_id = r.string() or ""
            members = [(r.string() or "", r.bytes_() or b"")
                       for _ in range(r.i32())]
            if code == 25:               # UNKNOWN_MEMBER_ID: reset
                self.member_id = ""
                continue
            if code == 27:               # rebalance superseded us
                continue
            if code:
                raise KafkaError(code, "JoinGroup")
            self.member_id = member_id
            self.generation = generation
            assignments = {}
            if member_id == leader:
                assignments = self._range_assign(members)
            sync = (enc_string(self.group) +
                    enc_i32(self.generation) +
                    enc_string(self.member_id) +
                    enc_array([enc_string(mid) + enc_bytes(blob)
                               for mid, blob in
                               sorted(assignments.items())]))
            r = self.client._rpc(14, 0, sync)
            code = r.i16()
            mine = r.bytes_() or b""
            if code in (22, 27):         # stale generation/rebalance
                continue
            if code:
                raise KafkaError(code, "SyncGroup")
            self.assignment = decode_assignment(mine)
            return self.assignment
        raise KafkaError(-1, "JoinGroup: never stabilized")

    def _range_assign(self, members) -> "dict[str, bytes]":
        """The classic range assignor over every member's
        subscription."""
        subs = {mid: decode_subscription(meta)
                for mid, meta in members}
        per_member: dict[str, dict[str, list[int]]] = \
            {mid: {} for mid in subs}
        topics = sorted({t for ts in subs.values() for t in ts})
        md = self.client.metadata(topics) if topics else \
            {"topics": {}}
        for topic in topics:
            info = md["topics"].get(topic, {})
            count = len(info.get("partitions", []))
            wanting = sorted(m for m, ts in subs.items()
                             if topic in ts)
            if not wanting or not count:
                continue
            per = count // len(wanting)
            extra = count % len(wanting)
            start = 0
            for i, mid in enumerate(wanting):
                n = per + (1 if i < extra else 0)
                per_member[mid][topic] = list(range(start, start + n))
                start += n
        return {mid: encode_assignment(parts)
                for mid, parts in per_member.items()}

    def heartbeat(self) -> int:
        """0 = stable; ANY nonzero code means the caller must
        join() again (27 rebalance, 22 stale generation, 25 expelled
        — on 25 the member id is reset so the rejoin starts fresh)."""
        body = (enc_string(self.group) + enc_i32(self.generation) +
                enc_string(self.member_id))
        code = self.client._rpc(12, 0, body).i16()
        if code == 25:               # UNKNOWN_MEMBER_ID: expelled
            self.member_id = ""
        return code

    def leave(self) -> None:
        body = enc_string(self.group) + enc_string(self.member_id)
        self.client._rpc(13, 0, body).i16()
