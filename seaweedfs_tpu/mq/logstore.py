"""Filer-backed partition log segments (weed/mq/logstore/).

Each partition's messages live as JSON-line segment files under the
topic's filer directory:

    /topics/<ns>/<topic>/<rangeStart>-<rangeStop>/<tsNs>.log

— the reference's layout (logstore/log_to_parquet.go reads
/topics/<ns>/<t>/<partition>/ date dirs; we keep one level, named by
first-message timestamp so segments sort chronologically).  A hot
in-memory tail buffer absorbs appends and flushes to the filer when it
reaches FLUSH_BYTES or on demand — the shape of the reference's
log_buffer (util/log_buffer/) whose pages also flush to filer chunks.

Offsets ARE timestamps (strictly monotonic per partition, same rule as
the filer MetaLog): a subscriber resumes with `> tsNs` and can never
skip a same-stamp sibling.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse

from ..server.httpd import http_bytes
from ..util.log_buffer import LogBuffer
from .topic import Partition, Topic

FLUSH_BYTES = 256 * 1024


class PartitionLog:
    def __init__(self, filer: str, topic: Topic, partition: Partition):
        self.filer = filer
        self.topic = topic
        self.partition = partition
        self.dir = f"{topic.dir}/{partition}"
        # hot tail page (util/log_buffer): fills -> _flush_records
        # persists a filer segment; reads merge snapshot() on top of
        # the persisted segments
        self._buf = LogBuffer(self._flush_records, FLUSH_BYTES)
        self._last_ts = 0
        self._last_flushed_ts = 0
        # flushed-behind ring (util/log_buffer's prevBuffers,
        # log_buffer.go ReadFromBuffer): recently flushed pages stay
        # in memory, so a subscriber resuming within the ring's
        # coverage window is served ENTIRELY from memory — no filer
        # round-trips for hot tails (VERDICT r4 #10).  _ring_floor is
        # the newest stamp NOT covered by ring+buffer: reads with
        # ts_ns >= _ring_floor never need the persisted segments.
        from collections import deque
        self._ring: "deque[list[dict]]" = deque()
        self._ring_bytes = 0
        self._ring_floor = 0
        # lifetime payload-byte counter (monotonic): the broker's
        # hot-partition detector samples deltas of this to compute
        # append rates (pub balancer auto-split role)
        self.appended_bytes = 0
        self._lock = threading.Lock()

    # flushed pages retained in memory for hot tail reads
    RING_MAX_BYTES = 4 << 20
    RING_MAX_PAGES = 32

    # -- append -----------------------------------------------------------

    # a client-supplied stamp may lead the server clock by at most this
    # much; beyond it the server stamps instead — one far-future tsNs
    # would otherwise ratchet the partition's offset clock forever
    # (persisted in segments, surviving restarts)
    MAX_CLIENT_SKEW_NS = 5 * 60 * 1_000_000_000

    def append(self, key_b64: str, value_b64: str,
               ts_ns: int = 0) -> int:
        """Returns the assigned (strictly monotonic) offset tsNs."""
        with self._lock:
            if self._last_ts == 0:
                # resume the stamp clock above persisted history, so a
                # restarted broker can never assign an offset below an
                # already-served one.  The persisted hwm is also the
                # last FLUSHED stamp — seeding both keeps the
                # buffer-only read short-circuit honest after restart
                self._last_ts = self._persisted_hwm()
                self._last_flushed_ts = self._last_ts
                self._ring_floor = self._last_ts
            now = time.time_ns()
            ts = int(ts_ns) or now
            if ts > now + self.MAX_CLIENT_SKEW_NS:
                ts = now
            if ts <= self._last_ts:
                ts = self._last_ts + 1
            self._last_ts = ts
            rec = {"tsNs": ts, "key": key_b64, "value": value_b64}
            self._buf.add(rec, len(value_b64) + len(key_b64) + 32)
            # RAW payload bytes (b64 inflates 4/3; the operator's
            # MB/min threshold is in payload terms)
            self.appended_bytes += \
                (len(value_b64) + len(key_b64)) * 3 // 4
            return ts

    def append_many(self, records: "list[tuple[str, str, int]]"
                    ) -> list[int]:
        """Atomic multi-append: all of [(key_b64, value_b64, ts_ns)]
        land under one lock hold, or none do (the Kafka gateway's
        per-partition batch guarantee — a retried batch must not
        duplicate a committed prefix)."""
        with self._lock:
            if self._last_ts == 0:
                self._last_ts = self._persisted_hwm()
                self._last_flushed_ts = self._last_ts
                self._ring_floor = self._last_ts
            out = []
            now = time.time_ns()
            for key_b64, value_b64, ts_ns in records:
                ts = int(ts_ns) or now
                if ts > now + self.MAX_CLIENT_SKEW_NS:
                    ts = now
                if ts <= self._last_ts:
                    ts = self._last_ts + 1
                self._last_ts = ts
                self._buf.add({"tsNs": ts, "key": key_b64,
                               "value": value_b64},
                              len(value_b64) + len(key_b64) + 32)
                self.appended_bytes += \
                    (len(value_b64) + len(key_b64)) * 3 // 4
                out.append(ts)
            return out

    def flush(self) -> None:
        with self._lock:
            self._buf.flush()  # noqa: SWFS012 — explicit broker sync point (stop-then-flush invariant); appends buffer

    def _flush_records(self, recs: "list[dict]") -> None:
        """LogBuffer sink: one filer segment per flushed page.
        Caller holds the lock (LogBuffer flushes synchronously from
        append/flush, which hold it)."""
        body = "\n".join(json.dumps(r, separators=(",", ":"))
                         for r in recs).encode() + b"\n"
        name = f"{recs[0]['tsNs']:020d}.log"
        st, resp, _ = http_bytes(
            "POST", f"{self.filer}{urllib.parse.quote(self.dir)}/"
            f"{name}", body)
        if st >= 300:
            raise RuntimeError(
                f"mq: flush segment {self.dir}/{name}: {st} "
                f"{resp[:200]!r}")
        # retain the page in the flushed-behind ring (coverage floor
        # moves only when pages evict)
        if not self._ring:
            self._ring_floor = self._last_flushed_ts
        # store the page WITH its size: eviction must subtract exactly
        # what append added or the accounting drifts and eventually
        # evicts every page on arrival (dead ring)
        self._ring.append((recs, len(body)))
        self._ring_bytes += len(body)
        while self._ring and (
                self._ring_bytes > self.RING_MAX_BYTES or
                len(self._ring) > self.RING_MAX_PAGES):
            evicted, evicted_bytes = self._ring.popleft()
            self._ring_bytes -= evicted_bytes
            self._ring_floor = evicted[-1]["tsNs"]
        self._last_flushed_ts = recs[-1]["tsNs"]

    # -- read -------------------------------------------------------------

    def read_since(self, ts_ns: int, limit: int = 0) -> "list[dict]":
        """Messages with tsNs > ts_ns, oldest first: persisted segments
        (name-pruned — a segment named by its first stamp can be
        skipped when the NEXT segment starts <= ts_ns) then the hot
        buffer."""
        out: list[dict] = []
        with self._lock:
            # hot-path short-circuit: a tailing consumer whose resume
            # point is covered by the flushed-behind ring + live
            # buffer needs no filer I/O (log_buffer.go ReadFromBuffer
            # memory window)
            if self._last_ts and ts_ns >= self._ring_floor:
                for page, _sz in self._ring:
                    if page[-1]["tsNs"] <= ts_ns:
                        continue    # whole page at/before resume point
                    for rec in page:
                        if rec["tsNs"] > ts_ns:
                            out.append(rec)
                            if limit and len(out) >= limit:
                                return out
                for rec in self._buf.snapshot():
                    if rec["tsNs"] > ts_ns:
                        out.append(rec)
                        if limit and len(out) >= limit:
                            break
                return out
        # The persisted scan restarts from a fresh listing when a
        # listed segment 404s mid-read: a concurrent compaction
        # deleted it, and skipping it while returning LATER segments'
        # rows would advance the consumer's offset past messages now
        # living in the parquet — permanent loss.  Within one pass,
        # emitted stamps are forced strictly increasing, which drops
        # the exact-duplicate rows a crashed compaction can leave
        # (parquet written, victim logs not yet deleted).
        for _attempt in range(4):
            out = []
            if self._scan_persisted(ts_ns, limit, out):
                break
        else:
            raise RuntimeError(
                f"mq: segments under {self.dir} kept vanishing "
                f"mid-read (compaction storm?)")
        if limit and len(out) >= limit:
            return out[:limit]
        # buffer rows continue the strictly-increasing guard: a flush
        # racing this read could otherwise surface a row both from its
        # fresh segment and the buffer snapshot
        last = out[-1]["tsNs"] if out else ts_ns
        with self._lock:
            for rec in self._buf.snapshot():
                if rec["tsNs"] > last:
                    out.append(rec)
                    if limit and len(out) >= limit:
                        break
        return out

    def _scan_persisted(self, ts_ns: int, limit: int,
                        out: "list[dict]") -> bool:
        """One pass over the persisted segments appending rows with
        stamp > ts_ns to `out` (strictly increasing).  False = a listed
        segment vanished (caller re-lists); True = pass completed (or
        the limit was reached)."""
        segs = self._list_segments()
        # prune: keep segments that may contain stamps > ts_ns
        keep: list[str] = []
        for i, name in enumerate(segs):
            first_next = int(segs[i + 1].split(".")[0]) \
                if i + 1 < len(segs) else None
            if first_next is not None and first_next <= ts_ns:
                continue
            keep.append(name)
        last = ts_ns
        for name in keep:
            if name.endswith(".parquet"):
                # merged read (logstore/merged_read.go): compacted
                # columnar segments replay through the same sequence,
                # byte-exact via their raw _key/_value columns
                from .parquet_store import read_parquet_rows
                for rec in read_parquet_rows(self.filer, self.dir,
                                             name, last):
                    if rec["tsNs"] > last:
                        last = rec["tsNs"]
                        out.append(rec)
                        if limit and len(out) >= limit:
                            return True
                continue
            st, body, _ = http_bytes(
                "GET", f"{self.filer}{urllib.parse.quote(self.dir)}/"
                f"{name}")
            if st == 404:
                return False  # compacted away under us: re-list
            if st != 200:
                continue
            for line in body.splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("tsNs", 0) > last:
                    last = rec["tsNs"]
                    out.append(rec)
                    if limit and len(out) >= limit:
                        return True
        return True

    def _list_segments(self) -> "list[str]":
        st, body, _ = http_bytes(
            "GET", f"{self.filer}{urllib.parse.quote(self.dir)}/"
            f"?limit=1000000")
        if st != 200:
            return []
        names = [e["fullPath"].rsplit("/", 1)[-1]
                 for e in json.loads(body).get("entries", [])
                 if not e.get("isDirectory")]
        # both log and compacted parquet segments, one chronological
        # sequence (both are named by their first message stamp).
        # parquet_store._list_files shares this listing protocol.
        return sorted(n for n in names
                      if n.endswith((".log", ".parquet")))

    def high_water_mark(self) -> int:
        """Newest offset in this partition (0 if empty)."""
        with self._lock:
            if self._last_ts:
                return self._last_ts
        hwm = self._persisted_hwm()
        with self._lock:
            # cache: an idle partition polled after a restart must not
            # re-list + re-download the newest segment on every poll.
            # Seed BOTH stamps, exactly like append()'s first-use path:
            # _last_ts without _last_flushed_ts would make read_since's
            # buffer-only short-circuit (ts_ns >= _last_flushed_ts)
            # skip ALL persisted history on the next read.
            if self._last_ts == 0:
                self._last_ts = hwm
                self._last_flushed_ts = hwm
                # restart: the ring is empty, so memory coverage
                # begins strictly after the persisted history
                self._ring_floor = hwm
        return hwm

    def _persisted_hwm(self) -> int:
        """Newest stamp in the persisted segments (no lock taken)."""
        segs = self._list_segments()
        if not segs:
            return 0
        if segs[-1].endswith(".parquet"):
            from .parquet_store import parquet_max_ts
            return parquet_max_ts(self.filer, self.dir, segs[-1])
        st, body, _ = http_bytes(
            "GET", f"{self.filer}{urllib.parse.quote(self.dir)}/"
            f"{segs[-1]}")
        last = 0
        if st == 200:
            for line in body.splitlines():
                try:
                    last = max(last, json.loads(line).get("tsNs", 0))
                except ValueError:
                    continue
        return last
