"""MQ broker (weed/mq/broker/broker_server.go:51 MessageQueueBroker).

JSON-HTTP mirror of the broker gRPC surface (pb/mq_broker.proto):

  POST /topics/configure {namespace, topic, partitionCount}
      <- ConfigureTopic: splits the hash ring into partitions and
         persists the layout to the filer (topic.conf), so every broker
         and a restarted broker agree on key->partition routing.
  GET  /topics/lookup?namespace=&topic=
      <- LookupTopicBrokers: partition layout + owning broker urls.
  POST /topics/publish {namespace, topic, key, value(b64), tsNs?}
      <- PublishMessage: routes by key hash to the partition, appends
         to its filer-backed log, returns {partition, tsNs} (the
         offset).
  GET  /topics/subscribe?namespace=&topic=&partition=&sinceNs=&limit=
      <- SubscribeMessage (poll form, like the filer's events stream):
         replayable from any offset; offsets are strictly monotonic
         per-partition timestamps.
  POST /offsets/commit {group, namespace, topic, partition, tsNs}
  GET  /offsets/fetch?group=&namespace=&topic=&partition=
      <- consumer-group offset store (mq/kafka consumer_offset/),
         persisted via the filer so committed positions survive broker
         restarts.
  POST /topics/flush {namespace, topic} — force segment flush (tests,
         graceful shutdown).

Multi-broker (mq/pub_balancer/ analog over our shared-filer plane):
brokers register heartbeat files under /topics/.brokers/; configure
allocates partitions round-robin across LIVE brokers and persists the
assignment in topic.conf; publish/subscribe for a partition another
broker owns answer 409 {"owner": addr} and the client re-dials; when
an owner's heartbeat goes stale, the broker asked next TAKES OVER the
partition (rewrites the assignment) — safe because partition logs
live in the filer, so ownership is coordination, not data placement.
The acked-but-unflushed tail of a crashed owner (≤ flush_interval) is
lost, the same crash semantics as single-broker.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.parse

from ..util import wlog
from ..server.httpd import HttpServer, Request, http_bytes
from .logstore import PartitionLog
from .topic import Partition, Topic, partition_for_key, split_ring

OFFSETS_DIR = "/topics/.offsets"
BROKERS_DIR = "/topics/.brokers"


class NameError_(ValueError):
    pass


class _RWLock:
    """Small writer-preferring read/write lock for the per-topic conf
    fence (review r5): appends take the read side so different
    partitions of one topic append concurrently; conf mutations
    (configure / takeover / repartition claim+drain) and the flush
    broadcast take the write side, which waits out in-flight admitted
    appends — the property the write-loss fence needs — without
    serializing the whole hot path on one mutex."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._waiting_writers = 0

    class _Side:
        def __init__(self, lock, write):
            self._lock, self._write = lock, write

        def __enter__(self):
            acq = self._lock._acquire_write if self._write \
                else self._lock._acquire_read
            acq()
            return self

        def __exit__(self, *exc):
            rel = self._lock._release_write if self._write \
                else self._lock._release_read
            rel()

    def read(self) -> "_RWLock._Side":
        return _RWLock._Side(self, write=False)

    def write(self) -> "_RWLock._Side":
        return _RWLock._Side(self, write=True)

    def _acquire_read(self):
        with self._cond:
            # writer preference: new readers queue behind a waiting
            # writer so a drain can't be starved by a publish stream
            while self._writer or self._waiting_writers:
                self._cond.wait()
            self._readers += 1

    def _release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def _acquire_write(self):
        with self._cond:
            self._waiting_writers += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
                self._writer = True
            finally:
                self._waiting_writers -= 1

    def _release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


def _check_name(kind: str, name: str) -> None:
    """Topic/namespace/group names become filer path segments: a '/'
    would add path levels, a leading '.' collides with reserved dirs
    (.offsets), empty collapses segments."""
    if not name or "/" in name or name.startswith("."):
        raise NameError_(f"invalid {kind} name {name!r}")


class _LocalReq:
    """Minimal Request shim for internally-driven route handlers
    (auto-split calling _repartition)."""

    def __init__(self, payload: dict):
        self._payload = payload
        self.query: dict = {}

    def json(self) -> dict:
        return self._payload


class BrokerServer:
    # a broker whose heartbeat is older than this is dead for
    # assignment/takeover purposes (pub_balancer liveness analog)
    BROKER_TTL = 5.0

    def __init__(self, filer: str, host: str = "127.0.0.1",
                 port: int = 0, flush_interval: float = 1.0,
                 auto_split_mb_per_min: float = 0.0,
                 auto_split_max_partitions: int = 64):
        self.filer = filer
        # hot-partition auto-split (pub balancer partition-lifecycle
        # role): when any single partition's append rate exceeds the
        # threshold, the topic's partition count doubles via the
        # fenced repartition path.  0 disables.
        self.auto_split_bytes_per_sec = \
            auto_split_mb_per_min * (1 << 20) / 60.0
        self.auto_split_max_partitions = auto_split_max_partitions
        self._split_samples: dict = {}   # (topic,partition) -> bytes
        self._splitting: set = set()     # topics mid-auto-split
        self.http = HttpServer(host, port)
        self._topics: dict[Topic, list[Partition]] = {}
        # parallel to _topics: owning broker address per partition
        self._owners: dict[Topic, list[str]] = {}
        self._conf_loaded: dict[Topic, float] = {}
        self._live_cache: tuple[float, list[str]] = (0.0, [])
        self._logs: dict[tuple[Topic, Partition], PartitionLog] = {}
        self._lock = threading.Lock()
        # per-topic conf locks serialize each topic's load-check-
        # persist-cache sequences (configure/repartition/takeover)
        # against each other AND against fenced appends + flushes of
        # that topic — per-topic so one topic's long repartition drain
        # never stalls publishes to unrelated topics (review r5).
        # Guarded by self._lock.
        self._topic_conf_locks: dict[Topic, _RWLock] = {}
        # topics this broker is actively repartitioning: publishes to
        # them answer 503-retry so the drain below is authoritative
        # (guarded by self._lock)
        self._repartitioning: set[Topic] = set()
        # peer-side delete fence (guarded by self._lock): when a PEER
        # broker is deleting a topic, publishes here must not pass a
        # <=CONF_TTL-stale owner gate and append into dirs the delete
        # is about to remove — _flush_all would resurrect the topic
        # with orphan messages.  Values are wall-clock expiry stamps;
        # after expiry a fresh conf load finds the conf gone -> 404.
        self._deleting: dict[Topic, float] = {}
        # periodic flush bounds the acked-but-unflushed window to
        # ~flush_interval on a crash (the reference's log_buffer also
        # flushes on a timer, util/log_buffer)
        self._flush_interval = flush_interval
        self._stop_event = threading.Event()
        self._flush_thread: threading.Thread | None = None
        r = self.http.route
        r("POST", "/topics/configure", self._configure)
        r("GET", "/topics/lookup", self._lookup)
        r("GET", "/topics/list", self._list_topics)
        r("POST", "/topics/publish", self._publish)
        r("POST", "/topics/publish_batch", self._publish_batch)
        r("GET", "/topics/subscribe", self._subscribe)
        r("POST", "/topics/flush", self._flush)
        r("POST", "/offsets/commit", self._commit_offset)
        r("GET", "/offsets/fetch", self._fetch_offset)
        r("POST", "/offsets/delete_group", self._delete_group_offsets)
        # schema plane (weed/mq/schema) + parquet compaction
        # (weed/mq/logstore/log_to_parquet.go)
        r("POST", "/topics/schema", self._schema_register)
        r("GET", "/topics/schema", self._schema_get)
        r("POST", "/topics/compact", self._compact)
        r("POST", "/topics/repartition", self._repartition)
        r("POST", "/topics/balance", self._balance)
        r("POST", "/topics/truncate", self._truncate)
        r("POST", "/topics/delete", self._delete_topic)
        # topic -> (revision, recordType) cache for publish validation
        self._schema_cache: dict = {}
        self._schema_cache_ts: dict = {}

    def start(self) -> "BrokerServer":
        self.http.start()
        # the reference's broker API is gRPC (mq_broker.proto
        # SeaweedMessaging); serve it beside the JSON-HTTP twin
        self.grpc_server, self.grpc_port = None, 0
        try:
            from ..pb.mq_service import start_broker_grpc
            self.grpc_server, self.grpc_port = start_broker_grpc(
                self, host=self.http.host)
        except ImportError:     # grpcio absent: HTTP-only mode
            pass
        except Exception as e:  # pragma: no cover — a real defect
            wlog.error(f"broker {self.url}: gRPC plane failed to start: "
                  f"{e!r}")
        self._heartbeat()
        self._flush_thread = threading.Thread(target=self._flush_loop,
                                              daemon=True)
        self._flush_thread.start()
        return self

    # -- broker registry (pub_balancer AddBroker/RemoveBroker) ------------

    def _heartbeat(self) -> None:
        try:
            http_bytes("POST",
                       f"{self.filer}{BROKERS_DIR}/{self.url}",
                       json.dumps({"ts": time.time()}).encode())
        except OSError:
            pass  # next tick

    def _registry_entries(self) -> list[dict]:
        """Raw broker-registry listing.  Fails CLOSED: an unreadable
        registry must not read as "every peer is dead" — that would
        green-light takeovers of healthy brokers' partitions."""
        try:
            st, body, _ = http_bytes(
                "GET", f"{self.filer}{BROKERS_DIR}/?limit=1000")
        except OSError as e:
            raise RuntimeError(f"broker registry unreachable: {e}")
        if st == 404:
            return []               # registry dir not created yet
        if st != 200:
            raise RuntimeError(f"broker registry: {st}")
        try:
            entries = json.loads(body).get("entries", [])
        except ValueError as e:
            raise RuntimeError(f"broker registry undecodable: {e}")
        return [e for e in entries
                if not e.get("isDirectory") and "fullPath" in e]

    def _live_brokers(self) -> list[str]:
        """Registry entries with fresh heartbeats, briefly cached
        (publish-path takeover checks must not hammer the filer)."""
        now = time.monotonic()
        ts, cached = self._live_cache
        if now - ts < 1.0:
            return cached
        live = []
        # registry mtimes are cross-process wall timestamps — the wall
        # clock is the only clock both sides share
        cutoff = time.time() - self.BROKER_TTL  # noqa: SWFS011
        for e in self._registry_entries():
            if e.get("attributes", {}).get("mtime", 0) >= cutoff:
                live.append(e["fullPath"].rsplit("/", 1)[-1])
        if self.url not in live:
            live.append(self.url)   # we are definitionally alive
        live.sort()
        self._live_cache = (now, live)
        return live

    def _registered_brokers(self) -> list[str]:
        """EVERY registry entry, liveness-filter skipped — the
        repartition flush broadcast must also reach a peer whose
        heartbeat merely lapsed (alive-but-deregistered peers still
        hold conf caches and tails)."""
        return sorted(e["fullPath"].rsplit("/", 1)[-1]
                      for e in self._registry_entries())

    def stop(self) -> None:
        # stop accepting requests FIRST: a publish acked after the
        # flush loop but before http shutdown would be lost
        if getattr(self, "grpc_server", None) is not None:
            # stop() is non-blocking (returns an Event); WAIT before
            # flushing, or an in-flight gRPC publish could append+ack
            # after _flush_all and lose an acknowledged message
            self.grpc_server.stop(grace=0.5).wait()
            self.grpc_server = None
        self.http.stop()
        self._stop_event.set()
        # join BEFORE deregistering: a heartbeat racing past the
        # event check would re-register us after the DELETE
        if self._flush_thread is not None:
            self._flush_thread.join(timeout=10)
        self._flush_all()
        try:    # deregister so peers take over without waiting TTL
            http_bytes("DELETE",
                       f"{self.filer}{BROKERS_DIR}/{self.url}")
        except OSError:
            pass

    def _flush_loop(self) -> None:
        while not self._stop_event.wait(self._flush_interval):
            self._flush_all()
            self._heartbeat()
            if self.auto_split_bytes_per_sec > 0:
                try:
                    self._maybe_auto_split()
                except Exception as e:  # noqa: BLE001 — detector must
                    wlog.warning(       # not kill the flush loop
                        "auto-split detector: %s", e, component="mq")

    def _maybe_auto_split(self) -> None:
        """Sample per-partition append-byte deltas; a partition
        hotter than the threshold doubles its topic's partition count
        through the fenced repartition path (splitting spreads the
        keyspace, so the hot partition's range halves)."""
        now = time.monotonic()
        with self._lock:
            snapshot = [(t, p, log.appended_bytes)
                        for (t, p), log in self._logs.items()]
        hot: "set[Topic]" = set()
        for t, p, total in snapshot:
            with self._lock:   # _split_samples shared w/ delete+split
                prev_total, prev_ts = self._split_samples.get(
                    (t, p), (total, now))
                self._split_samples[(t, p)] = (total, now)
            dt = now - prev_ts
            if dt <= 0:
                continue
            if (total - prev_total) / dt > self.auto_split_bytes_per_sec:
                hot.add(t)
        for t in hot:
            try:
                parts = self._load_layout(t)
            except RuntimeError:
                continue
            if parts is None or \
                    len(parts) * 2 > self.auto_split_max_partitions:
                continue
            with self._lock:
                if t in self._splitting:
                    continue
                self._splitting.add(t)
            # NOT inline: a repartition can take seconds (cluster
            # lock + CONF_TTL wait + drain) and this loop is also the
            # broker's heartbeat — blocking it past BROKER_TTL would
            # get this broker declared dead mid-split
            threading.Thread(target=self._auto_split_one,
                             args=(t, len(parts) * 2),
                             daemon=True).start()

    def _auto_split_one(self, t: Topic, new_n: int) -> None:
        try:
            status, _body = self._repartition(_LocalReq({
                "namespace": t.namespace, "topic": t.name,
                "partitionCount": new_n}))
            if status == 200:
                # fresh rate baselines for the new partitions
                with self._lock:
                    self._split_samples = {
                        k: v for k, v in self._split_samples.items()
                        if k[0] != t}
        finally:
            with self._lock:
                self._splitting.discard(t)

    def _flush_all(self) -> None:
        with self._lock:
            logs = list(self._logs.values())
        for log in logs:
            try:
                log.flush()
            except Exception as e:  # noqa: BLE001 — best-effort;
                wlog.warning(       # retried on the next tick
                    "partition flush failed: %s", e, component="mq")

    @property
    def url(self) -> str:
        return self.http.url

    # -- topic layout -----------------------------------------------------

    def _conf_path(self, t: Topic) -> str:
        return f"{t.dir}/topic.conf"

    def _topic_lock(self, t: Topic) -> "_RWLock":
        """The topic's conf read/write lock (created on first use):
        appends read-side, conf mutations + flush write-side."""
        with self._lock:
            lk = self._topic_conf_locks.get(t)
            if lk is None:
                lk = self._topic_conf_locks[t] = _RWLock()
            return lk

    # how long a cached topic.conf (and its ownership column) stays
    # authoritative; peers\' takeovers become visible within this —
    # the split-brain window of the registry-based coordination (a
    # cluster lock would close it; documented divergence)
    CONF_TTL = 2.0

    def _load_layout(self, t: Topic, fresh: bool = False
                     ) -> "list[Partition] | None":
        """None means CONFIRMED not-configured (filer 404).  A filer
        error raises — conflating it with 'not configured' would let
        _configure overwrite an existing layout during a filer blip,
        silently re-routing every stored key."""
        with self._lock:
            if not fresh and t in self._topics and \
                    time.monotonic() - self._conf_loaded.get(t, 0) \
                    < self.CONF_TTL:
                return self._topics[t]
        st, body, _ = http_bytes(
            "GET", self.filer + urllib.parse.quote(self._conf_path(t)))
        if st == 404:
            return None
        if st != 200:
            raise RuntimeError(f"filer {self.filer} topic.conf: {st}")
        raw = json.loads(body)["partitions"]
        parts = [Partition.from_json(p) for p in raw]
        # pre-assignment confs carry no broker field: self-owned
        owners = [p.get("broker") or self.url for p in raw]
        with self._lock:
            self._topics[t] = parts
            self._owners[t] = owners
            self._conf_loaded[t] = time.monotonic()
        return parts

    def _persist_layout(self, t: Topic, parts: "list[Partition]",
                        owners: "list[str]") -> "str | None":
        doc = [dict(p.to_json(), broker=o)
               for p, o in zip(parts, owners)]
        st, _, _ = http_bytes(
            "POST", self.filer + urllib.parse.quote(self._conf_path(t)),
            json.dumps({"partitions": doc}).encode())
        if st >= 300:
            return f"persist layout: {st}"
        with self._lock:
            self._topics[t] = parts
            self._owners[t] = list(owners)
            self._conf_loaded[t] = time.monotonic()
        return None

    def _owner_gate(self, t: Topic, parts: "list[Partition]",
                    idx: int) -> "tuple[int, dict] | None":
        """None when this broker may serve partition idx (it owns it,
        or it just took over from a dead owner); otherwise the
        redirect response.  Takeover rule (pub_balancer repair.go
        shape): the owner must be absent from the live registry."""
        with self._lock:
            owners = self._owners.get(t) or [self.url] * len(parts)
            owner = owners[idx] if idx < len(owners) else self.url
        if owner == self.url:
            return None
        try:
            live = self._live_brokers()
        except RuntimeError as e:
            return 503, {"error": str(e)}
        if owner in live:
            return 409, {"error": "not owner", "owner": owner,
                         "partition": idx}
        # owner is dead: take the partition over under the CLUSTER
        # lock (filer-hosted lock ring, cluster/lock_manager.py) —
        # without it two brokers can pass the dead-owner check
        # concurrently and clobber each other's conf rewrite (the
        # round-3 ~CONF_TTL split-brain window).  The fresh re-read
        # inside the lock sees any claim a peer completed first.
        from ..cluster import ClusterLock
        try:
            takeover_lock = ClusterLock(
                self.filer, f"mq-conf:{self._conf_path(t)}",
                owner=self.url, ttl_sec=10.0).acquire(timeout=5.0)
        except (TimeoutError, OSError) as e:
            return 503, {"error": f"takeover lock: {e}"}
        try:
            with self._topic_lock(t).write():
                try:
                    self._load_layout(t, fresh=True)
                except RuntimeError as e:
                    return 503, {"error": str(e)}
                with self._lock:
                    owners = list(self._owners.get(t) or
                                  [self.url] * len(parts))
                if owners[idx] == owner:     # still the dead one
                    if not takeover_lock.is_held():
                        return 503, {"error": "takeover lock lost"}
                    owners[idx] = self.url
                    err = self._persist_layout(t, parts, owners)
                    if err:
                        return 503, {"error": err}
                elif owners[idx] != self.url:
                    return 409, {"error": "not owner",
                                 "owner": owners[idx],
                                 "partition": idx}
        finally:
            takeover_lock.release()
        return None

    def _topic_from(self, ns: str, name: str) -> Topic:
        _check_name("namespace", ns)
        _check_name("topic", name)
        return Topic(ns, name)

    # -- repartition (partition split/merge) ---------------------------

    def _repartition(self, req: Request):
        """Change a topic's partition count (the reference's partition
        split/merge role, topic.go SplitPartitions + balancer
        reconciliation), preserving every stored message and its
        order: all existing messages are merged chronologically, re-
        hashed by key onto the new ring, and appended with their
        original stamps; old partition dirs are deleted after the new
        conf is live.  Runs under the CLUSTER lock.

        Write-loss fencing (ADVICE r4 + review): (a) this broker
        refuses publishes to the topic for the duration (503-retry),
        (b) after claiming ownership we wait out CONF_TTL so every
        peer's layout cache expires and its next publish redirects
        here, (c) a flush broadcast then pushes anything peers acked
        into filer segments before the drain, and (d) publish paths
        re-gate at append time when their layout cache aged out, so a
        peer stalled in validation past the window redirects instead
        of appending to a log we already drained.  The conf-plane
        lock is NOT held across the sleep/broadcast — only the two
        short conf mutations take it."""
        import base64 as _b64

        from ..cluster import ClusterLock
        b = req.json()
        try:
            t = self._topic_from(b["namespace"], b["topic"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        new_n = int(b["partitionCount"])
        if not 0 < new_n <= 4096:
            return 400, {"error": f"bad partition count {new_n}"}
        try:
            lock = ClusterLock(
                self.filer, f"mq-conf:{self._conf_path(t)}",
                owner=self.url, ttl_sec=30.0).acquire(timeout=10.0)
        except (TimeoutError, OSError) as e:
            return 503, {"error": f"repartition lock: {e}"}
        with self._lock:
            # claim-or-fail (review r5): the set is shared with
            # _delete_topic; blindly adding would let this op's
            # finally-discard drop a concurrent owner's publish fence
            if t in self._repartitioning:
                lock.release()
                return 503, {"error": "another repartition/delete "
                                      "of this topic is in progress; "
                                      "retry"}
            self._repartitioning.add(t)
        old_owners = None
        claimed = False

        def _rollback_claim():
            """An abort after step 1's claim must restore the previous
            owner column (review r5): leaving this broker as persisted
            sole owner of every partition would silently funnel the
            topic's whole load here after a FAILED operation."""
            if not (claimed and old_owners):
                return ""
            with self._topic_lock(t).write():
                err = self._persist_layout(t, old_parts, old_owners)
            return f"; owner rollback failed: {err}" if err \
                else "; owners rolled back"

        try:
            # 1. claim every partition: a conf naming this broker as
            # sole owner makes peers redirect here, so no new writes
            # land on logs we're about to drain
            with self._topic_lock(t).write():
                try:
                    old_parts = self._load_layout(t, fresh=True)
                except RuntimeError as e:
                    return 503, {"error": str(e)}
                if old_parts is None:
                    return 404, {"error": f"topic {t} not configured"}
                if len(old_parts) == new_n:
                    return 200, {"partitions":
                                 [p.to_json() for p in old_parts],
                                 "migrated": 0}
                with self._lock:
                    old_owners = list(self._owners.get(t) or
                                      [self.url] * len(old_parts))
                err = self._persist_layout(
                    t, old_parts, [self.url] * len(old_parts))
                if err:
                    return 503, {"error": err}
                claimed = True
            # 1.5 wait out peer layout caches, then flush peer tails:
            # a peer with a <=CONF_TTL-stale conf still passes its own
            # owner gate and keeps appending to the old partition logs
            # after our claim; once CONF_TTL elapses every peer
            # re-reads the conf and redirects here.  The flush
            # broadcast then pushes whatever landed in peers'
            # in-memory tails during the window into filer segments,
            # so the drain below migrates those acknowledged messages
            # instead of deleting them with the old dirs in step 4.
            # The broadcast goes to EVERY registered broker — a peer
            # whose heartbeat lapsed may still be alive with a fresh
            # conf cache; only a peer that is both unreachable AND
            # outside the live set is treated as crashed (its
            # unflushed tail is lost under the module's documented
            # crash semantics).
            try:
                live = set(self._live_brokers())
                registered = set(self._registered_brokers())
            except RuntimeError as e:
                return 503, {"error": f"broker registry: {e}"
                             + _rollback_claim()}
            peers = sorted((registered | live) - {self.url})
            if peers:
                time.sleep(self.CONF_TTL + 0.1)
            for peer in peers:
                # bare address: http_bytes' dial funnel applies the
                # configured scheme (TLS plane) — hardcoding http://
                # would silently skip TLS-only peers
                try:
                    st_f, body_f, _ = http_bytes(
                        "POST", f"{peer}/topics/flush",
                        json.dumps({"namespace": t.namespace,
                                    "topic": t.name}).encode())
                except OSError as e:
                    st_f, body_f = 0, str(e).encode()
                if st_f != 200 and peer in live:
                    # a LIVE peer whose tail we cannot confirm flushed
                    # may hold acked messages step 4 would delete —
                    # abort (restoring the previous owners); the
                    # operator retries once the peer flushes or drops
                    # from the registry
                    return 503, {
                        "error": f"peer {peer} flush unconfirmed "
                                 f"({st_f}): "
                                 f"{body_f[:200].decode(errors='replace')}"
                                 + _rollback_claim()}
            with self._topic_lock(t).write():
                # 2. drain: flush hot tails, then merge every stored
                # message chronologically
                msgs: list = []
                for p in old_parts:
                    log = self._log_for(t, p)
                    log.flush()
                    msgs.extend(log.read_since(0))
                msgs.sort(key=lambda r: r.get("tsNs", 0))
                # 3. new layout + re-hash with original stamps (the
                # per-partition monotonic clock bumps exact ties)
                new_parts = split_ring(new_n)
                new_logs = {}
                with self._lock:
                    # forget old log objects so fresh dirs are used
                    for p in old_parts:
                        self._logs.pop((t, p), None)
                migrated = 0
                for rec in msgs:
                    key = _b64.b64decode(rec.get("key", "") or "")
                    p = partition_for_key(key, new_parts)
                    if p not in new_logs:
                        new_logs[p] = PartitionLog(self.filer, t, p)
                    new_logs[p].append(rec.get("key", ""),
                                       rec.get("value", ""),
                                       int(rec.get("tsNs", 0)))
                    migrated += 1
                for log in new_logs.values():
                    log.flush()
                # 4. publish the new conf, then delete old dirs
                err = self._persist_layout(
                    t, new_parts, [self.url] * new_n)
                if err:
                    return 503, {"error": err}
                old_dirs = {str(p) for p in old_parts} - \
                    {str(p) for p in new_parts}
                for d in old_dirs:
                    http_bytes(
                        "DELETE",
                        f"{self.filer}"
                        f"{urllib.parse.quote(t.dir + '/' + d)}"
                        f"?recursive=true")
            return 200, {"partitions":
                         [p.to_json() for p in new_parts],
                         "migrated": migrated}
        finally:
            with self._lock:
                self._repartitioning.discard(t)
            lock.release()

    def _balance(self, req: Request):
        """mq.balance (pub_balancer BalanceTopicPartitionOnBrokers):
        reassign every topic's partition ownership round-robin across
        the LIVE brokers and persist the layouts.  Peers pick the new
        routing up within CONF_TTL; in-memory tails are flushed first
        so no acked message is stranded on a de-owned broker."""
        from ..cluster import ClusterLock
        try:
            live = self._live_brokers()
        except RuntimeError as e:
            return 503, {"error": str(e)}
        try:
            namespaces = self._namespaces()
        except RuntimeError as e:
            return 503, {"error": str(e)}
        moved = 0
        topics = []
        for ns in namespaces:
            st2, body2, _ = http_bytes(
                "GET", f"{self.filer}/topics/{ns}/?limit=1000")
            if st2 != 200:
                continue
            for t_e in json.loads(body2).get("entries", []):
                if t_e.get("isDirectory"):
                    topics.append(Topic(
                        ns, t_e["fullPath"].rsplit("/", 1)[-1]))
        import hashlib as _hashlib
        changed: list[Topic] = []
        for t in topics:
            try:
                lock = ClusterLock(
                    self.filer, f"mq-conf:{self._conf_path(t)}",
                    owner=self.url, ttl_sec=15.0).acquire(timeout=5.0)
            except (TimeoutError, OSError):
                continue    # busy topic: next balance run
            try:
                with self._topic_lock(t).write():
                    try:
                        parts = self._load_layout(t, fresh=True)
                    except RuntimeError:
                        continue
                    if not parts:
                        continue
                    with self._lock:
                        old = list(self._owners.get(t) or
                                   [self.url] * len(parts))
                    # per-topic starting offset: plain round-robin
                    # from live[0] would pile every single-partition
                    # topic onto ONE broker — the exact skew balance
                    # exists to fix
                    base = int(_hashlib.sha1(
                        str(t).encode()).hexdigest()[:8], 16)
                    new = [live[(base + i) % len(live)]
                           for i in range(len(parts))]
                    if new != old:
                        if self._persist_layout(t, parts, new) is None:
                            moved += sum(1 for a, b in zip(old, new)
                                         if a != b)
                            changed.append(t)
            finally:
                lock.release()
        if changed:
            # Stranding fence (same shape as _repartition): wait out
            # every broker's conf cache so de-owned brokers stop
            # admitting appends, then have EVERY registered broker
            # (self included) flush its tails for the moved topics AND
            # drop log objects for partitions it no longer owns — a
            # retained PartitionLog's memory window would later hide
            # the interim owner's persisted messages.
            time.sleep(self.CONF_TTL + 0.1)
            try:
                registered = set(self._registered_brokers()) | \
                    {self.url}
            except RuntimeError as e:
                return 503, {"error": f"broker registry: {e}",
                             "movedPartitions": moved}
            unflushed = []
            for t in changed:
                for peer in sorted(registered):
                    try:
                        st_f, _, _ = http_bytes(
                            "POST", f"{peer}/topics/flush",
                            json.dumps({"namespace": t.namespace,
                                        "topic": t.name}).encode())
                    except OSError:
                        st_f = 0
                    if st_f != 200:
                        unflushed.append(f"{t}@{peer}")
            if unflushed:
                return 503, {"error":
                             "balance applied but tails unconfirmed "
                             "on: " + ", ".join(unflushed[:10]),
                             "movedPartitions": moved}
        return 200, {"brokers": live, "topics": len(topics),
                     "movedPartitions": moved}

    def _truncate(self, req: Request):
        """mq.topic.truncate: drop a topic's stored messages, keeping
        its configuration/layout.  Peer brokers drop their in-memory
        tails FIRST (localOnly broadcast) — an owning peer would
        otherwise keep serving (and later re-flushing) pre-truncate
        messages from its LogBuffer."""
        b = req.json()
        try:
            t = self._topic_from(b["namespace"], b["topic"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        with self._topic_lock(t).write():
            try:
                parts = self._load_layout(t, fresh=True)
            except RuntimeError as e:
                return 503, {"error": str(e)}
            if parts is None:
                return 404, {"error": f"topic {t} not configured"}
            with self._lock:
                for p in parts:
                    self._logs.pop((t, p), None)
                if b.get("invalidateConf"):
                    # the caller is DELETING the topic: our cached
                    # layout must not authorize any more appends, and
                    # the fence outlives CONF_TTL so a republish
                    # cannot sneak in on a stale owner column before
                    # the conf file disappears
                    self._topics.pop(t, None)
                    self._owners.pop(t, None)
                    self._conf_loaded.pop(t, None)
                    self._deleting[t] = time.time() + \
                        self.CONF_TTL * 2
        if not b.get("localOnly"):
            try:
                peers = [p for p in self._registered_brokers()
                         if p != self.url]
            except RuntimeError as e:
                return 503, {"error": str(e)}
            peer_failures = []
            for peer in peers:
                try:
                    st_p, body_p, _ = http_bytes(
                        "POST", f"{peer}/topics/truncate",
                        json.dumps({
                            "namespace": t.namespace,
                            "topic": t.name,
                            "localOnly": True,
                            "invalidateConf":
                                bool(b.get("invalidateConf")),
                        }).encode())
                except OSError as e:
                    st_p, body_p = 0, str(e).encode()
                if st_p != 200:
                    peer_failures.append(
                        f"{peer}: {st_p} {body_p[:80]!r}")
            if peer_failures:
                # an unreachable-but-ALIVE peer still holds its tail
                # and would re-flush the "truncated" messages later —
                # abort BEFORE deleting dirs so state stays coherent
                # (registered-but-crashed peers: deregister them or
                # retry once they drop from the registry)
                return 503, {"error": "peer tails not dropped: "
                                      + "; ".join(peer_failures)}
            failures = []
            with self._topic_lock(t).write():
                for p in parts:
                    try:
                        st_d, body_d, _ = http_bytes(
                            "DELETE",
                            f"{self.filer}"
                            f"{urllib.parse.quote(t.dir + '/' + str(p))}"
                            f"?recursive=true")
                    except OSError as e:
                        st_d, body_d = 0, str(e).encode()
                    if st_d not in (200, 204, 404):
                        failures.append(f"{p}: {st_d} "
                                        f"{body_d[:100]!r}")
            if failures:
                # persisted segments survive: a fresh PartitionLog
                # would serve the "truncated" messages again — say so
                return 500, {"error": "partition dirs not deleted: "
                                      + "; ".join(failures)}
        return 200, {"truncated": len(parts)}

    def _delete_topic(self, req: Request):
        """Remove a topic entirely — messages, layout conf, schema,
        and committed group offsets (the Kafka DeleteTopics role).
        Rides the truncate flow first (peer in-memory tails must drop
        BEFORE dirs die or they re-flush "deleted" messages), then
        removes the whole topic directory.  Local publishes are
        fenced for the duration (503-retry; after completion they get
        the honest 404)."""
        b = req.json()
        try:
            t = self._topic_from(b["namespace"], b["topic"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        with self._lock:
            # claim-or-fail: an in-flight repartition (auto-split)
            # would otherwise re-create the conf/dirs mid-delete, and
            # our finally-discard would drop ITS publish fence
            if t in self._repartitioning:
                return 503, {"error": "repartition of this topic is "
                                      "in progress; retry"}
            self._repartitioning.add(t)   # publish fence + op claim
        try:
            status, body = self._truncate(_LocalReq(
                {"namespace": t.namespace, "topic": t.name,
                 "invalidateConf": True}))
            if status != 200:
                return status, body
            # conf file FIRST: once it is gone, any fresh layout load
            # anywhere answers 404, independent of the peers' fence
            # windows — then the directory tree
            try:
                http_bytes("DELETE",
                           f"{self.filer}"
                           f"{urllib.parse.quote(self._conf_path(t))}")
            except OSError:
                pass    # recursive dir delete below still covers it
            try:
                st_d, body_d, _ = http_bytes(
                    "DELETE",
                    f"{self.filer}{urllib.parse.quote(t.dir)}"
                    f"?recursive=true")
            except OSError as e:
                st_d, body_d = 0, str(e).encode()
            if st_d not in (200, 204, 404):
                return 500, {"error": f"topic dir not deleted: "
                                      f"{st_d} {body_d[:100]!r}"}
            # committed consumer-group offsets die with the topic — a
            # recreated topic must not resume consumers from stale
            # pre-delete positions
            self._delete_topic_offsets(t)
            with self._lock:
                self._topics.pop(t, None)
                self._owners.pop(t, None)
                self._conf_loaded.pop(t, None)
                self._schema_cache.pop(t, None)
                self._schema_cache_ts.pop(t, None)
                # a publish racing the truncate may have re-created
                # log objects; drop them or _flush_all resurrects the
                # topic dir with orphan messages forever
                for key in [k for k in self._logs if k[0] == t]:
                    self._logs.pop(key, None)
                self._split_samples = {
                    k: v for k, v in self._split_samples.items()
                    if k[0] != t}
        finally:
            with self._lock:
                self._repartitioning.discard(t)
        return 200, {"deleted": str(t)}

    def _delete_group_offsets(self, req: Request):
        """Kafka DeleteGroups server side: drop EVERY committed
        offset of one consumer group (OFFSETS_DIR/<group>/)."""
        b = req.json()
        group = b.get("group", "")
        try:
            _check_name("group", group)
        except NameError_ as e:
            return 400, {"error": str(e)}
        path = f"{OFFSETS_DIR}/{group}"
        st, _, _ = http_bytes(
            "GET", f"{self.filer}/__meta__/lookup?path=" +
            urllib.parse.quote(path))
        existed = st == 200
        if existed:
            st_d, body_d, _ = http_bytes(
                "DELETE",
                f"{self.filer}{urllib.parse.quote(path)}"
                f"?recursive=true")
            if st_d not in (200, 204, 404):
                return 500, {"error": f"delete offsets: {st_d} "
                                      f"{body_d[:100]!r}"}
        return 200, {"existed": existed}

    def _delete_topic_offsets(self, t: Topic) -> None:
        """Best-effort removal of every group's committed offsets for
        the topic (OFFSETS_DIR/<group>/<ns>.<topic>/)."""
        try:
            st, body, _ = http_bytes(
                "GET", f"{self.filer}{OFFSETS_DIR}/?limit=10000")
        except OSError:
            return
        if st != 200:
            return
        for e in json.loads(body).get("entries", []):
            if not e.get("isDirectory"):
                continue
            group = e["fullPath"].rsplit("/", 1)[-1]
            try:
                http_bytes(
                    "DELETE",
                    f"{self.filer}{OFFSETS_DIR}/"
                    f"{urllib.parse.quote(group)}/"
                    f"{urllib.parse.quote(f'{t.namespace}.{t.name}')}"
                    f"?recursive=true")
            except OSError:
                pass

    # -- schema plane (weed/mq/schema; broker_grpc_pub.go gating) ------

    def _registry(self):
        from .schema import SchemaRegistry
        return SchemaRegistry(self.filer)

    def _schema_register(self, req: Request):
        from .schema import SchemaError
        b = req.json()
        try:
            t = self._topic_from(b["namespace"], b["topic"])
            rev = self._registry().register(t, b["recordType"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        except SchemaError as e:
            return 400, {"error": str(e)}
        except RuntimeError as e:
            return 503, {"error": str(e)}
        with self._lock:
            self._schema_cache.pop(t, None)
        return 200, {"revision": rev}

    def _schema_get(self, req: Request):
        try:
            t = self._topic_from(req.query["namespace"],
                                 req.query["topic"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        try:
            if "revision" in req.query:
                from .schema import SchemaError
                try:
                    rt = self._registry().get(
                        t, int(req.query["revision"]))
                except SchemaError as e:
                    return 404, {"error": str(e)}
                return 200, {"revision": int(req.query["revision"]),
                             "recordType": rt}
            latest = self._registry().latest(t)
        except RuntimeError as e:
            return 503, {"error": str(e)}
        if latest is None:
            return 404, {"error": f"topic {t} has no schema"}
        rev, rt = latest
        return 200, {"revision": rev, "recordType": rt}

    def _cached_schema(self, t: Topic) -> "dict | None":
        """Latest schema for publish validation, cached for CONF_TTL
        (same freshness rule as the layout cache)."""
        now = time.monotonic()
        with self._lock:
            if t in self._schema_cache and \
                    now - self._schema_cache_ts.get(t, 0) < self.CONF_TTL:
                return self._schema_cache[t]
        try:
            latest = self._registry().latest(t)
        except RuntimeError:
            return None  # filer blip: do not reject publishes
        rt = latest[1] if latest else None
        with self._lock:
            self._schema_cache[t] = rt
            self._schema_cache_ts[t] = now
        return rt

    def _validate_against_schema(self, t: Topic, value_b64: str
                                 ) -> "str | None":
        """Error string when the topic has a schema and the value does
        not conform (schema-gated publish); None = accept."""
        rt = self._cached_schema(t)
        if rt is None:
            return None
        raw = base64.b64decode(value_b64 or "")
        if not raw:
            # key-only tombstones/markers are always legal — every
            # schema field is optional (proto3 semantics)
            return None
        from .schema import SchemaError, validate_record
        try:
            record = json.loads(raw)
        except ValueError:
            return "schema-gated topic: value is not JSON"
        try:
            validate_record(rt, record)
        except SchemaError as e:
            return str(e)
        return None

    def _compact(self, req: Request):
        """log_to_parquet compaction of one topic (all partitions this
        broker owns, or every partition with force=true)."""
        from .parquet_store import compact_partition
        b = req.json()
        try:
            t = self._topic_from(b["namespace"], b["topic"])
            parts = self._load_layout(t)
        except NameError_ as e:
            return 400, {"error": str(e)}
        except RuntimeError as e:
            return 503, {"error": str(e)}
        if parts is None:
            return 404, {"error": f"topic {t} not configured"}
        rt = self._cached_schema(t)
        results = []
        for idx, p in enumerate(parts):
            if not b.get("force") and \
                    self._owner_gate(t, parts, idx) is not None:
                continue  # not ours; that broker compacts its own
            try:
                # flush the hot buffer so its rows are compactable
                self._log_for(t, p).flush()
                results.append(dict(
                    compact_partition(self.filer, t, p, rt,
                                      keep_recent_segments=int(
                                          b.get("keepRecent", 1)),
                                      min_segments=int(
                                          b.get("minSegments", 2))),
                    partition=p.to_json()))
            except (RuntimeError, OSError) as e:
                # one partition's failure must not block the others
                results.append({"partition": p.to_json(),
                                "error": str(e)})
        return 200, {"results": results}

    def _configure(self, req: Request):
        b = req.json()
        try:
            t = self._topic_from(b["namespace"], b["topic"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        n = int(b.get("partitionCount", 4))
        with self._topic_lock(t).write():
            with self._lock:
                # an explicit (re)configure supersedes any delete
                # fence here: the conf it persists is fresh truth
                self._deleting.pop(t, None)
            try:
                existing = self._load_layout(t)
            except RuntimeError as e:
                return 503, {"error": str(e)}
            if existing is not None:
                if len(existing) != n:
                    # repartitioning changes key->partition routing of
                    # already-stored messages; refuse (the reference
                    # reconciles via assignments — out of scope)
                    return 409, {"error":
                                 f"topic {t} already has "
                                 f"{len(existing)} partitions"}
                return 200, {"partitions":
                             [p.to_json() for p in existing]}
            parts = split_ring(n)
            # round-robin allocation across live brokers
            # (pub_balancer/allocate.go AllocateTopicPartitions)
            try:
                live = self._live_brokers()
            except RuntimeError:
                live = [self.url]   # solo fallback: configure works
            owners = [live[i % len(live)] for i in range(n)]
            err = self._persist_layout(t, parts, owners)
            if err:
                return 500, {"error": err}
        return 200, {"partitions": [p.to_json() for p in parts]}

    def _namespaces(self) -> "list[str]":
        """Topic namespaces in the filer tree, reserved dot-dirs
        (.brokers, .offsets) excluded.  Shared by mq.balance and the
        gRPC ListTopics so the filter cannot drift."""
        st, body, _ = http_bytes("GET",
                                 f"{self.filer}/topics/?limit=1000")
        if st == 404:
            return []
        if st != 200:
            raise RuntimeError(f"filer list: {st}")
        return sorted(
            e["fullPath"].rsplit("/", 1)[-1]
            for e in json.loads(body).get("entries", [])
            if e.get("isDirectory") and
            not e["fullPath"].rsplit("/", 1)[-1].startswith("."))

    def _list_topics(self, req: Request):
        """Configured topics of a namespace, from the filer tree
        (broker.proto ListTopics): each topic dir under
        /topics/<ns>/ holding a topic.conf."""
        ns = req.query.get("namespace", "")
        try:
            _check_name("namespace", ns)
        except NameError_ as e:
            return 400, {"error": str(e)}
        st, body, _ = http_bytes(
            "GET", f"{self.filer}/topics/{urllib.parse.quote(ns)}/"
                   f"?limit=10000")
        if st == 404:
            return 200, {"topics": []}
        if st != 200:
            return 503, {"error": f"filer list: {st}"}
        names = [e["fullPath"].rsplit("/", 1)[-1] for e in
                 json.loads(body).get("entries", [])
                 if e.get("isDirectory")]
        return 200, {"topics": sorted(names)}

    def _lookup(self, req: Request):
        try:
            t = self._topic_from(req.query["namespace"],
                                 req.query["topic"])
            parts = self._load_layout(t)
        except NameError_ as e:
            return 400, {"error": str(e)}
        except RuntimeError as e:
            return 503, {"error": str(e)}
        if parts is None:
            return 404, {"error": f"topic {t} not configured"}
        with self._lock:
            owners = self._owners.get(t) or [self.url] * len(parts)
        return 200, {"topic": str(t), "assignments": [
            {"partition": p.to_json(), "broker": o}
            for p, o in zip(parts, owners)]}

    def _log_for(self, t: Topic, p: Partition) -> PartitionLog:
        with self._lock:
            log = self._logs.get((t, p))
            if log is None:
                log = PartitionLog(self.filer, t, p)
                self._logs[(t, p)] = log
            return log

    # -- pub/sub ----------------------------------------------------------

    # sentinel: the append fence found the gate decision outdated —
    # the caller must reload and re-gate before appending
    _STALE = object()

    def _fenced_append(self, t: Topic, parts: "list[Partition]",
                       idx: int, fn):
        """Final pre-append fence (round-5 review): the append runs
        under the topic's conf lock so it serializes against a local
        repartition's drain and the repartition flush broadcast;
        answers 503-retry while this broker is repartitioning t; and
        returns _STALE unless the CURRENT cached conf is fresh, IS the
        layout the caller gated on (a gate decision from the
        pre-repartition layout must not append into an old-range dir
        the drain already deleted), and still names this broker owner
        of partition idx — checking layout+ownership (not just a
        timestamp another thread's reload may have reset) means a
        stale gate decision can never append to a drained log.
        Returns fn()'s result or a (status, body) error."""
        # fast-path 503 BEFORE the topic lock: during a local
        # repartition the lock is held for the whole drain, and
        # blocking every publisher on it would pin the HTTP worker
        # pool instead of failing fast for a client retry
        with self._lock:
            if t in self._repartitioning:
                return 503, {"error": "repartition in progress; retry"}
        with self._topic_lock(t).read():
            with self._lock:
                if t in self._repartitioning:
                    return 503, {"error":
                                 "repartition in progress; retry"}
                owners = self._owners.get(t)
                current = self._topics.get(t)
                fresh = time.monotonic() - \
                    self._conf_loaded.get(t, 0) < self.CONF_TTL
            if not fresh or current != parts or owners is None \
                    or idx >= len(owners) or owners[idx] != self.url:
                return BrokerServer._STALE
            return fn()

    def _publish_guarded(self, b: dict, pick_idx, make_append):
        """Shared publish scaffold (review r5: the fence protocol
        lives in ONE place).  Two passes of load → gate → validate →
        fenced append: when the fence reports the gate decision
        outdated (slow schema fetch, concurrent repartition), re-gate
        on a fresh conf instead of appending to a drained log.
        pick_idx(parts) returns a partition index or a (status, body)
        error; make_append(parts, idx) validates and returns a thunk
        or a (status, body) error."""
        try:
            t = self._topic_from(b["namespace"], b["topic"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        with self._lock:
            fence = self._deleting.get(t, 0)
            if fence and time.time() >= fence:
                del self._deleting[t]       # expired: normal path
                fence = 0
        if fence:
            # a peer is deleting this topic: refuse now (503-retry);
            # once the fence lapses a FRESH conf load sees the conf
            # gone and answers the honest 404 (or serves a re-created
            # topic from scratch)
            return 503, {"error": "topic deletion in progress; retry"}
        for _attempt in range(2):
            try:
                parts = self._load_layout(t)
            except RuntimeError as e:
                return 503, {"error": str(e)}
            if parts is None:
                return 404, {"error": f"topic {t} not configured"}
            idx = pick_idx(parts)
            if isinstance(idx, tuple):
                return idx
            redirect = self._owner_gate(t, parts, idx)
            if redirect is not None:
                return redirect
            thunk = make_append(t, parts, idx)
            if isinstance(thunk, tuple):
                return thunk
            res = self._fenced_append(t, parts, idx, thunk)
            if res is BrokerServer._STALE:
                continue
            if isinstance(res, tuple):
                return res
            return 200, {"partition": parts[idx].to_json(),
                         "tsNs": res}
        return 503, {"error": "topic layout changing; retry"}

    @staticmethod
    def _index_picker(b: dict):
        """Partition selection for a publish body: explicit index (the
        Kafka gateway's client already partitioned; re-hashing would
        misroute) or key hash."""
        def pick(parts):
            if "partition" in b and b["partition"] is not None:
                idx = int(b["partition"])
                if not 0 <= idx < len(parts):
                    return (400, {"error":
                                  f"partition index {idx} out of "
                                  f"range 0..{len(parts) - 1}"})
                return idx
            key = base64.b64decode(b.get("key", "")) \
                if b.get("key") else b""
            return parts.index(partition_for_key(key, parts))
        return pick

    def _publish(self, req: Request):
        b = req.json()

        def make_append(t, parts, idx):
            err = self._validate_against_schema(t, b.get("value", ""))
            if err:
                return 400, {"error": err}
            return lambda: self._log_for(t, parts[idx]).append(
                b.get("key", ""), b.get("value", ""),
                int(b.get("tsNs", 0)))

        return self._publish_guarded(b, self._index_picker(b),
                                     make_append)

    def _publish_batch(self, req: Request):
        """Atomic multi-message publish to one explicit partition —
        the per-partition batch semantics Kafka producers assume
        (broker.proto PublishMessage streams get this from the
        single-writer partition loop)."""
        b = req.json()

        def pick(parts):
            idx = int(b["partition"])
            if not 0 <= idx < len(parts):
                return (400, {"error": f"partition index {idx} out of "
                                       f"range 0..{len(parts) - 1}"})
            return idx

        def make_append(t, parts, idx):
            records = [(m.get("key", ""), m.get("value", ""),
                        int(m.get("tsNs", 0)))
                       for m in b.get("messages", [])]
            for _k, v, _ts in records:  # atomic: reject whole batch
                err = self._validate_against_schema(t, v)
                if err:
                    return 400, {"error": err}
            return lambda: self._log_for(
                t, parts[idx]).append_many(records)

        return self._publish_guarded(b, pick, make_append)

    def _subscribe(self, req: Request):
        try:
            t = self._topic_from(req.query["namespace"],
                                 req.query["topic"])
            parts = self._load_layout(t)
        except NameError_ as e:
            return 400, {"error": str(e)}
        except RuntimeError as e:
            return 503, {"error": str(e)}
        if parts is None:
            return 404, {"error": f"topic {t} not configured"}
        idx = int(req.query.get("partition", -1))
        since = int(req.query.get("sinceNs", 0))
        limit = int(req.query.get("limit", 1000))
        if not 0 <= idx < len(parts):
            return 400, {"error": f"partition index {idx} out of "
                                  f"range 0..{len(parts) - 1}"}
        redirect = self._owner_gate(t, parts, idx)
        if redirect is not None:
            return redirect
        log = self._log_for(t, parts[idx])
        msgs = log.read_since(since, limit)
        return 200, {"partition": parts[idx].to_json(),
                     "messages": msgs,
                     "highWaterMarkNs": log.high_water_mark()}

    def _flush(self, req: Request):
        b = req.json()
        t = Topic(b["namespace"], b["topic"])
        flushed = 0
        # under the topic's conf lock (review r5): a repartition
        # coordinator's flush broadcast must not return 200 while a
        # fenced append that already passed its gate is still landing
        # in the tail — serializing here guarantees any append the
        # fence admitted is in the buffer (and thus in this flush)
        # before we confirm.
        with self._topic_lock(t).write():
            with self._lock:
                items = [(p, log) for (lt, p), log
                         in self._logs.items() if lt == t]
            for _p, log in items:
                log.flush()
                flushed += 1
            # drop log objects for partitions this broker no longer
            # owns (fresh conf): a retained PartitionLog's memory
            # window (_ring_floor short-circuit) would hide messages
            # another owner persists while we are de-owned, if
            # ownership ever returns here
            try:
                parts = self._load_layout(t, fresh=True)
            except RuntimeError:
                parts = None
            if parts is not None:
                with self._lock:
                    owners = self._owners.get(t) or []
                    mine = {p for p, o in zip(parts, owners)
                            if o == self.url}
                    for p, _log in items:
                        if p not in mine:
                            self._logs.pop((t, p), None)
        return 200, {"flushed": flushed}

    # -- consumer-group offsets -------------------------------------------

    def _offset_path(self, group: str, t: Topic, idx: int) -> str:
        return f"{OFFSETS_DIR}/{group}/{t.namespace}.{t.name}/p{idx}"

    def _commit_offset(self, req: Request):
        b = req.json()
        try:
            t = self._topic_from(b["namespace"], b["topic"])
            _check_name("group", b["group"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        path = self._offset_path(b["group"], t, int(b["partition"]))
        st, resp, _ = http_bytes(
            "POST", self.filer + urllib.parse.quote(path),
            json.dumps({"tsNs": int(b["tsNs"])}).encode())
        if st >= 300:
            return 500, {"error": f"persist offset: {st}"}
        return 200, {}

    def _fetch_offset(self, req: Request):
        try:
            t = self._topic_from(req.query["namespace"],
                                 req.query["topic"])
            _check_name("group", req.query["group"])
        except NameError_ as e:
            return 400, {"error": str(e)}
        path = self._offset_path(req.query["group"], t,
                                 int(req.query["partition"]))
        st, body, _ = http_bytes(
            "GET", self.filer + urllib.parse.quote(path))
        if st == 404:
            # no commit yet — `committed` lets callers distinguish
            # this from a real commit at position 0/-1 (the Kafka
            # gateway must not misread those as "no offset")
            return 200, {"tsNs": 0, "committed": False}
        if st != 200:
            # a filer blip must NOT read as "no commit": the consumer
            # would restart from 0 and reprocess the whole partition
            return 503, {"error": f"offset store: {st}"}
        return 200, {"tsNs": int(json.loads(body)["tsNs"]),
                     "committed": True}
