"""Parquet logstore: compact JSON-line log segments into parquet
(weed/mq/logstore/log_to_parquet.go, read_parquet_to_log.go,
merged_read.go).

Compaction rewrites a partition's cold `.log` segments into one
columnar `.parquet` file named by its first message stamp (so parquet
and log segments sort chronologically in one sequence) and deletes the
compacted logs.  Every parquet file carries the raw message columns
(_key, _value binary, _ts_ns) so replay is byte-exact regardless of
schema; when the topic has a registered schema, the record's fields
are ALSO materialized as typed columns — those power the query
engine's row-group statistics pruning (query/engine.py parquet path,
the reference's aggregations.go:40 fast path).
"""

from __future__ import annotations

import base64
import io
import json
import urllib.parse

from ..server.httpd import http_bytes
from .topic import Partition, Topic


def _partition_dir(topic: Topic, partition: Partition) -> str:
    return f"{topic.dir}/{partition}"


def _list_files(filer: str, dir_path: str) -> "list[str]":
    st, body, _ = http_bytes(
        "GET", f"{filer}{urllib.parse.quote(dir_path)}/?limit=1000000")
    if st != 200:
        return []
    return sorted(
        e["fullPath"].rsplit("/", 1)[-1]
        for e in json.loads(body).get("entries", [])
        if not e.get("isDirectory"))


def _read_log_rows(filer: str, dir_path: str, name: str
                   ) -> "list[dict]":
    st, body, _ = http_bytes(
        "GET", f"{filer}{urllib.parse.quote(dir_path)}/{name}")
    if st != 200:
        return []
    rows = []
    for line in body.splitlines():
        try:
            rows.append(json.loads(line))
        except ValueError:
            continue
    return rows


def compact_partition(filer: str, topic: Topic, partition: Partition,
                      record_type: "dict | None" = None,
                      keep_recent_segments: int = 1,
                      min_segments: int = 2) -> dict:
    """log_to_parquet.go CompactTopicPartitions analog: all but the
    newest `keep_recent_segments` log segments become one parquet
    file.  Returns {"compacted": n_segments, "rows": n, "file": name}.
    The hot tail stays as logs — the buffer flush keeps appending
    there, and a tailing subscriber's short-circuit path is
    untouched."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    d = _partition_dir(topic, partition)
    logs = [n for n in _list_files(filer, d) if n.endswith(".log")]
    victims = logs[:-keep_recent_segments] if keep_recent_segments \
        else logs
    if len(victims) < min_segments:
        return {"compacted": 0, "rows": 0}
    rows: list[dict] = []
    for name in victims:
        rows.extend(_read_log_rows(filer, d, name))
    if not rows:
        return {"compacted": 0, "rows": 0}
    rows.sort(key=lambda r: r.get("tsNs", 0))

    keys = [base64.b64decode(r.get("key", "")) for r in rows]
    values = [base64.b64decode(r.get("value", "")) for r in rows]
    stamps = [int(r.get("tsNs", 0)) for r in rows]
    arrays = {
        "_key": pa.array(keys, pa.binary()),
        "_value": pa.array(values, pa.binary()),
        "_ts_ns": pa.array(stamps, pa.int64()),
    }
    names = ["_key", "_value", "_ts_ns"]
    if record_type is not None:
        from .schema import _arrow_type
        decoded = []
        for v in values:
            try:
                decoded.append(json.loads(v))
            except ValueError:
                decoded.append({})
        for f in record_type["fields"]:
            col = [d.get(f["name"]) if isinstance(d, dict) else None
                   for d in decoded]
            at = _arrow_type(f["type"])
            try:
                arr = pa.array(col, at)
            except (pa.ArrowInvalid, pa.ArrowTypeError, OverflowError):
                # pre-schema history / overflow rows: null the typed
                # cell (the raw _value column preserves the bytes) —
                # one bad row must not wedge compaction forever
                arr = pa.array([_fit_or_none(v, at) for v in col], at)
            arrays[f["name"]] = arr
            names.append(f["name"])
    table = pa.table({n: arrays[n] for n in names})
    buf = io.BytesIO()
    # small row groups so min/max statistics prune effectively
    pq.write_table(table, buf, row_group_size=4096)
    first_ts = stamps[0]
    pname = f"{first_ts:020d}.parquet"
    st, resp, _ = http_bytes(
        "POST", f"{filer}{urllib.parse.quote(d)}/{pname}",
        buf.getvalue())
    if st >= 300:
        raise RuntimeError(f"write parquet {d}/{pname}: {st} "
                           f"{resp[:200]!r}")
    leftovers = []
    for name in victims:
        st, _, _ = http_bytes(
            "DELETE", f"{filer}{urllib.parse.quote(d)}/{name}")
        if st >= 300 and st != 404:
            st2, _, _ = http_bytes(  # one retry
                "DELETE", f"{filer}{urllib.parse.quote(d)}/{name}")
            if st2 >= 300 and st2 != 404:
                leftovers.append(name)
    # A surviving victim log means its rows exist in BOTH the log and
    # the parquet; the merged read's strictly-increasing stamp guard
    # dedupes replay, but the operator must know (the next compaction
    # retries the delete since the segment is still listed).
    out = {"compacted": len(victims) - len(leftovers),
           "rows": len(rows), "file": pname}
    if leftovers:
        out["undeletedSegments"] = leftovers
    return out


def _fit_or_none(v, arrow_type):
    """Best-effort single-value coercion; None when the value cannot
    be represented in the column type."""
    import pyarrow as pa
    try:
        pa.array([v], arrow_type)
        return v
    except (pa.ArrowInvalid, pa.ArrowTypeError, OverflowError):
        return None


def parquet_max_ts(filer: str, dir_path: str, name: str) -> int:
    """Newest _ts_ns in a parquet segment, from the footer's row-group
    statistics alone — no row data is read."""
    import pyarrow.parquet as pq

    st, body, _ = http_bytes(
        "GET", f"{filer}{urllib.parse.quote(dir_path)}/{name}")
    if st != 200:
        return 0
    md = pq.ParquetFile(io.BytesIO(body)).metadata
    best = 0
    for rg in range(md.num_row_groups):
        g = md.row_group(rg)
        for i in range(g.num_columns):
            c = g.column(i)
            if c.path_in_schema == "_ts_ns" and \
                    c.statistics is not None and \
                    c.statistics.has_min_max:
                best = max(best, c.statistics.max)
    return best


def read_parquet_rows(filer: str, dir_path: str, name: str,
                      since_ns: int = 0) -> "list[dict]":
    """read_parquet_to_log.go analog: parquet rows back into the
    {tsNs, key, value} message shape, byte-exact via the raw
    columns."""
    import pyarrow.parquet as pq

    st, body, _ = http_bytes(
        "GET", f"{filer}{urllib.parse.quote(dir_path)}/{name}")
    if st != 200:
        return []
    pf = pq.ParquetFile(io.BytesIO(body))
    out = []
    for rg in range(pf.num_row_groups):
        md = pf.metadata.row_group(rg)
        col = {md.column(i).path_in_schema: md.column(i)
               for i in range(md.num_columns)}
        stats = col.get("_ts_ns").statistics if "_ts_ns" in col \
            else None
        if stats is not None and stats.has_min_max and \
                stats.max <= since_ns:
            continue  # whole row group is older than the resume point
        t = pf.read_row_group(rg, columns=["_key", "_value", "_ts_ns"])
        for key, value, ts in zip(t.column("_key").to_pylist(),
                                  t.column("_value").to_pylist(),
                                  t.column("_ts_ns").to_pylist()):
            if ts > since_ns:
                out.append({
                    "tsNs": ts,
                    "key": base64.b64encode(key or b"").decode(),
                    "value": base64.b64encode(value or b"").decode(),
                })
    return out
