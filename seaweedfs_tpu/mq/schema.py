"""MQ schema registry (weed/mq/schema/schema.go, schema_builder.go).

A topic may register a RecordType — named, typed fields (scalars,
lists, nested records) — with append-only revisions.  Registered
schemas gate publishes (a non-conforming record is rejected at the
broker, schema.go's role in broker_grpc_pub.go) and drive the parquet
logstore (to_parquet_schema.go analog via pyarrow in parquet_store).

The registry document lives in the filer beside the topic's
partitions:

    /topics/<ns>/<topic>/schema.json   {"revisions": [RecordType...]}

RecordType JSON shape (flat_schema_utils.go's wire form, pythonized):

    {"fields": [{"name": "user_id", "type": "int64"},
                {"name": "tags",    "type": {"list": "string"}},
                {"name": "address", "type": {"record": {"fields":
                    [{"name": "city", "type": "string"}]}}}]}

Scalar types: bool int32 int64 float double bytes string
(schema.go:36 TypeToString).
"""

from __future__ import annotations

import json
import urllib.parse

from ..server.httpd import http_bytes
from .topic import Topic

SCALARS = {"bool", "int32", "int64", "float", "double", "bytes",
           "string"}

_PY_OK = {
    "bool": (bool,),
    "int32": (int,),
    "int64": (int,),
    "float": (int, float),
    "double": (int, float),
    "string": (str,),
    "bytes": (str,),  # base64/utf8 text on the JSON wire
}


class SchemaError(ValueError):
    pass


def check_record_type(rt: dict) -> None:
    """Validate a RecordType document (schema_builder.go invariants:
    named fields, known types, no duplicate names)."""
    if not isinstance(rt, dict) or not isinstance(rt.get("fields"),
                                                  list):
        raise SchemaError("recordType must be {'fields': [...]}")
    seen = set()
    for f in rt["fields"]:
        name = f.get("name")
        if not name or not isinstance(name, str):
            raise SchemaError("every field needs a string name")
        if name in seen:
            raise SchemaError(f"duplicate field {name!r}")
        seen.add(name)
        _check_type(f.get("type"), name)


def _check_type(t, where: str) -> None:
    if isinstance(t, str):
        if t not in SCALARS:
            raise SchemaError(f"{where}: unknown scalar type {t!r}")
        return
    if isinstance(t, dict):
        if set(t) == {"list"}:
            _check_type(t["list"], f"{where}[]")
            return
        if set(t) == {"record"}:
            check_record_type(t["record"])
            return
    raise SchemaError(f"{where}: bad type {t!r}")


def validate_record(rt: dict, record: dict, where: str = "") -> None:
    """Reject a record that doesn't conform to the RecordType
    (to_schema_value.go's coercion, as validation).  Unknown keys are
    rejected — a typo'd producer field must not vanish silently."""
    if not isinstance(record, dict):
        raise SchemaError(f"{where or 'record'}: not an object")
    by_name = {f["name"]: f for f in rt["fields"]}
    for key in record:
        if key not in by_name:
            raise SchemaError(f"{where}{key}: not in schema")
    for f in rt["fields"]:
        name, t = f["name"], f["type"]
        if name not in record or record[name] is None:
            continue  # all fields optional (proto3 semantics)
        _validate_value(t, record[name], f"{where}{name}")


def _validate_value(t, v, where: str) -> None:
    if isinstance(t, str):
        ok = _PY_OK[t]
        if not isinstance(v, ok) or (t != "bool" and
                                     isinstance(v, bool)):
            raise SchemaError(
                f"{where}: expected {t}, got {type(v).__name__}")
        return
    if "list" in t:
        if not isinstance(v, list):
            raise SchemaError(f"{where}: expected list")
        for i, item in enumerate(v):
            _validate_value(t["list"], item, f"{where}[{i}]")
        return
    validate_record(t["record"], v, f"{where}.")


def to_arrow_schema(rt: dict):
    """RecordType -> pyarrow schema (to_parquet_schema.go), plus the
    system columns every row carries (_key, _ts_ns — the reference
    parquet files carry the same, log_to_parquet.go:48)."""
    import pyarrow as pa
    return pa.schema(
        [pa.field(f["name"], _arrow_type(f["type"]))
         for f in rt["fields"]] +
        [pa.field("_key", pa.binary()), pa.field("_ts_ns", pa.int64())])


def _arrow_type(t):
    import pyarrow as pa
    if isinstance(t, str):
        return {
            "bool": pa.bool_(), "int32": pa.int32(),
            "int64": pa.int64(), "float": pa.float32(),
            "double": pa.float64(), "bytes": pa.binary(),
            "string": pa.string(),
        }[t]
    if "list" in t:
        return pa.list_(_arrow_type(t["list"]))
    return pa.struct([pa.field(f["name"], _arrow_type(f["type"]))
                      for f in t["record"]["fields"]])


class SchemaRegistry:
    """Filer-persisted, append-only revisions per topic."""

    def __init__(self, filer: str):
        self.filer = filer

    def _path(self, t: Topic) -> str:
        return f"{t.dir}/schema.json"

    def _load(self, t: Topic) -> "list[dict]":
        st, body, _ = http_bytes(
            "GET", self.filer + urllib.parse.quote(self._path(t)))
        if st == 404:
            return []
        if st != 200:
            raise RuntimeError(f"schema registry read: {st}")
        return json.loads(body)["revisions"]

    def register(self, t: Topic, record_type: dict) -> int:
        """Append a new revision; returns its id (0-based).
        Re-registering the identical latest schema is a no-op returning
        the current revision (idempotent producers)."""
        check_record_type(record_type)
        revisions = self._load(t)
        if revisions and revisions[-1] == record_type:
            return len(revisions) - 1
        revisions.append(record_type)
        st, body, _ = http_bytes(
            "POST", self.filer + urllib.parse.quote(self._path(t)),
            json.dumps({"revisions": revisions}).encode())
        if st >= 300:
            raise RuntimeError(f"schema registry write: {st}")
        return len(revisions) - 1

    def latest(self, t: Topic) -> "tuple[int, dict] | None":
        revisions = self._load(t)
        if not revisions:
            return None
        return len(revisions) - 1, revisions[-1]

    def get(self, t: Topic, revision: int) -> dict:
        revisions = self._load(t)
        if not 0 <= revision < len(revisions):
            raise SchemaError(f"no revision {revision}")
        return revisions[revision]
