"""Metadata notification fan-out (weed/notification/configuration.go).

The reference publishes every filer metadata mutation to a configured
message bus (kafka / aws_sqs / google_pub_sub / gocdk) keyed by file
path; consumers build search indexes, replication queues, and audit
trails from it.  This package is that plane: a `Publisher` interface,
concrete webhook / MQ / log-file publishers selected by a spec string
(notification.toml analog), and a `NotificationTailer` that follows the
filer's persistent metadata log and fans every event out with at-least-
once delivery (checkpointed offset, per-event retries with backoff).

Spec strings:
    webhook:http://host:port/path     POST one JSON event per request
    mq:broker_addr/namespace/topic    publish to the built-in MQ broker
    kafka:host:port/topic             REAL Kafka wire protocol (any
                                      Kafka-compatible broker)
    sqs:<queue_url>                   AWS SQS SendMessage (SigV4; creds
                                      from the standard env vars)
    pubsub:<endpoint>/projects/<p>/topics/<t>
                                      Google Pub/Sub REST publish
    logfile:/path/to/file             append JSON lines (debug/audit)
"""

from __future__ import annotations

import json
import os
import threading
import time


class Publisher:
    def publish(self, event: dict) -> None:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        pass


def _event_key(event: dict) -> str:
    """One key rule for every sink: the entry path (per-path ordering
    in partitioned topics depends on all sinks agreeing)."""
    return (event.get("newEntry") or event.get("oldEntry") or
            {}).get("fullPath", "")


class WebhookPublisher(Publisher):
    """POST each event as JSON (the gocdk/webhook shape)."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url
        self.timeout = timeout

    def publish(self, event: dict) -> None:
        import urllib.request
        req = urllib.request.Request(
            self.url, data=json.dumps(event).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            if resp.status >= 300:
                raise OSError(f"webhook {self.url}: {resp.status}")


class MqPublisher(Publisher):
    """Publish into the built-in MQ broker (the kafka-notification
    analog: same fan-out role, our native bus)."""

    def __init__(self, broker: str, namespace: str, topic: str):
        from ..mq.client import MQClient
        self._client = MQClient(broker)
        self.namespace = namespace
        self.topic = topic
        self._configured = False

    def publish(self, event: dict) -> None:
        if not self._configured:
            try:
                self._client.configure_topic(self.namespace, self.topic)
                self._configured = True
            except RuntimeError:
                # distinguish "already configured by a peer" (lookup
                # succeeds -> proceed) from a transient broker/filer
                # failure (raise so the tailer retries configuration
                # next round instead of wedging forever)
                try:
                    self._client.lookup(self.namespace, self.topic)
                    self._configured = True
                except RuntimeError as e:
                    raise OSError(str(e)) from None
        key = _event_key(event)
        try:
            self._client.publish(self.namespace, self.topic,
                                 key.encode(),
                                 json.dumps(event).encode())
        except RuntimeError as e:  # broker-side error: retryable
            raise OSError(str(e)) from None


class KafkaPublisher(Publisher):
    """Publish metadata events over the REAL Kafka wire protocol
    (weed/notification/kafka/kafka_queue.go role): works against any
    Kafka-compatible broker — including our own gateway — via the
    binary-protocol client (mq/kafka_client.py; CRC32C v2 record
    batches, ApiVersions negotiation).  Events are keyed by entry
    path so per-path ordering survives partitioned topics."""

    def __init__(self, host: str, port: int, topic: str,
                 partitions: int = 4):
        from ..mq.kafka_client import KafkaClient
        self.host, self.port = host, port
        self.topic = topic
        self.partitions = partitions
        self._client: "KafkaClient | None" = None
        self._npart = 0

    def _ensure(self):
        from ..mq.kafka_client import KafkaClient
        if self._client is None:
            self._client = KafkaClient(self.host, self.port)
        if not self._npart:
            def live_parts():
                md = self._client.metadata([self.topic])
                info = md["topics"].get(self.topic)
                if info and not info["error"]:
                    return info["partitions"]
                return []
            parts = live_parts()
            if not parts:
                self._client.create_topic(self.topic,
                                          self.partitions)
                parts = live_parts()
            if not parts:
                raise OSError(f"kafka topic {self.topic} not "
                              f"creatable")
            self._npart = len(parts)
        return self._client

    def publish(self, event: dict) -> None:
        import zlib

        from ..mq.kafka_client import KafkaError
        key = _event_key(event).encode()
        try:
            c = self._ensure()
            # DETERMINISTIC key hash: Python's hash() is salted per
            # process, which would re-shuffle the key->partition map
            # on every restart and break per-path ordering
            part = zlib.crc32(key) % self._npart
            c.produce(self.topic, part,
                      [(key, json.dumps(event).encode())])
        except (KafkaError, OSError, RuntimeError) as e:
            # drop the connection so the retry re-dials + renegotiates
            if self._client is not None:
                try:
                    self._client.close()
                except OSError:
                    pass
            self._client = None
            self._npart = 0
            raise OSError(str(e)) from None

    def close(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass
            self._client = None


class SqsPublisher(Publisher):
    """AWS SQS SendMessage over the Query API with SigV4
    (weed/notification/aws_sqs/aws_sqs_pub.go role).  Credentials come
    from the standard env vars (AWS_ACCESS_KEY_ID /
    AWS_SECRET_ACCESS_KEY); the queue URL carries the endpoint, so a
    local SQS-compatible server works for tests and the real service
    when egress exists."""

    def __init__(self, queue_url: str, region: str = ""):
        import urllib.parse as up
        self.queue_url = queue_url
        u = up.urlsplit(queue_url)
        self.origin = f"{u.scheme}://{u.netloc}"
        self.path = u.path or "/"
        # region from the standard host shape sqs.<region>.amazonaws.com
        host_parts = u.netloc.split(".")
        self.region = region or os.environ.get("AWS_REGION") or (
            host_parts[1] if len(host_parts) > 2 and
            host_parts[0].startswith("sqs") else "us-east-1")

    def publish(self, event: dict) -> None:
        import urllib.parse as up

        from ..s3.auth import sign_request
        from ..server.httpd import http_bytes
        body = up.urlencode({
            "Action": "SendMessage",
            "Version": "2012-11-05",
            "MessageBody": json.dumps(event),
            "MessageAttribute.1.Name": "key",
            "MessageAttribute.1.Value.DataType": "String",
            "MessageAttribute.1.Value.StringValue": _event_key(event),
        }).encode()
        ak = os.environ.get("AWS_ACCESS_KEY_ID", "")
        sk = os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        headers = {"Content-Type":
                   "application/x-www-form-urlencoded"}
        if ak:
            # sign_request takes the scheme-less authority (it becomes
            # the signed host header verbatim)
            import urllib.parse as up
            headers = sign_request(
                "POST", up.urlsplit(self.origin).netloc, self.path,
                {}, headers, body, ak, sk, region=self.region,
                service="sqs")
        st, resp, _ = http_bytes("POST", self.origin + self.path,
                                 body, headers)
        if st >= 300:
            raise OSError(f"sqs {self.queue_url}: {st} {resp[:200]}")


class PubSubPublisher(Publisher):
    """Google Pub/Sub REST publish
    (weed/notification/google_pub_sub/google_pub_sub.go role):
    POST {endpoint}/v1/projects/<p>/topics/<t>:publish with base64
    message data + the entry path as an attribute.  Bearer token from
    `token` or env GOOGLE_BEARER_TOKEN; the official emulator needs
    none."""

    def __init__(self, endpoint: str, project: str, topic: str,
                 token: str = ""):
        self.endpoint = endpoint.rstrip("/")
        self.project = project
        self.topic = topic
        self.token = token or os.environ.get("GOOGLE_BEARER_TOKEN", "")

    def publish(self, event: dict) -> None:
        import base64

        from ..server.httpd import http_bytes
        payload = json.dumps({"messages": [{
            "data": base64.b64encode(
                json.dumps(event).encode()).decode(),
            "attributes": {"key": _event_key(event)},
        }]}).encode()
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        st, resp, _ = http_bytes(
            "POST", f"{self.endpoint}/v1/projects/{self.project}"
                    f"/topics/{self.topic}:publish", payload, headers)
        if st >= 300:
            raise OSError(f"pubsub {self.project}/{self.topic}: "
                          f"{st} {resp[:200]}")


class LogFilePublisher(Publisher):
    """Append JSON lines — the audit/debug sink."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def publish(self, event: dict) -> None:
        with self._lock:
            self._f.write(json.dumps(event) + "\n")
            self._f.flush()  # noqa: SWFS012 — audit/debug sink at human-scale event rates

    def close(self) -> None:
        with self._lock:
            self._f.close()


def from_spec(spec: str) -> Publisher:
    """notification.toml analog: one enabled sink chosen by spec."""
    kind, _, rest = spec.partition(":")
    if kind == "webhook":
        return WebhookPublisher(rest)
    if kind == "logfile":
        return LogFilePublisher(rest)
    if kind == "mq":
        broker, _, topic_path = rest.partition("/")
        ns, _, topic = topic_path.partition("/")
        if not (broker and ns and topic):
            raise ValueError(
                f"mq spec must be mq:broker/namespace/topic: {spec!r}")
        return MqPublisher(broker, ns, topic)
    if kind == "kafka":
        addr, _, topic = rest.partition("/")
        host, _, port = addr.rpartition(":")
        if not (host and port.isdigit() and topic):
            raise ValueError(
                f"kafka spec must be kafka:host:port/topic: {spec!r}")
        return KafkaPublisher(host, int(port), topic)
    if kind == "sqs":
        # sqs:https://sqs.us-east-1.amazonaws.com/123456/my-queue
        if "://" not in rest:
            raise ValueError(
                f"sqs spec must be sqs:<queue_url>: {spec!r}")
        return SqsPublisher(rest)
    if kind == "pubsub":
        # pubsub:https://pubsub.googleapis.com/projects/<p>/topics/<t>
        endpoint, sep, tail = rest.partition("/projects/")
        project, _, topic = tail.partition("/topics/")
        if not (sep and project and topic):
            raise ValueError(
                "pubsub spec must be "
                f"pubsub:<endpoint>/projects/<p>/topics/<t>: {spec!r}")
        return PubSubPublisher(endpoint, project, topic)
    raise ValueError(f"unknown notification spec {spec!r} "
                     "(webhook:|mq:|kafka:|sqs:|pubsub:|logfile:)")


class NotificationTailer:
    """Follows a filer's MetaLog and fans events out with at-least-once
    delivery: the offset checkpoint advances only after a successful
    publish, and failures retry with capped backoff (the reference's
    notification queue blocks the same way rather than dropping)."""

    def __init__(self, meta_log, publisher: Publisher,
                 state_path: str | None = None,
                 poll_interval: float = 0.2):
        self.meta_log = meta_log
        self.publisher = publisher
        self.state_path = state_path
        self.poll_interval = poll_interval
        self._since = self._load_offset()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _load_offset(self) -> int:
        if not self.state_path:
            return 0
        try:
            with open(self.state_path, encoding="utf-8") as f:
                return int(json.load(f).get("sinceNs", 0))
        except (OSError, ValueError):
            return 0

    def _save_offset(self) -> None:
        if not self.state_path:
            return
        tmp = f"{self.state_path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"sinceNs": self._since}, f)
        os.replace(tmp, self.state_path)

    def start(self) -> "NotificationTailer":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self.publisher.close()

    def _run(self) -> None:
        backoff = self.poll_interval
        while not self._stop.is_set():
            events = self.meta_log.events_since(self._since, limit=256)
            if not events:
                self._stop.wait(self.poll_interval)
                continue
            for ev in events:
                while not self._stop.is_set():
                    try:
                        self.publisher.publish(ev)
                        backoff = self.poll_interval
                        break
                    except OSError:
                        # at-least-once: never advance past an
                        # undelivered event; capped exponential backoff
                        self._stop.wait(backoff)
                        backoff = min(backoff * 2, 10.0)
                if self._stop.is_set():
                    return
                self._since = ev["tsNs"]
                self._save_offset()
