"""`benchmark` subcommand (weed/command/benchmark.go:28-116): concurrent
small-file write+read load against a cluster, with latency percentiles —
the harness behind the reference's published 15.7k writes/s / 47k
reads/s numbers (README.md:555-605)."""

from __future__ import annotations

import random
import time
from concurrent.futures import ThreadPoolExecutor

from . import operation


def _percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(p / 100 * len(sorted_vals)))
    return sorted_vals[idx]


def _stats(name: str, latencies: list[float], total_bytes: int,
           wall: float) -> dict:
    lat = sorted(latencies)
    n = len(lat)
    return {
        "op": name,
        "requests": n,
        "seconds": round(wall, 2),
        "req_per_sec": round(n / wall, 1) if wall else 0,
        "kb_per_sec": round(total_bytes / wall / 1024, 1) if wall else 0,
        "avg_ms": round(sum(lat) / n * 1000, 2) if n else 0,
        "p50_ms": round(_percentile(lat, 50) * 1000, 2),
        "p95_ms": round(_percentile(lat, 95) * 1000, 2),
        "p99_ms": round(_percentile(lat, 99) * 1000, 2),
        "max_ms": round(lat[-1] * 1000, 2) if lat else 0,
    }


def run_benchmark(master: str, n_files: int = 1000,
                  file_size: int = 1024, concurrency: int = 16,
                  read_ratio_check: bool = True) -> list[dict]:
    rng = random.Random(0)
    payload = bytes(rng.getrandbits(8) for _ in range(file_size))
    fids: list[str] = []
    write_lat: list[float] = []

    def write_one(i: int) -> tuple[str, float]:
        t0 = time.perf_counter()
        a = operation.assign(master)
        operation.upload(a.url, a.fid, payload)
        return a.fid, time.perf_counter() - t0

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        for fid, dt in pool.map(write_one, range(n_files)):
            fids.append(fid)
            write_lat.append(dt)
    write_wall = time.perf_counter() - t0
    results = [_stats("write", write_lat, n_files * file_size,
                      write_wall)]

    read_lat: list[float] = []

    def read_one(fid: str) -> float:
        t0 = time.perf_counter()
        data = operation.read(master, fid)
        if read_ratio_check and len(data) != file_size:
            raise RuntimeError(f"short read on {fid}")
        return time.perf_counter() - t0

    order = fids[:]
    rng.shuffle(order)
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        read_lat = list(pool.map(read_one, order))
    read_wall = time.perf_counter() - t0
    results.append(_stats("read", read_lat, n_files * file_size,
                          read_wall))
    return results
