"""Device-mesh construction helpers.

Axes:
  * "stripe" — data-parallel axis over stripe rows / byte columns of the
    volume stream (the reference's analog: independent 1GB/1MB stripe rows,
    weed/storage/erasure_coding/ec_encoder.go:280-319).
  * "shard"  — model/tensor-parallel axis over shard rows (the reference's
    analog: the 14 shard files spread across servers,
    weed/storage/erasure_coding/shard_distribution.go:101).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

STRIPE_AXIS = "stripe"
SHARD_AXIS = "shard"


def make_mesh(devices=None, shard_axis_size: int | None = None) -> Mesh:
    """Build a 2D ("stripe", "shard") mesh over `devices`.

    shard_axis_size defaults to the largest divisor of len(devices) that
    is <= 4 (RS(10,4) has 4 parity rows to split tensor-parallel); the
    remaining factor becomes the stripe (data-parallel) axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shard_axis_size is None:
        shard_axis_size = 1
        for cand in (4, 3, 2):
            if n % cand == 0:
                shard_axis_size = cand
                break
    if n % shard_axis_size:
        raise ValueError(f"{n} devices not divisible by shard axis "
                         f"{shard_axis_size}")
    arr = np.asarray(devices).reshape(n // shard_axis_size, shard_axis_size)
    return Mesh(arr, (STRIPE_AXIS, SHARD_AXIS))
