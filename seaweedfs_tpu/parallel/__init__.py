"""Distributed execution: device meshes and sharded erasure-coding.

The reference scales by *processes* (volume servers spread shards across
machines, gRPC fan-out for replication/rebuild — weed/topology/
store_replicate.go:27, weed/storage/store_ec.go:366).  The TPU-native
equivalent inside one pod-slice is a `jax.sharding.Mesh` with XLA
collectives over ICI: stripes are the batch ("data-parallel") axis and
shard rows are the "tensor-parallel" axis; cross-shard reconstruction is
a ring XOR-reduce (`ppermute`) — the storage analog of ring attention.
"""

from .mesh import make_mesh  # noqa: F401
from . import ec_sharded  # noqa: F401
