"""Sharded erasure coding over a device mesh (shard_map + ICI collectives).

The reference distributes EC work across *machines*: shards live on
different volume servers (weed/storage/erasure_coding/
shard_distribution.go:101) and degraded reads fan out parallel reads of
surviving shards, XOR-combining reconstructed data on the caller
(weed/storage/store_ec.go:366-443).  On a TPU slice those fan-outs become
XLA collectives over ICI:

  * encode  — stripe columns are data-parallel ("stripe" axis), parity
    rows are tensor-parallel ("shard" axis).  No collective needed: GF
    parity is columnwise-independent, so each device writes its slice of
    its parity rows.
  * reconstruct — survivor shard rows live distributed over the "shard"
    axis (the natural storage layout: one shard per device/server).  Each
    device computes its partial XOR-sum of coefficient×shard terms and a
    ring XOR-reduce (`ppermute`, the storage analog of ring attention)
    combines them — bit-exact, since XOR is associative/commutative.

All bulk data rides as packed uint32 words ([K, W] — 4 GF bytes per word,
see ops.rs_jax) so no uint8 relayout happens on device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def _shard_map(f, mesh, in_specs, out_specs,
               check_replication: "bool | None" = None):
    """Version shim over the jax shard_map API skew: the entry point
    moved (jax.experimental.shard_map -> jax.shard_map, handled by
    the import above) and the replication-check kwarg was renamed
    (check_rep in jax <= 0.4.x -> check_vma).  Callers say what they
    mean once; the shim speaks whichever dialect this jax does."""
    kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if check_replication is None:
        return shard_map(f, **kw)
    try:
        return shard_map(f, check_vma=check_replication, **kw)
    except TypeError:  # older jax: the kwarg is check_rep
        return shard_map(f, check_rep=check_replication, **kw)


def _axis_size(axis_name: str) -> int:
    """jax.lax.axis_size only exists in newer jax; psum over the
    Python constant 1 constant-folds to a static int on every version
    this shim spans."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)

from ..ops import rs_matrix
from ..ops.rs_jax import _packed_xor_network, expand_tables_u32
from .mesh import SHARD_AXIS, STRIPE_AXIS


def _ring_xor(x: jax.Array, axis_name: str) -> jax.Array:
    """XOR all-reduce over `axis_name` via a ring of ppermutes.

    s-1 hops, each overlapping neighbor transfers on ICI; bit-exact in any
    order because XOR is associative and commutative.
    """
    s = _axis_size(axis_name)
    if s == 1:
        return x
    perm = [(j, (j + 1) % s) for j in range(s)]
    acc = x
    t = x
    for _ in range(s - 1):
        t = jax.lax.ppermute(t, axis_name, perm)
        acc = acc ^ t
    return acc


def _apply_tables_local(mat_local: jax.Array, data32: jax.Array) -> jax.Array:
    """[r_local, K] uint8 × [K, W_local] uint32 -> [r_local, W_local]."""
    return _packed_xor_network(expand_tables_u32(mat_local), data32)


@functools.lru_cache(maxsize=32)
def _encode_shard_map(mesh):
    """Per-mesh encode shard_map (traceable, un-jitted): parity rows
    tensor-parallel over "shard", columns data-parallel over "stripe"."""
    return _shard_map(
        _apply_tables_local, mesh,
        in_specs=(P(SHARD_AXIS, None), P(None, STRIPE_AXIS)),
        out_specs=P(SHARD_AXIS, STRIPE_AXIS))


@functools.lru_cache(maxsize=32)
def _reconstruct_shard_map(mesh):
    """Per-mesh distributed-reconstruction shard_map (ring XOR-reduce)."""
    return _shard_map(
        _reconstruct_local, mesh,
        in_specs=(P(None, SHARD_AXIS), P(SHARD_AXIS, STRIPE_AXIS)),
        # the ring XOR leaves every shard-axis device with the full sum;
        # replication can't be statically inferred through ppermute
        out_specs=P(None, STRIPE_AXIS), check_replication=False)


@functools.lru_cache(maxsize=32)
def _encode_fn(mesh):
    """Jitted per-mesh encode; cached so repeated calls don't retrace."""
    return jax.jit(_encode_shard_map(mesh))


@functools.lru_cache(maxsize=32)
def _reconstruct_fn(mesh):
    """Jitted per-mesh reconstruction; cached to avoid retraces."""
    return jax.jit(_reconstruct_shard_map(mesh))


def encode_sharded(mesh, mat, data32):
    """Distributed parity computation.

    mat: [R, K] uint8 parity rows (R divisible by the "shard" axis size).
    data32: [K, W] uint32 packed data shards (W divisible by the "stripe"
    axis size × 1 word).  Returns [R, W] uint32 parity, sharded
    P("shard", "stripe").
    """
    return _encode_fn(mesh)(mat, data32)


def _reconstruct_local(coeffs_local: jax.Array, survivors_local: jax.Array
                       ) -> jax.Array:
    """coeffs_local [T, k_local] uint8, survivors_local [k_local, W_local]
    uint32 -> full [T, W_local] after ring XOR-reduce over the shard axis."""
    partial = _apply_tables_local(coeffs_local, survivors_local)
    return _ring_xor(partial, SHARD_AXIS)


def reconstruct_sharded(mesh, coeffs, survivors32):
    """Distributed reconstruction: survivors live sharded over the "shard"
    axis (one group of shard rows per device — the storage layout), output
    target rows are produced on every shard-axis device via ring XOR.

    coeffs: [T, K] uint8 reconstruction matrix (targets × survivors);
    K must be divisible by the shard axis size (pad with zero-coefficient
    columns + zero rows if not — XOR identity makes padding free).
    survivors32: [K, W] uint32.  Returns [T, W] uint32.
    """
    return _reconstruct_fn(mesh)(coeffs, survivors32)


def _apply_tables_batch_local(mat_local: jax.Array, batch32: jax.Array
                              ) -> jax.Array:
    """[r_local, K] × [V_local, K, W] -> [V_local, r_local, W]."""
    return jax.vmap(lambda d: _apply_tables_local(mat_local, d))(batch32)


@functools.lru_cache(maxsize=32)
def _encode_batch_fn(mesh):
    return jax.jit(_shard_map(
        _apply_tables_batch_local, mesh,
        in_specs=(P(SHARD_AXIS, None), P(STRIPE_AXIS, None, None)),
        out_specs=P(STRIPE_AXIS, SHARD_AXIS, None)))


def encode_volume_batch(mesh, mat, batch32):
    """Batch-of-volumes encode (BASELINE.json config 3: 64 volumes
    across the slice): volumes ride the data-parallel "stripe" axis,
    parity rows the tensor-parallel "shard" axis.

    mat: [R, K] uint8; batch32: [V, K, W] uint32 with V divisible by
    the stripe axis.  Returns [V, R, W] uint32.
    """
    return _encode_batch_fn(mesh)(mat, batch32)


def pad_survivors(coeffs: np.ndarray, survivors32: np.ndarray, multiple: int):
    """Pad the survivor dimension up to `multiple` with zero rows/columns
    (zero GF coefficients contribute nothing to the XOR sum)."""
    t, k = coeffs.shape
    pad = (-k) % multiple
    if pad == 0:
        return coeffs, survivors32
    coeffs = np.pad(coeffs, ((0, 0), (0, pad)))
    survivors32 = np.pad(survivors32, ((0, pad), (0, 0)))
    return coeffs, survivors32


@functools.partial(jax.jit, static_argnames=("mesh", "survivor_rows",
                                             "pad_rows"))
def _ec_step(mesh, data32, parity_mat, recon_coeffs,
             survivor_rows: tuple, pad_rows: int):
    """One full distributed EC pipeline step (see distributed_ec_step)."""
    par = _encode_shard_map(mesh)(parity_mat, data32)
    all_shards = jnp.concatenate([data32, par], axis=0)
    survivors = all_shards[jnp.asarray(survivor_rows)]
    if pad_rows:
        survivors = jnp.concatenate(
            [survivors,
             jnp.zeros((pad_rows, survivors.shape[1]), survivors.dtype)],
            axis=0)
    rec = _reconstruct_shard_map(mesh)(recon_coeffs, survivors)
    return par, rec


def distributed_ec_step(mesh, data32: np.ndarray, data_shards: int = 10,
                        parity_shards: int = 4, lost=(0, 11)):
    """The framework's "training step": encode a striped volume batch over
    the mesh, lose shards, reconstruct them distributed, and return
    (parity, reconstructed, max_abs_error).

    Exercises the real production shardings end-to-end: data-parallel
    stripes, tensor-parallel shard rows, and the ring-XOR collective.
    """
    total = data_shards + parity_shards
    shard_ax = mesh.shape[SHARD_AXIS]
    k, w = data32.shape
    assert k == data_shards
    parity_mat = rs_matrix.parity_matrix(data_shards, parity_shards)
    present = [i not in lost for i in range(total)]
    coeffs, rows = rs_matrix.reconstruction_matrix(
        data_shards, parity_shards, present, list(lost))
    pad = (-len(rows)) % shard_ax
    coeffs, _ = pad_survivors(
        coeffs, np.zeros((len(rows), 0), np.uint32), shard_ax)
    par, rec = _ec_step(
        mesh, jnp.asarray(data32), jnp.asarray(parity_mat),
        jnp.asarray(coeffs), survivor_rows=tuple(rows), pad_rows=pad)
    # check reconstruction against ground truth
    full = np.concatenate([np.asarray(data32), np.asarray(par)], axis=0)
    err = int(np.max(np.abs(
        full[list(lost)].astype(np.int64) -
        np.asarray(rec).astype(np.int64))))
    return np.asarray(par), np.asarray(rec), err
