"""Multi-volume batched EC file encode over the device mesh.

BASELINE.json config 3 is a 64-volume `ec.encode` batch across the
slice.  The reference encodes volumes one at a time on whatever worker
picks them up (worker/tasks/erasure_coding/ec_task.go:426); on TPU the
economics invert — one launch carrying many volumes' stripe rows keeps
the chip fed, so the batch axis is VOLUMES, data-parallel over the
mesh's "stripe" axis, while parity rows stay tensor-parallel over
"shard" (parallel/ec_sharded.encode_volume_batch).

Output is byte-identical to running `write_ec_files` per volume: every
volume keeps the reference's small-row geometry
(ec_encoder.go:304-319), rows are stacked per step exactly like the
single-volume aggregated path, and only each volume's real bytes are
written.  Volumes large enough to contain 1GB large-block rows
(>= 10GB) fall back to the per-volume path — those are beyond the
batch-job shape this targets (volume size limit is ~1GB).
"""

from __future__ import annotations

import os

import numpy as np

from ..ops import rs_matrix
from ..storage.erasure_coding.ec_context import (ECContext,
                                                 LARGE_BLOCK_SIZE,
                                                 SMALL_BLOCK_SIZE,
                                                 TPU_BATCH_SIZE)


def encode_volume_files_batch(bases: "list[str]", ctx: ECContext,
                              mesh=None) -> None:
    """Encode every `<base>.dat` into `<base>.ec00..ecNN`, batching all
    volumes into one device launch per step.

    Device bytes per launch stay ~TPU_BATCH_SIZE * data_shards by
    shrinking the per-volume row group as the batch widens
    (rows_per_step = TPU_BATCH / (block * n_volumes)).

    The mesh path is taken when `mesh` is given explicitly or the ctx
    backend is the jax one; other backends (cpu/native — no mesh to
    ride) and volumes large enough for 1GB large-block rows fall back
    to the per-volume pipeline, which honors ctx.backend and stays
    byte-identical.

    File handles are opened per step, not held for the whole batch —
    (total+1) x 64 volumes of persistent fds would brush the default
    1024 ulimit."""
    d = ctx.data_shards
    block = SMALL_BLOCK_SIZE
    large_row = LARGE_BLOCK_SIZE * d
    small_row = block * d
    sizes = [os.path.getsize(b + ".dat") for b in bases]
    if (mesh is None and ctx.backend != "jax") or \
            any(s >= large_row for s in sizes):
        from ..storage.erasure_coding import ec_encoder
        for b in bases:
            ec_encoder.write_ec_files(b, ctx)
        return

    import jax.numpy as jnp

    from .ec_sharded import encode_volume_batch
    from .mesh import STRIPE_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh()
    stripe = mesh.shape[STRIPE_AXIS]
    v = len(bases)
    v_pad = -(-v // stripe) * stripe  # zero-volumes pad the mesh axis
    rows_per_step = max(1, TPU_BATCH_SIZE // (block * v_pad))
    step_bytes = rows_per_step * block
    n_rows = [-(-s // small_row) for s in sizes]
    n_steps = max((-(-r // rows_per_step) for r in n_rows), default=0)

    mat = jnp.asarray(rs_matrix.parity_matrix(d, ctx.parity_shards))
    for b in bases:  # truncate any stale outputs once
        for i in range(ctx.total):
            open(b + ctx.to_ext(i), "wb").close()
    for s in range(n_steps):
        batch = np.zeros((v_pad, d, step_bytes), dtype=np.uint8)
        reals = []
        for vi in range(v):
            rows_left = n_rows[vi] - s * rows_per_step
            real_rows = max(0, min(rows_per_step, rows_left))
            reals.append(real_rows * block)
            if real_rows == 0:
                continue
            with open(bases[vi] + ".dat", "rb") as dat:
                dat.seek(s * rows_per_step * small_row)
                for r in range(real_rows):
                    base_off = r * block
                    for i in range(d):
                        chunk = dat.read(block)
                        if chunk:
                            batch[vi, i,
                                  base_off:base_off + len(chunk)] = \
                                np.frombuffer(chunk, dtype=np.uint8)
        batch32 = batch.reshape(v_pad, d, -1).view(np.uint32)
        par = np.asarray(encode_volume_batch(
            mesh, mat, jnp.asarray(batch32)))
        par8 = par.view(np.uint8).reshape(
            v_pad, ctx.parity_shards, step_bytes)
        for vi in range(v):
            real = reals[vi]
            if real == 0:
                continue
            for i in range(d):
                with open(bases[vi] + ctx.to_ext(i), "ab") as f:
                    f.write(batch[vi, i, :real].data)
            for j in range(ctx.parity_shards):
                with open(bases[vi] + ctx.to_ext(d + j), "ab") as f:
                    f.write(par8[vi, j, :real].data)
