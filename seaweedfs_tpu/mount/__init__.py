"""FUSE mount (weed/mount): filer-backed op table + ctypes libfuse
bridge.  See DESIGN.md for the architecture and scope."""

from .weedfs import FuseError, WeedFS  # noqa: F401
