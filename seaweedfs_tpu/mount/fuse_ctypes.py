"""ctypes bridge to libfuse.so.2 (the kernel-facing half of the mount;
see DESIGN.md).

The environment ships libfuse 2.9 and /dev/fuse but neither headers
nor pybind11, so the `fuse_operations` table (FUSE_USE_VERSION 26) and
the x86-64 glibc `struct stat` are declared by hand — their layouts
are fixed ABI.  Runs `fuse_main_real` foreground + single-threaded;
every callback trampoline is pinned on the instance so the C side can
never call into a collected object.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno

from .weedfs import FuseError, WeedFS

c_off_t = ctypes.c_longlong
c_mode_t = ctypes.c_uint
c_dev_t = ctypes.c_ulonglong


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


class Stat(ctypes.Structure):
    """x86-64 glibc struct stat."""
    _fields_ = [
        ("st_dev", c_dev_t),
        ("st_ino", ctypes.c_ulong),
        ("st_nlink", ctypes.c_ulong),
        ("st_mode", c_mode_t),
        ("st_uid", ctypes.c_uint),
        ("st_gid", ctypes.c_uint),
        ("__pad0", ctypes.c_int),
        ("st_rdev", c_dev_t),
        ("st_size", c_off_t),
        ("st_blksize", ctypes.c_long),
        ("st_blocks", ctypes.c_long),
        ("st_atim", Timespec),
        ("st_mtim", Timespec),
        ("st_ctim", Timespec),
        ("__unused", ctypes.c_long * 3),
    ]


class Statvfs(ctypes.Structure):
    _fields_ = [
        ("f_bsize", ctypes.c_ulong),
        ("f_frsize", ctypes.c_ulong),
        ("f_blocks", ctypes.c_ulong),
        ("f_bfree", ctypes.c_ulong),
        ("f_bavail", ctypes.c_ulong),
        ("f_files", ctypes.c_ulong),
        ("f_ffree", ctypes.c_ulong),
        ("f_favail", ctypes.c_ulong),
        ("f_fsid", ctypes.c_ulong),
        ("f_flag", ctypes.c_ulong),
        ("f_namemax", ctypes.c_ulong),
        ("__spare", ctypes.c_int * 6),
    ]


_getattr_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(Stat))
_readlink_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t)
_open_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p)
_read_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t, c_off_t, ctypes.c_void_p)
_statfs_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(Statvfs))
_mkdir_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, c_mode_t)
_path_t = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p)
_rename_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p)
_chmod_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, c_mode_t)
_chown_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_uint, ctypes.c_uint)
_truncate_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, c_off_t)
_write_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t, c_off_t, ctypes.c_void_p)
_fi_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p)
_create_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, c_mode_t, ctypes.c_void_p)
_ftruncate_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, c_off_t, ctypes.c_void_p)
_utimens_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(Timespec))
# int (*filler)(void *buf, const char *name, const struct stat *, off_t)
_fill_dir_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
    ctypes.POINTER(Stat), c_off_t)
_readdir_t = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p, _fill_dir_t,
    c_off_t, ctypes.c_void_p)
_voidp_t = ctypes.c_void_p


class FuseOperations(ctypes.Structure):
    """fuse.h FUSE_USE_VERSION 26 operation table (libfuse 2.9)."""
    _fields_ = [
        ("getattr", _getattr_t),
        ("readlink", _readlink_t),
        ("getdir", _voidp_t),
        ("mknod", _voidp_t),
        ("mkdir", _mkdir_t),
        ("unlink", _path_t),
        ("rmdir", _path_t),
        ("symlink", _voidp_t),
        ("rename", _rename_t),
        ("link", _voidp_t),
        ("chmod", _chmod_t),
        ("chown", _chown_t),
        ("truncate", _truncate_t),
        ("utime", _voidp_t),
        ("open", _open_t),
        ("read", _read_t),
        ("write", _write_t),
        ("statfs", _statfs_t),
        ("flush", _fi_t),
        ("release", _fi_t),
        ("fsync", _voidp_t),
        ("setxattr", _voidp_t),
        ("getxattr", _voidp_t),
        ("listxattr", _voidp_t),
        ("removexattr", _voidp_t),
        ("opendir", _voidp_t),
        ("readdir", _readdir_t),
        ("releasedir", _voidp_t),
        ("fsyncdir", _voidp_t),
        ("init", _voidp_t),
        ("destroy", _voidp_t),
        ("access", _voidp_t),
        ("create", _create_t),
        ("ftruncate", _ftruncate_t),
        ("fgetattr", _voidp_t),
        ("lock", _voidp_t),
        ("utimens", _utimens_t),
        ("bmap", _voidp_t),
        ("flags", ctypes.c_uint),  # flag_nullpath_ok etc. bitfield
        ("ioctl", _voidp_t),
        ("poll", _voidp_t),
        ("write_buf", _voidp_t),
        ("read_buf", _voidp_t),
        ("flock", _voidp_t),
        ("fallocate", _voidp_t),
    ]


def _fill_stat(st: Stat, d: dict) -> None:
    ctypes.memset(ctypes.byref(st), 0, ctypes.sizeof(st))
    st.st_mode = d["st_mode"]
    st.st_size = d["st_size"]
    st.st_nlink = d.get("st_nlink", 1)
    st.st_uid = d.get("st_uid", 0)
    st.st_gid = d.get("st_gid", 0)
    st.st_blksize = 4096
    st.st_blocks = (d["st_size"] + 511) // 512
    for name, key in (("st_atim", "st_atime"), ("st_mtim", "st_mtime"),
                      ("st_ctim", "st_ctime")):
        t = float(d.get(key, 0) or 0)
        ts = getattr(st, name)
        ts.tv_sec = int(t)
        ts.tv_nsec = int((t - int(t)) * 1e9)


class FuseMount:
    def __init__(self, fs: WeedFS):
        self.fs = fs
        path = ctypes.util.find_library("fuse") or "libfuse.so.2"
        self._lib = ctypes.CDLL(path)
        self.ops = FuseOperations()
        # pin the trampolines on self — libfuse keeps raw pointers
        self._cbs = {
            "getattr": _getattr_t(self._getattr),
            "readlink": _readlink_t(self._readlink),
            "open": _open_t(self._open),
            "read": _read_t(self._read),
            "statfs": _statfs_t(self._statfs),
            "readdir": _readdir_t(self._readdir),
            # write path
            "create": _create_t(self._create),
            "write": _write_t(self._write),
            "truncate": _truncate_t(self._truncate),
            "ftruncate": _ftruncate_t(self._ftruncate),
            "flush": _fi_t(self._flush),
            "release": _fi_t(self._release),
            "mkdir": _mkdir_t(self._mkdir),
            "unlink": _path_t(self._unlink),
            "rmdir": _path_t(self._rmdir),
            "rename": _rename_t(self._rename),
            "chmod": _chmod_t(self._chmod),
            # owner/time updates: accepted without persistence (the
            # filer keeps authoritative attrs; tar/cp must not fail)
            "chown": _chown_t(lambda p, u, g: 0),
            "utimens": _utimens_t(lambda p, ts: 0),
        }
        for name, cb in self._cbs.items():
            setattr(self.ops, name, cb)

    # -- callbacks (errno-style returns) ----------------------------------

    def _guard(self, fn, *args):
        try:
            return fn(*args)
        except FuseError as e:
            return -e.errno
        except Exception:  # noqa: BLE001 — never unwind into C
            return -errno.EIO

    def _getattr(self, path, stp):
        def run():
            _fill_stat(stp.contents,
                       self.fs.getattr(path.decode()))
            return 0
        return self._guard(run)

    def _readlink(self, path, buf, size):
        def run():
            target = self.fs.readlink(path.decode()).encode()
            n = min(len(target), size - 1)
            ctypes.memmove(buf, target, n)
            buf[n] = b"\x00"
            return 0
        return self._guard(run)

    @staticmethod
    def _fi_flags(fip) -> int:
        """fuse_file_info.flags is the struct's FIRST field (an int)."""
        if not fip:
            return 0
        return ctypes.cast(fip,
                           ctypes.POINTER(ctypes.c_int)).contents.value

    def _open(self, path, fip):
        return self._guard(
            lambda: self.fs.open(path.decode(),
                                 self._fi_flags(fip)) and 0)

    def _create(self, path, mode, fip):
        return self._guard(
            lambda: self.fs.create(path.decode(), mode) and 0)

    def _write(self, path, buf, size, offset, fip):
        def run():
            data = ctypes.string_at(buf, size)
            return self.fs.write(path.decode(), data, offset)
        return self._guard(run)

    def _truncate(self, path, length):
        return self._guard(
            lambda: self.fs.truncate(path.decode(), length) or 0)

    def _ftruncate(self, path, length, fip):
        return self._truncate(path, length)

    def _flush(self, path, fip):
        return self._guard(
            lambda: self.fs.flush(path.decode()) or 0)

    def _release(self, path, fip):
        import os as _os
        flags = self._fi_flags(fip)
        writable = bool(flags & (_os.O_WRONLY | _os.O_RDWR))
        return self._guard(
            lambda: self.fs.release(path.decode(), writable) or 0)

    def _chmod(self, path, mode):
        return self._guard(
            lambda: self.fs.chmod(path.decode(), mode) or 0)

    def _mkdir(self, path, mode):
        return self._guard(
            lambda: self.fs.mkdir(path.decode(), mode) or 0)

    def _unlink(self, path):
        return self._guard(
            lambda: self.fs.unlink(path.decode()) or 0)

    def _rmdir(self, path):
        return self._guard(
            lambda: self.fs.rmdir(path.decode()) or 0)

    def _rename(self, old, new):
        return self._guard(
            lambda: self.fs.rename(old.decode(), new.decode()) or 0)

    def _read(self, path, buf, size, offset, fip):
        def run():
            data = self.fs.read(path.decode(), size, offset)
            ctypes.memmove(buf, data, len(data))
            return len(data)
        return self._guard(run)

    def _statfs(self, path, svp):
        def run():
            d = self.fs.statfs(path.decode())
            ctypes.memset(ctypes.byref(svp.contents), 0,
                          ctypes.sizeof(svp.contents))
            for k, v in d.items():
                setattr(svp.contents, k, v)
            return 0
        return self._guard(run)

    def _readdir(self, path, buf, filler, offset, fip):
        def run():
            for name in self.fs.readdir(path.decode()):
                if filler(buf, name.encode(), None, 0):
                    break
            return 0
        return self._guard(run)

    # -- main loop --------------------------------------------------------

    def run(self, mountpoint: str, foreground: bool = True) -> int:
        """fuse_main_real: mounts and serves until unmounted
        (fusermount -u) or killed."""
        args = [b"seaweedfs-tpu", mountpoint.encode(), b"-s",
                b"-o", b"default_permissions"]
        if foreground:
            args.insert(2, b"-f")
        argv = (ctypes.c_char_p * len(args))(*args)
        return self._lib.fuse_main_real(
            len(args), argv, ctypes.byref(self.ops),
            ctypes.sizeof(self.ops), None)


def mount(filer: str, mountpoint: str, grpc_port: int = 0) -> int:
    fs = WeedFS(filer)
    # local control API (mount.proto SeaweedMount): lets an operator
    # adjust the mount's quota without remounting
    grpc_server = None
    try:
        from ..pb.mount_service import start_mount_grpc
        grpc_server, bound = start_mount_grpc(fs, port=grpc_port)
        print(f"mount control gRPC on 127.0.0.1:{bound}")
    except ImportError:
        pass
    except Exception as e:  # the mount itself must still proceed
        import sys
        print(f"mount control gRPC failed to start: {e!r}",
              file=sys.stderr)
    try:
        return FuseMount(fs).run(mountpoint)
    finally:
        if grpc_server is not None:
            grpc_server.stop(grace=0.5)
        fs.close()
