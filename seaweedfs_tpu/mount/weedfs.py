"""The mount op table (weed/mount/weedfs.go + weedfs_*.go op files):
filesystem operations answered from the filer's HTTP API, with a TTL'd
metadata cache invalidated by the filer's metadata-event stream
(mount/meta_cache + SubscribeMetadata in the reference).

Pure Python and kernel-free: `fuse_ctypes.py` adapts this table to
libfuse; tests drive it directly.
"""

from __future__ import annotations

import errno
import json
import os
import stat as stat_mod
import threading
import time
import urllib.parse
from dataclasses import dataclass, field

from ..filer.entry import FileChunk
from ..filer.filechunks import total_size
from ..server.httpd import http_bytes, http_json


@dataclass
class _WriteState:
    """One path's open-for-write state: INTERVAL dirty pages (the
    analog of mount/dirty_pages_chunked.go), not a whole-file buffer.

    `pages` is a sorted, non-overlapping list of (start, bytearray)
    intervals; once the buffered total crosses FLUSH_THRESHOLD the
    pages stream to the filer as overlapping chunks (later-wins
    resolution, filer/filechunks.py) and are dropped — memory stays
    bounded for arbitrarily large sequential writes.  `size` is the
    authoritative visible length while open; `trunc_point` is the
    low-water mark of any shrinking truncate since the last flush
    (the server must clip there BEFORE new pages land, or stale
    middle content would reappear)."""
    pages: list = field(default_factory=list)
    size: int = 0
    refs: int = 0
    dirty: bool = False
    truncated: bool = False
    trunc_point: "int | None" = None
    # serializes the NETWORK phase of flushes for this path: a slow
    # snapshot posted after a later flush would win the server-side
    # mtime race and resurrect stale bytes
    flush_lock: threading.Lock = field(default_factory=threading.Lock)

    def buffered(self) -> int:
        return sum(len(b) for _, b in self.pages)

    def covers(self, offset: int, size: int) -> bool:
        """True when one buffered interval spans the whole range (the
        common write-then-read-back pattern — no server round trip
        needed)."""
        for start, buf in self.pages:
            if start <= offset and offset + size <= start + len(buf):
                return True
        return False

    def read_overlay(self, offset: int, size: int) -> bytes:
        out = bytearray(size)
        for start, buf in self.pages:
            lo = max(start, offset)
            hi = min(start + len(buf), offset + size)
            if lo < hi:
                out[lo - offset:hi - offset] = buf[lo - start:hi - start]
        return bytes(out)

    def insert_missing(self, offset: int, data: bytes) -> None:
        """Requeue-after-failed-flush variant of insert: existing
        pages WIN (they hold newer writes made during the failed
        flush) — only the uncovered subranges are reinserted."""
        pos = offset
        end = offset + len(data)
        for start, buf in sorted(self.pages, key=lambda p: p[0]):
            pend = start + len(buf)
            if pend <= pos or start >= end:
                continue
            if pos < start:
                self.insert(pos, data[pos - offset:start - offset])
            pos = max(pos, pend)
        if pos < end:
            self.insert(pos, data[pos - offset:end - offset])

    def insert(self, offset: int, data: bytes) -> None:
        """Merge [offset, offset+len) into the interval list."""
        new_start, new_end = offset, offset + len(data)
        merged = bytearray(data)
        keep = []
        for start, buf in self.pages:
            end = start + len(buf)
            if end < new_start or start > new_end:
                keep.append((start, buf))
                continue
            # overlap/adjacency: splice existing bytes around the new
            if start < new_start:
                merged[0:0] = buf[:new_start - start]
                new_start = start
            if end > new_end:
                merged.extend(buf[new_end - start:])
                new_end = end
        keep.append((new_start, merged))
        keep.sort(key=lambda p: p[0])
        self.pages = keep

    def clip(self, length: int) -> None:
        kept = []
        for start, buf in self.pages:
            if start >= length:
                continue
            if start + len(buf) > length:
                buf = buf[:length - start]
            kept.append((start, buf))
        self.pages = kept


class FuseError(OSError):
    def __init__(self, err: int):
        super().__init__(err, errno.errorcode.get(err, str(err)))
        self.errno = err


class WeedFS:
    """Full op table: lookup/getattr, readdir, open/read, readlink
    (weedfs_attr.go, weedfs_dir_read.go, weedfs_file_read.go) plus the
    write path — create/write/truncate/flush, mkdir/unlink/rmdir,
    rename (weedfs_file_write.go, weedfs_dir_mkrm.go).

    Writes collect as INTERVAL dirty pages per open path
    (mount/dirty_pages_chunked.go): once FLUSH_THRESHOLD bytes are
    buffered they stream to the filer as overlapping chunks via
    /__chunk__/ (later-wins resolution), so arbitrarily large
    sequential writes run in bounded memory; flush/release drains the
    rest and applies any pending truncation."""

    MAX_CACHE_ENTRIES = 16384  # the reference's meta_cache is bounded

    # chunk-cache block size: reads are served from cached 1MB blocks
    # (util/chunk_cache, the reference mount's TieredChunkCache role)
    CHUNK_BLOCK = 1 << 20

    def __init__(self, filer: str, attr_ttl: float = 1.0,
                 follow_events: bool = True,
                 chunk_cache_mb: int = 64,
                 chunk_cache_dir: "str | None" = None,
                 chunk_cache_disk_mb: int = 1024):
        self.filer = filer
        self.attr_ttl = attr_ttl
        self._cache: dict[str, tuple[float, dict | None]] = {}
        self._writes: dict[str, _WriteState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._since_ns = time.time_ns()
        from ..util.chunk_cache import TieredChunkCache
        # without the event stream nothing would ever invalidate
        # cached data blocks — reads must stay always-fresh then
        self.chunk_cache = TieredChunkCache(
            mem_limit=chunk_cache_mb << 20,
            disk_dir=chunk_cache_dir,
            disk_limit=chunk_cache_disk_mb << 20) \
            if chunk_cache_mb > 0 and follow_events else None
        # collection quota (mount.proto Configure; weedfs_quota.go):
        # 0 = unlimited; above it, writes fail ENOSPC based on the
        # filer's cluster statistics, refreshed at most every 5s
        self.collection_capacity = 0
        self._quota_used = 0
        self._quota_checked = 0.0
        self._event_thread: threading.Thread | None = None
        if follow_events:
            self._event_thread = threading.Thread(
                target=self._follow_events, daemon=True)
            self._event_thread.start()

    def close(self) -> None:
        self._stop.set()

    # -- metadata cache (mount/meta_cache) --------------------------------

    def _lookup(self, path: str) -> dict | None:
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(path)
            if hit is not None and now - hit[0] <= self.attr_ttl:
                return hit[1]
        if path == "/":
            entry: dict | None = {"fullPath": "/",
                                  "isDirectory": True,
                                  "attributes": {"mode": 0o755}}
        else:
            st, body, _ = http_bytes(
                "GET", f"{self.filer}/__meta__/lookup?path=" +
                urllib.parse.quote(path))
            if st == 404:
                entry = None
            elif st != 200:
                raise FuseError(errno.EIO)
            else:
                entry = json.loads(body)
        with self._lock:
            if len(self._cache) >= self.MAX_CACHE_ENTRIES:
                # evict expired first; a crawler stat-ing millions of
                # distinct (incl. nonexistent) paths must not grow the
                # mount's memory without bound
                fresh = {p: v for p, v in self._cache.items()
                         if now - v[0] <= self.attr_ttl}
                if len(fresh) >= self.MAX_CACHE_ENTRIES:
                    fresh = dict(sorted(
                        fresh.items(), key=lambda kv: -kv[1][0]
                    )[:self.MAX_CACHE_ENTRIES // 2])
                self._cache = fresh
            self._cache[path] = (now, entry)
        return entry

    def _invalidate(self, path: str) -> None:
        with self._lock:
            self._cache.pop(path, None)
            parent = path.rsplit("/", 1)[0] or "/"
            self._cache.pop(parent, None)
        if self.chunk_cache is not None:
            # a changed file drops all of its cached data blocks —
            # the meta-event subscription (_follow_events) is the only
            # thing standing between the data-block cache and stale
            # reads, so every event path lands here.  (Blocks cached
            # by a PREVIOUS mount process are handled at the cache
            # layer: DiskChunkCache never serves adopted leftovers,
            # because the events that covered them died with the old
            # process.)
            self.chunk_cache.invalidate_path(path)

    def _follow_events(self) -> None:
        """Poll the filer's persistent metadata stream and invalidate
        touched paths (the reference's mount cache invalidation via
        SubscribeMetadata)."""
        while not self._stop.wait(self.attr_ttl / 2):
            try:
                r = http_json(
                    "GET", f"{self.filer}/__meta__/events"
                           f"?sinceNs={self._since_ns}&limit=1000")
            except OSError:
                continue
            for ev in r.get("events", []):
                for side in ("newEntry", "oldEntry"):
                    e = ev.get(side)
                    if e:
                        self._invalidate(e["fullPath"])
                self._since_ns = max(self._since_ns,
                                     int(ev.get("tsNs", 0)))

    # -- ops (weedfs_attr.go GetAttr) -------------------------------------

    @staticmethod
    def _entry_stat(entry: dict) -> dict:
        attrs = entry.get("attributes") or {}
        if entry.get("isDirectory"):
            mode = stat_mod.S_IFDIR | (attrs.get("mode", 0o755) & 0o7777)
            size = 4096
            nlink = 2
        elif attrs.get("symlinkTarget"):
            mode = stat_mod.S_IFLNK | 0o777
            size = len(attrs["symlinkTarget"])
            nlink = 1
        else:
            mode = stat_mod.S_IFREG | (attrs.get("mode", 0o644) & 0o7777)
            # max-extent size, the SAME definition the filer serves
            # bytes by — a summed size diverges on overlapping chunks
            # and makes the kernel clamp reads short
            size = total_size([FileChunk.from_json(c)
                               for c in entry.get("chunks", [])])
            nlink = 1
        return {"st_mode": mode, "st_size": size, "st_nlink": nlink,
                "st_uid": attrs.get("uid", 0),
                "st_gid": attrs.get("gid", 0),
                "st_mtime": float(attrs.get("mtime", 0) or 0),
                "st_ctime": float(attrs.get("crtime", 0) or 0),
                "st_atime": float(attrs.get("mtime", 0) or 0)}

    def getattr(self, path: str) -> dict:
        with self._lock:
            ws = self._writes.get(path)
            open_size = ws.size if ws is not None else None
        entry = self._lookup(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        st = self._entry_stat(entry)
        if open_size is not None:
            # overlay ONLY the size from the open write state (the
            # kernel stats after each write); mode/uid/gid/timestamps
            # stay the filer entry's truth
            st["st_size"] = open_size
        return st

    def readdir(self, path: str) -> "list[str]":
        entry = self._lookup(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        if not entry.get("isDirectory"):
            raise FuseError(errno.ENOTDIR)
        names = [".", ".."]
        last = ""
        while True:
            st, body, _ = http_bytes(
                "GET", self.filer +
                urllib.parse.quote(path.rstrip("/") + "/") +
                "?limit=1000&lastFileName=" +
                urllib.parse.quote(last))
            if st != 200:
                raise FuseError(errno.EIO)
            batch = json.loads(body).get("entries", [])
            names += [e["fullPath"].rsplit("/", 1)[-1] for e in batch]
            if len(batch) < 1000:
                return names
            last = batch[-1]["fullPath"].rsplit("/", 1)[-1]

    def open(self, path: str, flags: int = 0) -> int:
        entry = self._lookup(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        if entry.get("isDirectory"):
            raise FuseError(errno.EISDIR)
        if flags & (os.O_WRONLY | os.O_RDWR):
            # no whole-file seed read: non-TRUNC writes become
            # interval pages overlaid on the server content
            base_size = total_size([
                FileChunk.from_json(c)
                for c in entry.get("chunks", [])])
            with self._lock:
                ws = self._writes.setdefault(path, _WriteState())
                ws.refs += 1
                if ws.refs == 1:
                    ws.size = base_size
                if flags & os.O_TRUNC:
                    ws.pages = []
                    ws.size = 0
                    ws.truncated = ws.dirty = True
                    ws.trunc_point = 0
        return 0

    def read(self, path: str, size: int, offset: int) -> bytes:
        """Ranged read through the filer (weedfs_file_read.go —
        chunk-view resolution happens filer-side), with any open
        write-state's dirty pages overlaid on top (the kernel may
        read back what it just wrote before anything flushed)."""
        if size <= 0:
            return b""
        with self._lock:
            ws = self._writes.get(path)
            if ws is not None:
                size = max(0, min(size, ws.size - offset))
                if size and ws.covers(offset, size):
                    # fully in the dirty pages: no server round trip
                    return ws.read_overlay(offset, size)
                pages = [(s, bytes(b)) for s, b in ws.pages]
                trunc = ws.trunc_point
            else:
                pages = None
        if pages is not None and size == 0:
            return b""
        base = self._ranged_get_cached(path, offset, size)
        if pages is None:
            return base
        out = bytearray(size)            # gaps read as zeros
        out[:len(base)] = base[:size]
        if trunc is not None and trunc < offset + size:
            # a pending shrink: stale server bytes beyond the
            # truncation point must not show through the gaps
            lo = max(0, trunc - offset)
            out[lo:] = b"\x00" * (size - lo)
        for start, buf in pages:
            lo = max(start, offset)
            hi = min(start + len(buf), offset + size)
            if lo < hi:
                out[lo - offset:hi - offset] = \
                    buf[lo - start:hi - start]
        return bytes(out)

    def _ranged_get_cached(self, path: str, offset: int,
                           size: int) -> bytes:
        """Assemble a read from cached 1MB blocks (util/chunk_cache):
        repeated/sequential reads of a hot file hit memory (or the
        disk tier) instead of re-crossing to the filer.  Blocks drop
        when the file changes (the meta-event stream invalidates the
        path's group, same staleness window as the attr cache)."""
        if self.chunk_cache is None:
            return self._ranged_get(path, offset, size)
        B = self.CHUNK_BLOCK
        out = bytearray()
        pos, end = offset, offset + size
        while pos < end:
            bi = pos // B
            key = f"{path}@{bi}"
            block = self.chunk_cache.get(key)
            if block is None:
                block = self._ranged_get(path, bi * B, B)
                if block:
                    self.chunk_cache.set(key, block, group=path)
            lo = pos - bi * B
            want = min(end, (bi + 1) * B) - pos
            piece = block[lo:lo + want]
            out += piece
            if len(piece) < want:
                break  # EOF inside this block
            pos += want
        return bytes(out)

    def _ranged_get(self, path: str, offset: int, size: int) -> bytes:
        st, body, _ = http_bytes(
            "GET", self.filer + urllib.parse.quote(path), None,
            {"Range": f"bytes={offset}-{offset + size - 1}"})
        if st in (200, 206):
            return body if st == 206 else body[offset:offset + size]
        if st == 416:
            return b""                   # beyond EOF: overlay decides
        if st == 404:
            raise FuseError(errno.ENOENT)
        raise FuseError(errno.EIO)

    def readlink(self, path: str) -> str:
        entry = self._lookup(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        target = (entry.get("attributes") or {}).get("symlinkTarget")
        if not target:
            raise FuseError(errno.EINVAL)
        return target

    # -- write path (weedfs_file_write.go, simplified dirty buffer) -------

    QUOTA_REFRESH_SEC = 5.0

    def _check_quota(self) -> None:
        """ENOSPC once the cluster's used bytes exceed the configured
        collection capacity (weedfs_attr.go:45 IsOverQuota checks on
        every write-side op; usage refreshes like weedfs_quota.go)."""
        if self.collection_capacity <= 0:
            return
        now = time.monotonic()
        if now - self._quota_checked > self.QUOTA_REFRESH_SEC:
            self._quota_checked = now
            try:
                st, body, _ = http_bytes(
                    "GET", f"{self.filer}/__meta__/statistics")
                if st == 200:
                    self._quota_used = \
                        json.loads(body).get("usedSize", 0)
            except OSError:
                pass    # keep the last known usage
        if self._quota_used > self.collection_capacity:
            raise FuseError(errno.ENOSPC)

    def create(self, path: str, mode: int = 0o644) -> int:
        self._check_quota()
        # materialize the (empty) entry at the filer IMMEDIATELY: the
        # write-fsync-rename save pattern and cross-client readdir must
        # see the file while it is still open
        self._put(path, b"")
        if mode:
            self.chmod(path, mode)
        with self._lock:
            ws = self._writes.setdefault(path, _WriteState())
            ws.refs += 1
            if ws.refs == 1:
                ws.pages = []
                ws.size = 0
                ws.dirty = False
        return 0

    def chmod(self, path: str, mode: int) -> None:
        """Persist the mode via the filer's UpdateEntry analog — a
        silent-no-op chmod would claim success while exec bits never
        stick."""
        entry = self._lookup(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        attrs = dict(entry.get("attributes") or {})
        attrs["mode"] = mode & 0o7777
        self._set_attrs(path, attrs)

    def _set_attrs(self, path: str, attrs: dict) -> None:
        st, _, _ = http_bytes(
            "POST", f"{self.filer}/__meta__/set_attrs",
            json.dumps({"path": path, "attributes": attrs}).encode(),
            {"Content-Type": "application/json"})
        if st != 200:
            raise FuseError(errno.EIO)
        self._invalidate(path)

    # pages stream to the filer once this much is buffered — the
    # bound that makes huge sequential writes O(threshold) memory
    FLUSH_THRESHOLD = 8 * 1024 * 1024

    def write(self, path: str, data: bytes, offset: int) -> int:
        self._check_quota()
        with self._lock:
            ws = self._writes.get(path)
            if ws is None:
                raise FuseError(errno.EBADF)
            ws.insert(offset, data)
            ws.size = max(ws.size, offset + len(data))
            ws.dirty = True
            over = ws.buffered() >= self.FLUSH_THRESHOLD
        if over:
            self._flush_pages(path)
        return len(data)

    def truncate(self, path: str, length: int) -> None:
        with self._lock:
            ws = self._writes.get(path)
            if ws is not None:
                if length < ws.size:
                    ws.clip(length)
                    ws.trunc_point = length if ws.trunc_point is None \
                        else min(ws.trunc_point, length)
                ws.size = length
                ws.truncated = ws.dirty = True
                return
        # truncate without an open handle: server-side clip/extend,
        # no whole-file round trip.  Truncate-to-size is idempotent,
        # so a stale pooled connection may transparently retry
        st, _, _ = http_bytes(
            "POST", f"{self.filer}/__chunk__/" +
            urllib.parse.quote(path).lstrip("/") +
            f"?truncateTo={length}", b"", {"X-Idempotent": "1"})
        if st == 404:
            raise FuseError(errno.ENOENT)
        if st != 200:
            raise FuseError(errno.EIO)
        self._invalidate(path)

    def _chunk_post(self, path: str, offset: int, data: bytes,
                    truncate_to: "int | None" = None) -> None:
        q = f"?offset={offset}"
        if truncate_to is not None:
            q += f"&truncateTo={truncate_to}"
        st, _, _ = http_bytes(
            "POST", f"{self.filer}/__chunk__/" +
            urllib.parse.quote(path).lstrip("/") + q, data)
        if st != 200:
            raise FuseError(errno.EIO)

    def _flush_pages(self, path: str,
                     finalize: bool = False) -> None:
        """Stream buffered intervals to the filer as overlapping
        chunks (the dirty_pages_chunked.go writeback): shrink-clip
        first (stale middle content must not resurface), then the
        pages oldest-offset-first, then — on finalize — grow the
        visible size for pure zero-extensions."""
        with self._lock:
            ws = self._writes.get(path)
        if ws is None:
            return
        # serialize flushes per path: snapshot AND post under the
        # flush lock, so an earlier snapshot can never land after (and
        # thus server-mtime-beat) a later one
        with ws.flush_lock:
            with self._lock:
                pages, ws.pages = ws.pages, []
                trunc, ws.trunc_point = ws.trunc_point, None
                truncated = ws.truncated
                size = ws.size
                if finalize:
                    ws.truncated = False
            try:
                if trunc is not None:
                    self._chunk_post(path, 0, b"", truncate_to=trunc)
                for start, buf in pages:
                    self._chunk_post(path, start, bytes(buf))
                if finalize and truncated:
                    self._chunk_post(path, 0, b"", truncate_to=size)
            except FuseError:
                with self._lock:
                    ws2 = self._writes.get(path)
                    if ws2 is not None:
                        # re-queue for the next attempt; pages written
                        # meanwhile are NEWER and must win
                        for start, buf in pages:
                            ws2.insert_missing(start, bytes(buf))
                        if trunc is not None:
                            ws2.trunc_point = trunc if \
                                ws2.trunc_point is None else \
                                min(ws2.trunc_point, trunc)
                        ws2.truncated = ws2.truncated or truncated
                        ws2.dirty = True
                raise
        self._invalidate(path)

    def flush(self, path: str) -> None:
        """Flush dirty pages iff dirty (the kernel flushes on every
        close() of every dup'd fd — clean flushes must not re-upload
        anything)."""
        with self._lock:
            ws = self._writes.get(path)
            if ws is None or not ws.dirty:
                return
            ws.dirty = False
        self._flush_pages(path, finalize=True)

    def release(self, path: str, writable: bool = True) -> None:
        """`writable` mirrors the closing HANDLE's open mode (from
        fuse_file_info.flags): a read-only close must not decrement the
        write-state refcount — it would destroy a still-open writer's
        buffer."""
        if not writable:
            return
        self.flush(path)
        with self._lock:
            ws = self._writes.get(path)
            if ws is not None:
                ws.refs -= 1
                if ws.refs <= 0:
                    # last writable handle gone: drop the buffer
                    self._writes.pop(path, None)
        self._invalidate(path)

    def _drop_write_state(self, path: str) -> None:
        """After unlink/rename: a stale buffer keyed by the old path
        would resurrect the file on the next flush."""
        with self._lock:
            self._writes.pop(path, None)

    def _put(self, path: str, data: bytes) -> None:
        st, body, _ = http_bytes(
            "PUT", self.filer + urllib.parse.quote(path), data)
        if st not in (200, 201):
            raise FuseError(errno.EIO)
        self._invalidate(path)

    def mkdir(self, path: str, mode: int = 0o755) -> None:
        if self._lookup(path) is not None:
            raise FuseError(errno.EEXIST)
        st, _, _ = http_bytes(
            "PUT", self.filer +
            urllib.parse.quote(path.rstrip("/") + "/"))
        if st not in (200, 201):
            raise FuseError(errno.EIO)
        self._invalidate(path)

    def unlink(self, path: str) -> None:
        entry = self._lookup(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        if entry.get("isDirectory"):
            raise FuseError(errno.EISDIR)
        st, _, _ = http_bytes(
            "DELETE", self.filer + urllib.parse.quote(path))
        if st not in (200, 204):
            raise FuseError(errno.EIO)
        self._drop_write_state(path)
        self._invalidate(path)

    def rmdir(self, path: str) -> None:
        entry = self._lookup(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        if not entry.get("isDirectory"):
            raise FuseError(errno.ENOTDIR)
        # NON-recursive delete: the filer's own atomic 409 answers
        # non-empty — a pre-check + recursive=true would let a racing
        # create be silently destroyed
        st, _, _ = http_bytes(
            "DELETE", self.filer + urllib.parse.quote(path))
        if st == 409:
            raise FuseError(errno.ENOTEMPTY)
        if st not in (200, 204):
            raise FuseError(errno.EIO)
        self._invalidate(path)

    def rename(self, old: str, new: str) -> None:
        st, _, _ = http_bytes(
            "POST", f"{self.filer}/__meta__/rename",
            json.dumps({"oldPath": old, "newPath": new}).encode(),
            {"Content-Type": "application/json"})
        if st == 404:
            raise FuseError(errno.ENOENT)
        if st != 200:
            raise FuseError(errno.EIO)
        with self._lock:
            # open write buffers follow the file (or the renamed
            # DIRECTORY'S descendants) to their new names; left behind
            # they would resurrect the old paths on flush
            prefix = old.rstrip("/") + "/"
            for p in list(self._writes):
                if p == old:
                    self._writes[new] = self._writes.pop(p)
                elif p.startswith(prefix):
                    self._writes[new.rstrip("/") + "/" +
                                 p[len(prefix):]] = \
                        self._writes.pop(p)
        self._invalidate(old)
        self._invalidate(new)

    def statfs(self, path: str) -> dict:
        return {"f_bsize": 4096, "f_frsize": 4096,
                "f_blocks": 1 << 30, "f_bfree": 1 << 29,
                "f_bavail": 1 << 29, "f_files": 1 << 20,
                "f_ffree": 1 << 19, "f_namemax": 255}
