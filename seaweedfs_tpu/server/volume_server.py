"""Volume server: public HTTP data path + admin endpoints + heartbeat.

Data path mirrors the reference's public API exactly
(server/volume_server_handlers_write.go:19 PostHandler,
volume_server_handlers_read.go:138 GetOrHeadHandler):
GET/POST/DELETE on /<vid>,<fid>.

Admin gRPC surface (pb/volume_server.proto) is mirrored as JSON/HTTP
(see server/__init__.py): each handler cites its RPC.  The EC generate
handler preserves the reference's race invariant — the .ecx is written
BEFORE the shard files (volume_grpc_erasure_coding.go:89-98).
"""

from __future__ import annotations

import os
import queue
import re
import threading
import time
import urllib.parse

from ..util import wlog
from .. import security
from ..storage import types
from ..storage.erasure_coding import ECContext
from ..storage.erasure_coding import ec_decoder, ec_encoder
from ..storage.erasure_coding.ec_context import to_ext
from ..storage.needle import Needle
from ..storage.store import Store
from .httpd import FileSlice, HttpServer, Request, http_bytes, \
    http_download, http_json, is_admin_path

# shared request-field validator (also used by the master's assign
# front door) lives in security.py
_check_path_fields = security.check_path_fields

# byte offset of the payload inside a needle record (header + DataSize
# field) — the read plane's registration math (read_plane.py), reused
# when native WRITE-plane appends warm the read plane
_WP_DATA_OFFSET = types.NEEDLE_HEADER_SIZE + 4


class VolumeServer:
    def __init__(self, directories: list[str], master: str,
                 host: str = "127.0.0.1", port: int = 0,
                 public_url: str = "", pulse_seconds: float = 1.0,
                 data_center: str = "", rack: str = "",
                 max_volume_count: int = 8,
                 security_config: "security.SecurityConfig | None" = None,
                 fsync: bool = False):
        self.master = master
        self._security_override = security_config
        self.pulse_seconds = pulse_seconds
        self.data_center = data_center
        self.rack = rack
        self.http = HttpServer(host, port)
        self.store = Store(directories, ip=host, port=self.http.port,
                           public_url=public_url or self.http.url,
                           fsync=fsync)
        for loc in self.store.locations:
            loc.max_volume_count = max_volume_count
        r = self.http.route
        r("GET", "/status", self._status)
        # volume admin <- volume_server.proto AllocateVolume etc.
        r("POST", "/admin/allocate_volume", self._allocate_volume)
        r("POST", "/admin/delete_volume", self._delete_volume)
        r("POST", "/admin/mount_volume", self._mount_volume)
        r("POST", "/admin/unmount_volume", self._unmount_volume)
        r("POST", "/admin/set_readonly", self._set_readonly)
        r("POST", "/admin/configure_volume", self._configure_volume)
        r("POST", "/admin/vacuum", self._vacuum)
        r("GET", "/admin/volume_file", self._read_volume_file)
        r("POST", "/admin/receive_file", self._receive_file)
        # EC admin <- volume_server.proto:89-108
        r("POST", "/admin/ec/generate", self._ec_generate)
        r("POST", "/admin/ec/shard_write", self._ec_shard_write)
        r("POST", "/admin/ec/shard_write_commit",
          self._ec_shard_write_commit)
        r("POST", "/admin/ec/shard_write_abort",
          self._ec_shard_write_abort)
        r("POST", "/admin/ec/mount", self._ec_mount)
        r("POST", "/admin/ec/unmount", self._ec_unmount)
        r("POST", "/admin/ec/copy", self._ec_copy)
        r("POST", "/admin/ec/delete_shards", self._ec_delete_shards)
        r("POST", "/admin/ec/rebuild", self._ec_rebuild)
        r("POST", "/admin/ec/to_volume", self._ec_to_volume)
        r("GET", "/admin/ec/shard_read", self._ec_shard_read)
        r("GET", "/admin/ec/info", self._ec_info)
        r("POST", "/admin/query", self._query)
        r("POST", "/admin/tier_move", self._tier_move)
        r("POST", "/admin/tier_fetch", self._tier_fetch)
        r("GET", "/admin/volume_index", self._volume_index)
        r("POST", "/admin/delete_needle", self._admin_delete_needle)
        r("GET", "/admin/needle_raw", self._needle_raw)
        r("POST", "/admin/write_needle_raw", self._write_needle_raw)
        r("POST", "/admin/scrub", self._scrub)
        r("POST", "/admin/volume/merge", self._merge_volume)
        r("POST", "/admin/leave", self._leave)
        r("POST", "/admin/vacuum_toggle", self._vacuum_toggle)
        r("POST", "/admin/ec/scrub", self._ec_scrub)
        r("GET", "/metrics", self._metrics)
        from .debug import install_debug_routes
        install_debug_routes(self.http)  # util/grace/pprof.go analog
        self.http.fallback = self._data_path
        self.http.guard = self._guard
        self._hb_stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self._topology_id = ""
        self._last_hb_error: str | None = None
        # staged scatter-encode shard uploads awaiting commit:
        # uploadId -> {path, crc, bytes, vid, collection, stamp}
        self._pending_shard_writes: dict[str, dict] = {}
        self._pending_lock = threading.Lock()
        from .store_ec import EcReader
        self.ec_reader = EcReader(
            master, self.http.url,
            security_headers=lambda: self.security.admin_headers())
        # hot-needle cache (util/chunk_cache promoted server-side, the
        # reference's chunk_cache role at the volume tier): repeated
        # reads of a hot needle skip the index lookup + .dat read (and
        # for EC volumes the whole interval/degraded resolution).  Keys
        # carry a per-volume generation so compact-swap / merge /
        # unmount invalidate wholesale without enumerating needles;
        # write/delete invalidate their needle's group point-wise.
        from ..util.chunk_cache import (TieredChunkCache, read_cache_mb,
                                        read_cache_disk)
        mb = read_cache_mb(64)
        disk_dir, disk_mb = read_cache_disk()
        self.needle_cache = TieredChunkCache(
            mem_limit=mb << 20,
            disk_dir=(os.path.join(disk_dir, f"vol{self.http.port}")
                      if disk_dir else None),
            disk_limit=disk_mb << 20,
            name="volume_needle") if mb > 0 else None
        self._nc_gen: dict[int, int] = {}
        self._nc_gen_lock = threading.Lock()
        # fill/invalidate race guard: a GET that read the store BEFORE
        # a write landed must not cache its (now stale) needle AFTER
        # the write's invalidation ran — fills carry the epoch they
        # began at and land only if no invalidation intervened (the
        # same rule the filer metadata cache enforces)
        self._nc_epoch = 0
        from ..stats import Metrics
        self.metrics = Metrics("volume_server")
        self.http.role = "volume"        # tracing + request_seconds
        self.http.metrics = self.metrics
        # QoS plane (qos.py): tenant admission scoped to the admin /
        # maintenance plane (foreground needle traffic is internal and
        # protected by the EC feedback throttle, not tenant buckets);
        # this role's request_seconds is the throttle's primary
        # foreground signal — EC jobs hammer exactly these servers
        from .. import qos
        qos.install(self.http, "volume", path_prefix="/admin/")
        qos.throttle().add_metrics(f"volume:{self.http.port}",
                                   self.metrics)
        qos.throttle().maybe_start()
        # SLO autopilot (autopilot.py, ISSUE 20): this role's loop
        # owns the hot-needle cache size
        from .. import autopilot as _autopilot
        from .debug import install_autopilot_routes
        self.autopilot = _autopilot.build_for_volume(self)
        install_autopilot_routes(self.http, self.autopilot)
        self.autopilot.start()

    # -- lifecycle --------------------------------------------------------

    def start(self):
        # sweep staged scatter-upload temps orphaned by a crash: the
        # in-memory pending registry died with the old process, so
        # nothing else will ever reclaim these multi-MB files (the
        # lazy reaper only sees uploads registered in THIS process)
        for loc in self.store.locations:
            try:
                names = os.listdir(loc.directory)
            except OSError:
                continue
            for name in names:
                if ".scatter." in name:
                    try:
                        os.remove(os.path.join(loc.directory, name))
                    except OSError:
                        pass
        self.http.start()
        # UDS zero-copy read plane (RDMA sidecar analog,
        # seaweedfs-rdma-sidecar/rdma-engine/src/ipc.rs): same-host
        # readers fetch raw needle records via sendfile(2); path
        # advertised in /status (udsPath)
        self.uds_server = None
        if not self.security.volume_read_key:
            # the UDS plane carries no JWT; with read signing
            # configured it would be an auth bypass for any local
            # process, so it only exists on unauthenticated-read
            # deployments
            try:
                from .uds_reader import UdsNeedleServer
                sock = os.path.join(
                    self.store.locations[0].directory, "uds.sock")
                self.uds_server = UdsNeedleServer(
                    self.store, sock,
                    on_read=self._rp_warm_key).start()
            except OSError:  # pragma: no cover — no AF_UNIX
                self.uds_server = None
        # native TCP read plane (the C++ second implementation of the
        # needle-read surface — seaweed-volume/ Rust server +
        # rdma-sidecar role, native/read_plane.cc): plain needles are
        # served by an epoll+sendfile loop; port advertised in /status
        # (readPlanePort).  Same auth rule as the UDS plane.
        self.read_plane = None
        self._rp_volumes: set[int] = set()
        self._rp_lock = threading.Lock()
        self._rp_gen: dict[int, int] = {}
        self._rp_seen: dict[int, set] = {}
        self._rp_queue = None
        if not self.security.volume_read_key:
            try:
                from .read_plane import ReadPlane
                self.read_plane = ReadPlane(self.http.host)
            except (RuntimeError, OSError):
                self.read_plane = None
        if self.read_plane is not None:
            # write-path registrations drain through a worker so the
            # needle ack never waits on plane bookkeeping (the plane
            # is a read cache: until the entry lands, reads fall back
            # to this port and warm it lazily).  Bounded; overflow
            # drops the registration, lazy warm recovers it.
            self._rp_queue = queue.Queue(maxsize=4096)
            threading.Thread(target=self._rp_worker,
                             daemon=True).start()
            # flight-deck drainer (ISSUE 18): plane-served reads train
            # the hedge read_tracker + feed the flight recorder
            self.read_plane.start_record_drain()
        # native TCP WRITE plane (native/write_plane.cc — the C++
        # sibling of the read plane on the needle-write hot path):
        # plain anonymous uploads are recv'd, serialized, appended and
        # acked by an epoll loop; everything else 404s and the client
        # falls back to this port.  Same auth rule as the read plane
        # (the plane carries no JWT), kill switch
        # SEAWEEDFS_TPU_WRITE_PLANE=0.
        self.write_plane = None
        if not self.security.volume_write_key and \
                os.environ.get("SEAWEEDFS_TPU_WRITE_PLANE", "1") \
                not in ("0", "false"):
            try:
                from .write_plane import WritePlane
                self.write_plane = WritePlane(
                    self.http.host, on_tick=self._wp_tick,
                    on_epoch=self._wp_epoch)
            except (RuntimeError, OSError):
                self.write_plane = None   # pure-Python fallback
        if self.write_plane is not None:
            # eager attach: a volume the plane doesn't own answers
            # every native write with a 404 + client fallback, so
            # eligible volumes are handed over up front (and re-synced
            # at every lifecycle transition below)
            for loc in self.store.locations:
                for vid in list(loc.volumes):
                    self._wp_sync_volume(vid)
            self.write_plane.start_record_drain()
        # gRPC wire plane (volume_server.proto subset) — optional;
        # JSON-HTTP stays the always-on surface
        try:
            from ..pb.volume_service import start_volume_grpc
            self.grpc_server, self.grpc_port = start_volume_grpc(
                self, self.http.host)
        except ImportError:  # grpcio absent: HTTP-only mode
            self.grpc_server, self.grpc_port = None, 0
        except Exception as e:  # pragma: no cover — a real defect
            self.grpc_server, self.grpc_port = None, 0
            wlog.error(f"volume server {self.url}: gRPC plane failed to "
                  f"start: {e!r}")
        self._heartbeat_once()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True)
        self._hb_thread.start()
        return self

    def _rp_worker(self) -> None:
        while True:
            item = self._rp_queue.get()
            if item is None:
                return
            try:
                vid, n = item
                if isinstance(n, int):
                    # key-only warm (UDS on_read hook): the serve path
                    # only touched the needle map, so re-read the
                    # record here — off the hot path, once per needle
                    # (the _rp_seen gate below makes repeats free)
                    if n in self._rp_seen.get(vid, ()):
                        continue
                    n = self.store.read_needle(vid, n)
                self._rp_register(vid, n, lazy=True)
            except Exception:  # noqa: SWFS004 — read-plane cache
                pass           # upkeep must never kill the worker

    def _rp_warm_key(self, vid: int, key: int) -> None:
        """UDS post-serve hook: lazily mirror a needle the zero-copy
        path just served into the native read plane.  Without this,
        needles only ever read over UDS never reach the plane and the
        filer's native read funnel 404s on them forever."""
        q = getattr(self, "_rp_queue", None)
        if q is None or key in self._rp_seen.get(vid, ()):
            return
        try:
            q.put_nowait((vid, key))
        except queue.Full:
            pass           # drop: the next UDS read retries

    def _rp_enqueue(self, vid: int, needle) -> None:
        """Async write-path registration (see start()); no-op without
        the plane (getattr: a request can land between http.start()
        and the plane's init)."""
        q = getattr(self, "_rp_queue", None)
        if q is None:
            return
        try:
            q.put_nowait((vid, needle))
        except queue.Full:
            pass           # drop: lazy warm re-registers on first read

    def _rp_register(self, vid: int, needle,
                     lazy: bool = False) -> None:
        """Mirror a plain needle into the native read plane (write
        path + lazy on-read warm); no-ops without the plane.

        Epoch-checked against _rp_drop_volume: the needle offset is
        read AFTER snapshotting the volume's drop generation and the
        plane entry lands only if no drop intervened — otherwise a
        lazy warm racing a vacuum could re-bind pre-compaction offsets
        against the post-compaction .dat (silent wrong bytes)."""
        rp = self.read_plane
        if rp is None:
            return
        if lazy and needle.id in self._rp_seen.get(vid, ()):
            return      # already warm: skip the flush + native call
        v = self.store.find_volume(vid)
        if v is None or getattr(v, "version", 2) < 2:
            return      # v1 records lack the DataSize field the
            # plane's offset math assumes
        with self._rp_lock:
            gen = self._rp_gen.get(vid, 0)
        got = v.nm.get(needle.id)
        if got is None:
            return
        if lazy:
            # the plane reads its own fd: buffered appends must reach
            # the OS file before the entry is servable.  The write
            # path skips this — write_needle's group-commit barrier
            # already flushed the record before acking, so another
            # flush here would only re-serialize writers on the
            # volume lock.
            v.flush()
        with self._rp_lock:
            if self._rp_gen.get(vid, 0) != gen:
                return  # dropped (vacuum/delete) after our offset read
            if vid not in self._rp_volumes:
                try:
                    if not rp.add_volume(vid, v.file_name(".dat")):
                        return
                except OSError:
                    return
                self._rp_volumes.add(vid)
            rp.register_needle(vid, got[0], needle)
            self._rp_seen.setdefault(vid, set()).add(needle.id)

    # -- native write plane glue (server/write_plane.py) ------------------

    def _wp_sync_volume(self, vid: int) -> None:
        """(Re-)offer a volume to the native write plane after a
        lifecycle transition; attach failures fall back lazily — the
        Python port owns the writes and nothing breaks (the read
        plane's registration-failure contract)."""
        wp = getattr(self, "write_plane", None)
        if wp is None:
            return
        v = self.store.find_volume(vid)
        if v is None:
            return
        try:
            v.attach_native(wp)   # False for ineligible shapes
        except (OSError, RuntimeError, ValueError) as e:
            wlog.warning(f"write plane attach vid={vid} failed "
                         f"(python path serves it): {e!r}")

    def _wp_tick(self) -> None:
        """Pump-thread tick: drain every attached volume's completed
        native appends into its needle map / .idx checkpoint, and
        mirror them into the read plane (epoch-checked like
        _rp_register — a vacuum racing the drain drops the warm, lazy
        re-registration recovers it)."""
        rp = self.read_plane
        for loc in self.store.locations:
            for v in list(loc.volumes.values()):
                vid = v.id
                with self._rp_lock:
                    gen = self._rp_gen.get(vid, 0)
                entries = v.drain_native()
                if not entries or rp is None:
                    continue
                data_off_base = _WP_DATA_OFFSET
                with self._rp_lock:
                    if self._rp_gen.get(vid, 0) != gen:
                        continue   # dropped mid-drain: offsets stale
                    if vid not in self._rp_volumes:
                        try:
                            if not rp.add_volume(
                                    vid, v.file_name(".dat")):
                                continue
                        except OSError:
                            continue
                        self._rp_volumes.add(vid)
                    seen = self._rp_seen.setdefault(vid, set())
                    for e in entries:
                        rp.register_raw(
                            vid, e.key, e.cookie,
                            e.offset + data_off_base, e.data_len)
                        seen.add(e.key)

    def _wp_epoch(self, vid: int, epoch: int) -> None:
        """fsync-tier handshake: parked native acks wait on the
        volume's CommitBarrier — one barrier (one os.fsync) covers
        the whole epoch window, group commit across the C++
        boundary."""
        v = self.store.find_volume(vid)
        if v is not None:
            v._barrier.commit()

    def _rp_drop_volume(self, vid: int) -> None:
        """Forget a volume in the read plane (vacuum swapped the .dat,
        or the volume is gone); live needles lazily re-register.  The
        hot-needle cache drops the volume too — every caller of this
        is a point where the .dat is swapped, merged, or unmounted."""
        self._nc_drop_volume(vid)
        if self.read_plane is not None:
            with self._rp_lock:
                self._rp_gen[vid] = self._rp_gen.get(vid, 0) + 1
                self.read_plane.remove_volume(vid)
                self._rp_volumes.discard(vid)
                self._rp_seen.pop(vid, None)

    # -- hot-needle cache (util/chunk_cache server tier) ------------------

    def _nc_key(self, vid: int, key: int, cookie: int) -> str:
        with self._nc_gen_lock:
            gen = self._nc_gen.get(vid, 0)
        return f"{vid}.g{gen}.{key:x}.{cookie:08x}"

    def _nc_get(self, fid: types.FileId) -> "tuple[str, bytes] | None":
        """Cached (mime, data) for a needle, or None.  The cookie is
        part of the key: a wrong-cookie request misses and takes the
        store path, which raises the CookieMismatch the cache must not
        paper over."""
        if self.needle_cache is None:
            return None
        blob = self.needle_cache.get(
            self._nc_key(fid.volume_id, fid.key, fid.cookie))
        if blob is None:
            return None
        mlen = int.from_bytes(blob[:2], "big")
        return blob[2:2 + mlen].decode(), blob[2 + mlen:]

    def _nc_put(self, fid: types.FileId, n,
                token: "int | None" = None) -> None:
        """Promote a freshly read needle.  TTL'd needles stay out (the
        cache has no expiry clock of its own), as do bodies over the
        memory tier's bound (MemChunkCache skips them anyway).
        `token` is the epoch the fill's store read began at — a fill
        racing an invalidation is discarded, never resurrected."""
        if self.needle_cache is None or n.has_ttl():
            return
        if token is not None and token != self._nc_epoch:
            return
        mime = n.mime.decode() if n.mime else "application/octet-stream"
        blob = len(mime.encode()).to_bytes(2, "big") + \
            mime.encode() + bytes(n.data)
        key = self._nc_key(fid.volume_id, fid.key, fid.cookie)
        self.needle_cache.set(key, blob,
                              group=f"{fid.volume_id}.{fid.key:x}")
        # the pre-set epoch check alone is not atomic with set(): an
        # invalidation completing in between would wipe the group
        # BEFORE our key joined it, resurrecting the stale needle.
        # Re-verify after the insert and undo our own fill — one of
        # the two (group wipe or this delete) always removes it.
        if token is not None and token != self._nc_epoch:
            self.needle_cache.delete(key)

    def _nc_invalidate_needle(self, vid: int, key: int) -> None:
        """Point invalidation for one needle (every cookie spelling:
        the group is keyed without the cookie, so an admin delete that
        carries none still clears it)."""
        if self.needle_cache is not None:
            with self._nc_gen_lock:
                self._nc_epoch += 1
            self.needle_cache.invalidate_group(f"{vid}.{key:x}")

    def _nc_drop_volume(self, vid: int) -> None:
        """Wholesale invalidation by generation bump: old keys become
        unreachable and age out of the LRU (compact-swap, merge,
        unmount, delete, ec_to_volume, received .dat)."""
        if self.needle_cache is not None:
            with self._nc_gen_lock:
                self._nc_epoch += 1
                self._nc_gen[vid] = self._nc_gen.get(vid, 0) + 1

    def stop(self):
        self._hb_stop.set()
        from .. import qos
        if getattr(self, "autopilot", None) is not None:
            self.autopilot.stop()
        qos.throttle().remove_source(f"volume:{self.http.port}")
        if getattr(self, "_rp_queue", None) is not None:
            try:
                self._rp_queue.put_nowait(None)   # end the worker
            except queue.Full:
                pass           # daemon worker dies with the process
        if getattr(self, "read_plane", None) is not None:
            self.read_plane.stop()
        if getattr(self, "uds_server", None) is not None:
            self.uds_server.stop()
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop(grace=0.5)
        self.http.stop()
        self.ec_reader.close()
        # store.close() detaches every volume from the write plane
        # (drain + .idx checkpoint), so the plane must outlive it
        self.store.close()
        if getattr(self, "write_plane", None) is not None:
            self.write_plane.stop()

    @property
    def url(self) -> str:
        return self.http.url

    # -- auth (security/guard.go Guard + jwt.go) --------------------------

    @property
    def security(self) -> "security.SecurityConfig":
        # late-bound so security.configure() after construction applies
        return self._security_override or security.current()

    def _guard(self, req: Request):
        """Admin-plane gate (guard.go WhiteList+Jwt: every admin RPC is
        credential-gated in the reference)."""
        if is_admin_path(req.path):
            err = self.security.check_admin(req.query, req.headers,
                                            req.remote_ip)
            if err:
                return 401, {"error": err}
        return None

    # -- heartbeat (volume_grpc_client_to_master.go:51) -------------------

    def _heartbeat_once(self) -> None:
        hb = self.store.collect_heartbeat()
        if self.data_center:
            hb["dataCenter"] = self.data_center
        if self.rack:
            hb["rack"] = self.rack
        try:
            from .. import faults
            # armed `master.heartbeat` faults: delay stalls this pulse
            # (the chaos suite's slow-heartbeat scenario), error skips
            # it entirely — both retried next pulse like a real stall
            faults.fire("master.heartbeat", key=self.url)
            from ..operation import master_json
            # master_json re-dials the raft leader on "not leader"
            # replies (volume_grpc_client_to_master.go:109
            # doHeartbeatWithRetry re-dials on leader change)
            r = master_json(self.master, "POST", "/heartbeat", hb,
                            timeout=5,
                            headers=self.security.admin_headers())
        except OSError:
            return  # no leader reachable; retry next pulse
        err = r.get("error")
        if err:
            # a rejected heartbeat (bad admin key, whitelist miss) means
            # this server is invisible to the master — say so, once per
            # distinct error, instead of looping silently unregistered
            if err != self._last_hb_error:
                self._last_hb_error = err
                wlog.warning(f"volume server {self.url}: heartbeat rejected "
                      f"by master: {err}")
            return
        self._last_hb_error = None
        tid = r.get("topologyId", "")
        if tid and tid != self._topology_id:
            # new leadership epoch: this heartbeat already re-registered
            # the full volume/shard state (heartbeats are always full);
            # remember the id so a changed epoch is observable
            self._topology_id = tid

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.pulse_seconds):
            self._heartbeat_once()

    # -- public data path -------------------------------------------------

    def _data_path(self, req: Request):
        fid_str = req.path.lstrip("/")
        try:
            fid = types.parse_file_id(fid_str)
        except ValueError:
            return 404, {"error": f"bad file id {fid_str!r}"}
        self.metrics.counter_add(
            "request_total", 1.0,
            help_text="data-path requests", method=req.method)
        # per-fid JWT gate (volume_server_handlers_write.go
        # maybeCheckJwtAuthorization): writes/deletes need a token signed
        # with the write key, reads with the read key — when configured
        sec = self.security
        key = sec.volume_read_key if req.method in ("GET", "HEAD") \
            else sec.volume_write_key
        err = sec.check_fid_jwt(key, req.query, req.headers, str(fid))
        if err:
            return 401, {"error": err}
        if req.method in ("GET", "HEAD"):
            return self._get_needle(fid, req.headers.get("Range", ""),
                                    req.query, req=req)
        if req.method in ("POST", "PUT"):
            # body deliberately untouched here: the first read happens
            # inside _put_needle's "recv" stage so the decomposition
            # sees the true socket-drain cost
            return self._put_needle(fid, req)
        if req.method == "DELETE":
            return self._delete_needle(fid, req)
        return 405, {"error": "method not allowed"}

    def _metrics(self, req: Request):
        """Prometheus text endpoint (stats/metrics.go:49-662 analog)."""
        hb = self.store.collect_heartbeat()
        self.metrics.gauge_set("volumes", len(hb["volumes"]),
                               help_text="mounted volumes")
        self.metrics.gauge_set("ec_volumes", len(hb["ecShards"]))
        self.metrics.gauge_set(
            "max_volume_count", hb["maxVolumeCount"])
        from ..stats import render_process
        return 200, ((self.metrics.render() +
                      self._plane_metrics_text() +
                      render_process()).encode(),
                     "text/plain; version=0.0.4")

    def _plane_metrics_text(self) -> str:
        """Native-plane counters rendered straight from the C++
        atomics (the plane has no Python on its hot path, so the
        registry hears about it only at scrape time): write-plane
        requests/fallbacks + native-ack latency histogram, and the
        read plane's served counter beside its Python-port fallback
        sibling (counted in _get_needle)."""
        out = []
        rp = getattr(self, "read_plane", None)
        if rp is not None:
            out.append(
                "# HELP volume_server_read_plane_requests_total "
                "needle reads served by the native read plane\n"
                "# TYPE volume_server_read_plane_requests_total "
                "counter\n"
                f"volume_server_read_plane_requests_total "
                f"{rp.served()}\n")
        wp = getattr(self, "write_plane", None)
        if wp is None:
            return "".join(out)
        out.append(
            "# HELP volume_server_write_plane_requests_total needle "
            "writes acked by the native write plane\n"
            "# TYPE volume_server_write_plane_requests_total counter\n"
            f"volume_server_write_plane_requests_total "
            f"{wp.requests()}\n"
            "# HELP volume_server_write_plane_fallbacks_total native "
            "writes answered 404 (python port owns them)\n"
            "# TYPE volume_server_write_plane_fallbacks_total "
            "counter\n"
            f"volume_server_write_plane_fallbacks_total "
            f"{wp.fallbacks()}\n")
        from .write_plane import ACK_BUCKETS_S
        buckets, count, total_s = wp.ack_histogram()
        out.append("# HELP volume_server_write_plane_ack_seconds "
                   "native write-plane ack latency\n"
                   "# TYPE volume_server_write_plane_ack_seconds "
                   "histogram\n")
        for le, cum in zip(ACK_BUCKETS_S, buckets):
            out.append(f"volume_server_write_plane_ack_seconds_bucket"
                       f'{{le="{le}"}} {cum}\n')
        out.append(f"volume_server_write_plane_ack_seconds_bucket"
                   f'{{le="+Inf"}} {count}\n'
                   f"volume_server_write_plane_ack_seconds_sum "
                   f"{total_s}\n"
                   f"volume_server_write_plane_ack_seconds_count "
                   f"{count}\n")
        return "".join(out)

    def _get_needle(self, fid: types.FileId, rng: str = "",
                    query: "dict | None" = None, req=None):
        # armed `volume.read.serve` faults (delay: one slow replica;
        # error: one dead replica) fire before the cache OR the store
        # answers — the chaos lever behind the hedged-read scenarios;
        # keyed by this server's url so `match` can wedge exactly one
        # replica of a volume
        from .. import faults
        faults.fire("volume.read.serve", key=f"{self.http.url}/{fid}")
        cached = self._nc_get(fid)
        if cached is not None:
            mime, data = cached
        else:
            token = self._nc_epoch    # BEFORE the store read
            try:
                n = self.store.read_needle(fid.volume_id, fid.key,
                                           cookie=fid.cookie,
                                           ec_reader=self.ec_reader)
            except KeyError:
                return 404, {"error": "not found"}
            except ValueError as e:
                return 404, {"error": str(e)}
            if self.read_plane is not None:
                # symmetry with write_plane_fallbacks_total: a read
                # served here while the native plane is up is a
                # fallback (unwarmed, non-plain, or a client that
                # never tried the plane)
                self.metrics.counter_add(
                    "read_plane_fallbacks_total", 1.0,
                    help_text="python-port data reads while the "
                              "native read plane is active")
            self._rp_register(fid.volume_id, n, lazy=True)  # plane warm
            if not getattr(n, "was_degraded", False) or \
                    os.environ.get("SEAWEEDFS_TPU_DEGRADED_PROMOTE",
                                   "1") not in ("0", "false"):
                # degraded decodes are promoted by default (the
                # zipfian payoff: first read pays the d-way fan-out,
                # the rest hit memory) — the knob opts a cluster out
                # when decode results must never occupy cache
                self._nc_put(fid, n, token=token)
            mime = n.mime.decode() if n.mime \
                else "application/octet-stream"
            data = n.data
        if query and ("width" in query or "height" in query):
            # resize-on-read (volume_server_handlers_read.go:353 ->
            # images/resizing.go)
            from .. import images
            try:
                w = int(query.get("width", 0))
                h = int(query.get("height", 0))
            except ValueError:
                w = h = 0
            data = images.resized(data, mime, w, h,
                                  query.get("mode", ""))
        # response-side QoS byte metering (qos.charge_response): a
        # cache-hit stampede spends the tenant's in-flight-bytes
        # budget exactly like a store-read would — the hot cache must
        # not be a QoS bypass
        def _serve(status: int, body: bytes, headers: dict):
            if req is not None:
                from .. import qos
                release, deny = qos.charge_response(req, len(body),
                                                    "volume")
                if deny is not None:
                    return deny
                if release is not None:
                    headers = {**headers,
                               "Content-Length": str(len(body))}
                    return status, (qos.MeteredBody(body, release),
                                    headers)
            return status, (body, headers)

        # ranged needle reads keep the filer's chunk-view reads from
        # overfetching whole chunks (volume_server_handlers_read.go
        # serves Range on the data path)
        if rng.startswith("bytes="):
            try:
                lo, _, hi = rng[6:].partition("-")
                total = len(data)
                if lo:
                    start = int(lo)
                    stop = int(hi) + 1 if hi else total
                else:
                    start = total - min(int(hi), total)
                    stop = total
                part = data[start:stop]
                return _serve(206, part, {
                    "Content-Type": mime,
                    "Content-Range":
                        f"bytes {start}-{start + len(part) - 1}"
                        f"/{total}"})
            except ValueError:
                pass
        return _serve(200, data, {"Content-Type": mime})

    def _put_needle(self, fid: types.FileId, req: Request):
        # armed `volume.write.serve` faults (delay: one wedged
        # replica; error: one dead replica) fire before the write
        # track opens — the WRITE-side sibling of volume.read.serve,
        # the chaos lever behind the deadline/flight-recorder
        # scenarios; keyed by this server's url so `match` can wedge
        # exactly one replica of a volume
        from .. import faults
        faults.fire("volume.write.serve", key=f"{self.http.url}/{fid}")
        # write-path latency decomposition (profiling.py): the track
        # covers this handler; recv/index/append/flush/replicate stage
        # cells land in write_stage_seconds{stage} plus sibling trace
        # spans, so both `bench.py write_path` and `trace.show` can
        # say WHERE a slow write spent its time (the 50x ROADMAP gap
        # is unlocatable without this, arXiv:1709.05365 §5)
        from .. import profiling
        with profiling.track("write", role="volume",
                             metrics=self.metrics):
            return self._put_needle_tracked(fid, req)

    def _put_needle_tracked(self, fid: types.FileId, req: Request):
        from .. import profiling
        with profiling.stage("recv"):
            body = req.body
        self.metrics.counter_add("received_bytes", len(body))
        with profiling.stage("prep"):
            # needle construction is real per-request work (CRC over
            # the body, header encode) — uninstrumented it hides as
            # unattributed wall in the decomposition
            n = Needle(cookie=fid.cookie, id=fid.key, data=body)
            name = req.query.get("name", "")
            if name:
                n.set_name(name.encode())
            mime = req.headers.get("Content-Type", "")
            if mime and mime not in ("application/octet-stream",
                                     "multipart/form-data"):
                n.set_mime(mime.encode())
            ts = req.query.get("ts")
            ts_val = int(ts) if ts else int(time.time())
            n.set_last_modified(ts_val)
        try:
            size, unchanged = self.store.write_needle(fid.volume_id, n)
        except KeyError:
            return 404, {"error": f"volume {fid.volume_id} not found"}
        except PermissionError as e:
            return 409, {"error": str(e)}
        self._nc_invalidate_needle(fid.volume_id, fid.key)
        with profiling.stage("register"):
            self._rp_enqueue(fid.volume_id, n)
        # synchronous replication fan-out
        # (topology/store_replicate.go:27 ReplicatedWrite); forward the
        # original Content-Type and stamp ts so every replica writes a
        # byte-identical needle record (store_replicate.go ReplicatedWrite
        # forwards the request as-is)
        if req.query.get("type") != "replicate":
            # always set Content-Type: with a body and no header urllib
            # injects x-www-form-urlencoded, which the replica would store
            # as the needle mime (octet-stream is in the excluded list)
            with profiling.stage("replicate"):
                err = self._replicate(
                    fid, req, "POST", body,
                    extra_query={"ts": str(ts_val)},
                    headers={"Content-Type":
                             mime or "application/octet-stream"})
            if err:
                # the flight record of a failed write names the
                # replication fan-out, not just "500"
                profiling.flight_note("replicate", {"error": str(err)})
                return 500, {"error": f"replication: {err}"}
        return 201, {"name": name, "size": size, "eTag": n.etag(),
                     "unchanged": unchanged}

    def _delete_needle(self, fid: types.FileId, req: Request):
        if self.read_plane is not None:
            self.read_plane.delete_needle(fid.volume_id, fid.key)
        try:
            freed = self.store.delete_needle(
                fid.volume_id, Needle(cookie=fid.cookie, id=fid.key))
        except KeyError:
            freed = None
        # AFTER the store mutation (like _put_needle): invalidating
        # first would let a concurrent GET re-cache the pre-delete
        # needle with no later invalidation ever coming
        self._nc_invalidate_needle(fid.volume_id, fid.key)
        # deletes fan out like writes (store_replicate.go:142
        # ReplicatedDelete; EC: store_ec_delete.go:38) — a delete lost on
        # one holder would leave the object readable there via the read
        # path's location fallback.  Fan out even when the local copy is
        # already gone, and accept a sibling's 404, so concurrent/retried
        # deletes stay idempotent.
        if req.query.get("type") != "replicate":
            if self.store.find_ec_volume(fid.volume_id) is not None:
                err = self._ec_delete_fan_out(fid)
            else:
                err = self._replicate(fid, req, "DELETE", None,
                                      ok_statuses=(404,))
            if err:
                return 500, {"error": f"replication: {err}"}
        if freed is None:
            return 404, {"error": "not found"}
        return 202, {"size": freed}

    def _ec_delete_fan_out(self, fid: types.FileId) -> str | None:
        """Tombstone the needle in every other shard holder's .ecx/.ecj
        (store_ec_delete.go:38 doDeleteNeedleFromAtLeastOneRemoteEcShards;
        each holder keeps a full index copy)."""
        from ..operation import master_json
        try:
            r = master_json(
                self.master, "GET",
                f"/dir/ec_lookup?volumeId={fid.volume_id}",
                timeout=5)
        except OSError as e:
            return str(e)
        if "error" in r:
            # master doesn't know the shard set (restart, re-registration
            # in flight) — failing loudly beats a silent lost delete
            return f"ec_lookup: {r['error']}"
        headers = self.security.write_headers(str(fid))
        for loc in {l["url"] for l in r.get("shardIdLocations", [])}:
            if loc in (self.url, self.store.public_url):
                continue
            status, data, _ = http_bytes(
                "DELETE", f"{loc}/{fid}?type=replicate", headers=headers,
                                  timeout=60)
            if status >= 300 and status != 404:
                return f"{loc} -> {status}: {data[:200]!r}"
        return None

    def _replicate(self, fid: types.FileId, req: Request, method: str,
                   body: bytes | None,
                   extra_query: dict[str, str] | None = None,
                   headers: dict[str, str] | None = None,
                   ok_statuses: tuple[int, ...] = ()) -> str | None:
        """Fan out to sibling replicas, excluding self
        (store_replicate.go:192 DistributedOperation)."""
        v = self.store.find_volume(fid.volume_id)
        if v is None or not v.super_block.replica_placement.byte():
            return None
        from ..operation import master_json
        try:
            locs = master_json(
                self.master, "GET",
                f"/dir/lookup?volumeId={fid.volume_id}",
                timeout=5).get("locations", [])
        except OSError as e:
            return str(e)
        query = {k: v for k, v in req.query.items()
                 if k not in ("type", "jwt")}
        query.update(extra_query or {})
        qs = urllib.parse.urlencode(query)
        # re-sign for the replicas: the reference forwards the request's
        # jwt (store_replicate.go); holding the key, signing fresh avoids
        # forwarding expired tokens on slow fan-outs
        auth = self.security.write_headers(str(fid))
        if auth:
            headers = {**(headers or {}), **auth}
        for loc in locs:
            if loc["url"] in (self.url, self.store.public_url):
                continue
            status, data, _ = http_bytes(
                method,
                f"{loc['url']}/{fid}?type=replicate" +
                (f"&{qs}" if qs else ""),
                body, headers=headers, timeout=60)
            if status >= 300 and status not in ok_statuses:
                return f"{loc['url']} -> {status}: {data[:200]!r}"
        return None

    # -- status -----------------------------------------------------------

    def _status(self, req: Request):
        uds = getattr(self, "uds_server", None)
        rp = getattr(self, "read_plane", None)
        wp = getattr(self, "write_plane", None)
        return 200, {"version": "seaweedfs-tpu/0.1",
                     "udsPath": uds.sock_path if uds else "",
                     "readPlanePort": rp.port if rp else 0,
                     "writePlanePort": wp.port if wp else 0,
                     **self.store.collect_heartbeat()}

    # -- volume admin -----------------------------------------------------

    def _allocate_volume(self, req: Request):
        """volume_server.proto AllocateVolume."""
        b = req.json()
        collection = b.get("collection", "")
        _check_path_fields(collection)  # lands in the .dat/.idx path
        self.store.add_volume(
            int(b["volumeId"]), collection,
            b.get("replication", ""), b.get("ttl", ""))
        self._wp_sync_volume(int(b["volumeId"]))
        self._heartbeat_once()  # instant topology notify
        return 200, {}

    def _delete_volume(self, req: Request):
        vid = int(req.json()["volumeId"])
        self._rp_drop_volume(vid)
        self.store.delete_volume(vid)
        self._heartbeat_once()
        return 200, {}

    def _mount_volume(self, req: Request):
        b = req.json()
        collection = b.get("collection", "")
        _check_path_fields(collection)
        self.store.mount_volume(int(b["volumeId"]), collection)
        self._wp_sync_volume(int(b["volumeId"]))
        return 200, {}

    def _unmount_volume(self, req: Request):
        vid = int(req.json()["volumeId"])
        self._rp_drop_volume(vid)
        self.store.unmount_volume(vid)
        return 200, {}

    def _set_readonly(self, req: Request):
        b = req.json()
        vid = int(b["volumeId"])
        self.store.set_volume_read_only(vid, bool(b.get("readOnly", True)))
        v = self.store.find_volume(vid)
        if v is not None and v.read_only:
            v.sync()  # commit buffered .dat/.idx before anyone copies them
        elif v is not None:
            self._wp_sync_volume(vid)   # un-freeze: plane-eligible again
        # instant topology notify (same rule as mount/unmount): until
        # the master sees the flag it keeps ASSIGNING this volume, and
        # every write raced into the readonly window costs the client
        # a 409 + fresh-assign retry — with a pulse-length window that
        # outlasts the retry budget under an ec.encode burst
        self._heartbeat_once()
        return 200, {}

    def _configure_volume(self, req: Request):
        """volume_server.proto VolumeConfigure: rewrite the replica
        placement byte in the superblock + cached info."""
        b = req.json()
        vid = int(b["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        try:
            v.configure_replication(str(b.get("replication", "000")))
        except ValueError as e:
            return 400, {"error": str(e)}
        self._heartbeat_once()
        return 200, {"replication": str(
            v.super_block.replica_placement)}

    def _leave(self, req: Request):
        """volume.server.leave (command_volume_server_leave.go
        VolumeServerLeave): stop heartbeating so the master forgets
        this node after its pulse timeout; volumes stay served until
        the process exits (the operator evacuates first)."""
        self._hb_stop.set()
        return 200, {"left": True}

    def _vacuum_toggle(self, req: Request):
        """volume.vacuum.enable/disable (command_volume_vacuum_*.go
        DisableVacuum/EnableVacuum): a maintenance gate the vacuum
        handler honors."""
        self._vacuum_disabled = not bool(req.json().get("enabled",
                                                        True))
        return 200, {"vacuumEnabled": not self._vacuum_disabled}

    def _vacuum(self, req: Request):
        """volume_server.proto VacuumVolume{Check,Compact,Commit}."""
        if getattr(self, "_vacuum_disabled", False):
            return 409, {"error": "vacuum disabled on this server "
                                  "(volume.vacuum.enable to resume)"}
        vid = int(req.json()["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": "volume not found"}
        garbage = v.garbage_level()
        # compaction rewrites the .dat (offsets move): drop the read
        # plane's index FIRST so no stale (offset,len) can be served
        # against the swapped file; survivors lazily re-register.
        # (Volume.compact detaches the write plane itself — the .idx
        # snapshot must be complete — so re-offer it after the swap.)
        self._rp_drop_volume(vid)
        v.vacuum()
        self._wp_sync_volume(vid)
        return 200, {"garbageRatio": garbage}

    def _merge_volume(self, req: Request):
        """volume.merge server side (shell/command_volume_merge.go):
        pull peer replicas' .dat files and rewrite the local copy as
        the AppendAtNs-ordered union (Volume.merge_from).  The volume
        must already be readonly — the shell marks every replica
        before calling."""
        b = req.json()
        vid = int(b["volumeId"])
        peers = b.get("peers", [])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": "volume not found"}
        if not v.read_only:
            return 409, {"error": f"volume {vid} must be readonly "
                                  "before merging"}
        self._rp_drop_volume(vid)   # offsets move under the merge
        import tempfile
        tmp_paths = []
        try:
            for peer in peers:
                fd, tmp = tempfile.mkstemp(
                    suffix=".dat", dir=os.path.dirname(
                        v.file_name(".dat")))
                os.close(fd)
                # track BEFORE the pull: a failed download must not
                # leak a .dat-sized temp file past the finally
                tmp_paths.append(tmp)
                status, _hdrs = http_download(
                    f"{peer}/admin/volume_file?volumeId={vid}"
                    f"&collection={v.collection}&ext=.dat", tmp,
                    headers=self.security.admin_headers(), timeout=600)
                if status != 200:
                    return 500, {"error":
                                 f"pull .dat from {peer}: {status}"}
            merged = v.merge_from(tmp_paths)
        except (OSError, ValueError, PermissionError) as e:
            return 500, {"error": f"merge: {e}"}
        finally:
            for tmp in tmp_paths:
                try:
                    os.remove(tmp)
                except FileNotFoundError:
                    pass
        self._heartbeat_once()
        return 200, {"mergedNeedles": merged,
                     "datBytes": v.dat_size()}

    def _query(self, req: Request):
        """volume_server.proto:132 Query (server/volume_grpc_query.go):
        evaluate a SQL-subset SELECT over one stored needle's JSON/CSV
        content, returning matched rows — the compute-pushdown shape
        (filtering happens where the bytes live)."""
        from ..query import QueryError, run_query
        b = req.json()
        vid = int(b["volumeId"])
        key = int(b["key"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        try:
            n = v.read_needle(key)
        except KeyError as e:
            return 404, {"error": str(e)}
        try:
            rows = run_query(b["expression"], n.data,
                             b.get("inputFormat", "json"),
                             bool(b.get("csvHeader", True)))
        except QueryError as e:
            return 400, {"error": str(e)}
        return 200, {"rows": rows, "count": len(rows)}

    def _tier_move(self, req: Request):
        """volume_server.proto VolumeTierMoveDatToRemote
        (storage/volume_tier.go + s3_backend): upload the `.dat` to an
        S3-compatible backend, record it in .vif, drop the local copy,
        and reopen the volume in remote-read mode."""
        from ..storage.backend import configure_s3_backend, get_backend
        b = req.json()
        vid = int(b["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        if v.is_remote:
            return 200, {"alreadyRemote": True}
        backend_id = b.get("backendId", "default")
        if b.get("endpoint"):
            storage = configure_s3_backend(
                backend_id, b["endpoint"], b.get("bucket", "tier"),
                b.get("accessKey", ""), b.get("secretKey", ""))
        else:
            try:
                storage = get_backend(backend_id)
            except KeyError as e:
                return 400, {"error": str(e)}
        collection = v.collection
        # freeze + flush so the uploaded object is the complete volume;
        # heartbeat IMMEDIATELY so the master drops this volume from
        # its writable list — when the tier target is this very
        # cluster (the reference's own test trick), the upload's chunk
        # assigns must not route back into the frozen volume
        was_read_only = v.read_only
        self.store.set_volume_read_only(vid, True)
        v.sync()
        self._heartbeat_once()
        # per-replica object key: each replica tiers its OWN copy
        # (replicas can diverge; sharing one key would let the last
        # upload overwrite the object other replicas' .vif describe)
        replica_tag = f"{self.http.port}"
        key = (f"{collection}_" if collection else "") + \
            f"{vid}.{replica_tag}.dat"
        dat_path = v.file_name(".dat")
        try:
            storage.ensure_bucket()
            size = storage.upload(dat_path, key)
        except Exception as e:  # noqa: BLE001 — roll back the freeze
            if not was_read_only:
                self.store.set_volume_read_only(vid, False)
                self._heartbeat_once()
            return 500, {"error": f"tier upload failed: {e}"}
        v.volume_info.files = [{
            "backendType": "s3", "backendId": backend_id, "key": key,
            "fileSize": size, "extension": ".dat"}]
        v.volume_info.read_only = True
        v.save_volume_info()
        # swap to remote mode: close, drop the local .dat, remount —
        # Volume.__init__ sees the .vif files entry and opens the
        # backend-backed reader
        self.store.unmount_volume(vid)
        os.remove(dat_path)
        self.store.mount_volume(vid, collection)
        self._heartbeat_once()
        return 200, {"key": key, "fileSize": size,
                     "backendId": backend_id}

    def _tier_fetch(self, req: Request):
        """The inverse: download the remote `.dat` back to local disk
        (volume.tier.download / VolumeTierMoveDatFromRemote)."""
        from ..storage.backend import get_backend
        b = req.json()
        vid = int(b["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        if not v.is_remote:
            return 200, {"alreadyLocal": True}
        remote = v.volume_info.files[0]
        storage = get_backend(remote.get("backendId", "default"))
        collection = v.collection
        dat_path = v.file_name(".dat")
        size = storage.download(remote["key"], dat_path)
        v.volume_info.files = []
        # the volume is local and writable again; a stale readOnly in
        # the .vif would make a Go reader treat it as frozen forever
        v.volume_info.read_only = False
        v.save_volume_info()
        self.store.unmount_volume(vid)
        self.store.mount_volume(vid, collection)
        self._wp_sync_volume(vid)   # local + writable again
        if bool(b.get("deleteRemote", True)):
            storage.delete(remote["key"])
        self._heartbeat_once()
        # report which backend held the copy: volume.tier.compact
        # re-uploads to the SAME backend, and the binding in
        # volume_info.files was just cleared above
        return 200, {"fileSize": size,
                     "backendId": remote.get("backendId", "default")}

    def _volume_index(self, req: Request):
        """Live needle inventory of one volume: [key, size] pairs after
        replaying .idx delete semantics.  The repair plane
        (volume.check.disk / volume.fsck, shell/command_volume_fsck.go
        + command_volume_check_disk.go) diffs these across replicas or
        against filer references."""
        from ..storage import idx as idxmod
        vid = int(req.query["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        v.sync()
        with open(v.file_name(".idx"), "rb") as f:
            live = idxmod.live_entries(f.read())  # noqa: SWFS013 — admin repair inventory: live_entries needs the whole .idx (16B/needle), no byte response to stream
        return 200, {"volumeId": vid,
                     "entries": sorted((k, s)
                                       for k, (_o, s) in live.items())}

    def _admin_delete_needle(self, req: Request):
        """Tombstone one needle by key (no cookie: admin plane) — the
        purge arm of volume.fsck (-reallyDeleteFromVolume)."""
        b = req.json()
        vid = int(b["volumeId"])
        key = int(b["key"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        if self.read_plane is not None:
            self.read_plane.delete_needle(vid, key)
        try:
            n = v.read_needle(key)
        except KeyError:
            self._nc_invalidate_needle(vid, key)
            return 200, {"freed": 0}
        try:
            freed = v.delete_needle(n)
        except PermissionError as e:
            return 409, {"error": str(e)}
        # after the mutation, same ordering rule as _delete_needle
        self._nc_invalidate_needle(vid, key)
        return 200, {"freed": freed}

    def _needle_raw(self, req: Request):
        """Serve one needle's full on-disk record (header..padding) —
        the replica-repair copy unit (the reference syncs raw needles
        between replicas in command_volume_check_disk.go)."""
        vid = int(req.query["volumeId"])
        key = int(req.query["key"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        try:
            n = v.read_needle(key)
        except KeyError as e:
            return 404, {"error": str(e)}
        return 200, (n.to_bytes(v.version),
                     {"Content-Type": "application/octet-stream",
                      "X-Needle-Version": str(v.version)})

    def _write_needle_raw(self, req: Request):
        """Append a raw needle record pulled from a healthy replica
        (the receiving side of replica repair)."""
        vid = int(req.query["volumeId"])
        version = int(req.query.get("version", types.CURRENT_VERSION))
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        import struct
        if len(req.body) < 16:
            return 400, {"error": "needle record shorter than header"}
        try:
            n = Needle.parse_header(req.body[:16])
            n.parse_body(req.body[16:], version)
        except (ValueError, struct.error) as e:
            # struct.error: truncated body/CRC tail is not a ValueError
            return 400, {"error": f"bad needle record: {e}"}
        size, _ = self.store.write_needle(vid, n)
        self._nc_invalidate_needle(vid, n.id)
        self._rp_register(vid, n)
        return 200, {"size": size}

    def _read_volume_file(self, req: Request):
        """volume_server.proto:69 CopyFile equivalent: stream a byte
        range of a volume/EC file (.dat/.idx/.ecx/.ecj/.vif/.ecNN)."""
        vid = int(req.query["volumeId"])
        ext = req.query["ext"]
        collection = req.query.get("collection", "")
        try:
            _check_path_fields(collection, ext)
        except ValueError as e:
            return 400, {"error": str(e)}
        offset = int(req.query.get("offset", 0))
        size = int(req.query.get("size", -1))
        if ext in (".dat", ".idx"):
            v = self.store.find_volume(vid)
            if v is not None:
                v.sync()  # serve committed bytes, not a buffered tail
        path = self._file_path(vid, collection, ext)
        if path is None:
            return 404, {"error": f"no {ext} file for volume {vid}"}
        # stream, never buffer: a 30GB .dat pull must not hold the file
        # in RAM (the reference streams CopyFile in chunks,
        # volume_server.proto:69)
        total = os.path.getsize(path)
        n = max(total - offset, 0) if size < 0 else \
            max(min(size, total - offset), 0)
        f = open(path, "rb")
        f.seek(offset)
        return 200, (FileSlice(f, n), {"Content-Length": str(n)})

    def _receive_file(self, req: Request):
        """volume_server.proto ReceiveFile: accept a shard/index file
        pushed by a worker (erasure_coding/shard_distribution.go:101
        DistributeEcShards target side)."""
        vid = int(req.query["volumeId"])
        collection = req.query.get("collection", "")
        ext = req.query["ext"]
        try:
            _check_path_fields(collection, ext)
        except ValueError as e:
            return 400, {"error": str(e)}
        base = self._base_path(vid, collection)
        if ext in (".dat", ".idx"):
            # a pushed data/index file replaces volume content under
            # any cached needles — and under the write plane's owned
            # tail, which must be given back first
            v = self.store.find_volume(vid)
            if v is not None:
                v.detach_native()
            self._nc_drop_volume(vid)
        n = 0
        # temp + rename, like the gRPC ReceiveFile twin: a push that
        # dies mid-stream (or whose relay SOURCE dies — http_relay
        # starts this upload before the download completes) must never
        # leave a truncated file at the final path for _base_path to
        # later resolve
        import uuid as _uuid
        from .. import faults
        tmp = f"{base}{ext}.recv.{_uuid.uuid4().hex}"
        try:
            with open(tmp, "wb") as f:
                for chunk in req.stream_body():
                    if faults.fire("volume.receive_file.recv",
                                   key=f"{vid}{ext}") is not None:
                        raise IOError(
                            f"receive_file {vid}{ext}: fault-injected "
                            f"mid-stream failure")
                    f.write(chunk)
                    n += len(chunk)
            os.replace(tmp, base + ext)
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        return 200, {"bytes": n}

    def _file_path(self, vid: int, collection: str, ext: str
                   ) -> str | None:
        _check_path_fields(collection, ext)
        name = (f"{collection}_" if collection else "") + f"{vid}{ext}"
        for loc in self.store.locations:
            p = os.path.join(loc.directory, name)
            if os.path.exists(p):
                return p
        return None

    def _base_path(self, vid: int, collection: str) -> str:
        """Base file path for volume vid on the disk holding it (or the
        first location for new files)."""
        _check_path_fields(collection)
        for ext in (".dat", ".ecx", ".ec00"):
            p = self._file_path(vid, collection, ext)
            if p is not None:
                return p[: -len(ext)]
        name = (f"{collection}_" if collection else "") + str(vid)
        return os.path.join(self.store.locations[0].directory, name)

    # -- EC admin (volume_grpc_erasure_coding.go) -------------------------

    def _ec_generate(self, req: Request):
        """:43 VolumeEcShardsGenerate.  Invariant: write .ecx BEFORE the
        shard files and snapshot datSize first (race rationale :89-98),
        then persist the scheme to .vif (:132).

        With a `placement` map ({shard_id: url}) in the body this
        becomes SCATTER-encode: shard slices stream straight off the GF
        pipeline to their placement targets (one chunked
        `/admin/ec/shard_write` stream per remote shard), sidecars are
        pushed, and every shard is committed + mounted at its final
        destination — remote shards never touch this node's disks and
        the later `ec.balance` re-copy round disappears entirely."""
        b = req.json()
        vid = int(b["volumeId"])
        collection = b.get("collection", "")
        ctx = ECContext(
            int(b.get("dataShards") or 10),
            int(b.get("parityShards") or 4),
            collection, vid)
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        if collection != v.collection:
            # a mismatched collection would generate shards the mount
            # step (addressing <collection>_<vid>) can never find
            return 409, {"error": f"collection mismatch: volume {vid} "
                                  f"is {v.collection!r}, "
                                  f"not {collection!r}"}
        if not v.read_only:
            return 409, {"error": "volume must be readonly before encode"}
        v.sync()
        base = v.file_name("")
        dat_size = v.dat_size()
        placement = b.get("placement")
        if placement is not None:
            return self._ec_scatter_generate(
                v, ctx, collection, base, dat_size, placement,
                replan=int(b.get("replan", 0)))
        ec_encoder.write_sorted_file_from_idx(base)      # .ecx first!
        ec_encoder.write_ec_files(base, ctx)
        ec_encoder.save_ec_volume_info(base, ctx, dat_size, v.version)
        return 200, {"shardIds": list(range(ctx.total))}

    def _ec_scatter_generate(self, v, ctx: ECContext, collection: str,
                             base: str, dat_size: int,
                             placement: dict, replan: int = 0):
        """Placement-first streaming encode (the scatter tentpole).
        Order is the no-partial-stripe invariant: (1) pipeline every
        shard's windows to its sink and VERIFY delivery (crc + byte
        count, still uncommitted temps), (2) push sidecars
        (.ecx/.vif[/.ecj]) to every remote destination, (3) commit each
        shard — the receiver's atomic rename — with mount-on-commit,
        (4) mount local shards.  A failure anywhere unwinds: uncommitted
        temps are aborted, committed/mounted shards are deleted via
        delete_shards, and the caller (shell/worker) restores the
        volume to read-write.  Nothing is ever mounted from a partial
        stripe."""
        import time as _time
        from concurrent.futures import ThreadPoolExecutor

        from ..storage.erasure_coding.shard_sink import (
            LocalShardSink, RemoteShardSink, ScatterStats)
        dests: dict[int, str] = {}
        for sid_s, url in (placement or {}).items():
            dests[int(sid_s)] = url
        if sorted(dests) != list(range(ctx.total)):
            return 400, {"error": f"placement must cover shards "
                                  f"0..{ctx.total - 1}, got "
                                  f"{sorted(dests)}"}
        self_urls = {self.http.url, self.store.public_url}
        stats = ScatterStats()
        if replan:
            # the shell re-planned a failed stripe around dead/tripped
            # destinations and is retrying on this source: make the
            # re-plan COUNT so chaos runs can assert it happened
            self.metrics.counter_add(
                "ec_scatter_replans_total", float(replan),
                help_text="scatter encodes re-planned around failed "
                          "destinations")
        # destinations observed failing this run, for the shell's
        # re-planner ({failedDests: [...]} rides the error body)
        failed_dests: set = set()
        failed_lock = threading.Lock()

        def _note_failed(url: str) -> None:
            with failed_lock:
                failed_dests.add(url)
        t_start = _time.perf_counter()
        # snapshot any pre-existing .vif: for a TIERED volume it is the
        # ONLY reference to the remote .dat, and the unwind must
        # restore it verbatim, never delete it
        vif_before: "bytes | None" = None
        try:
            with open(base + ".vif", "rb") as vf:
                vif_before = vf.read()  # noqa: SWFS013 — .vif sidecar, format-bounded to a few hundred bytes
        except OSError:
            pass
        ec_encoder.write_sorted_file_from_idx(base)      # .ecx first!
        sinks: list = []
        local_sids: list[int] = []
        try:
            for sid in range(ctx.total):
                if dests[sid] in self_urls:
                    local_sids.append(sid)
                    sinks.append(LocalShardSink(
                        base + ctx.to_ext(sid), temp=True,
                        stats=stats))
                else:
                    sinks.append(RemoteShardSink(
                        dests[sid], v.id, sid, collection=collection,
                        headers=self.security.admin_headers))
            # (1) stream the volume through the GF pipeline; on return
            # every sink is finished (delivery verified) or aborted
            ec_encoder._generate_ec_files(base, ctx, sinks=sinks,
                                          stats=stats)
            t_encoded = _time.perf_counter()
            ec_encoder.save_ec_volume_info(base, ctx, dat_size,
                                           v.version)
            # (2) sidecars to every remote destination BEFORE any
            # commit: mount needs .ecx, and a destination must never
            # hold a visible shard it cannot serve.  One thread per
            # destination — the files are small, the round-trips are
            # what would serialize.
            remote_dests = sorted({u for s, u in dests.items()
                                   if s not in local_sids})
            sidecars: list[tuple[str, bytes]] = []
            for ext in (".ecx", ".vif", ".ecj"):
                if os.path.exists(base + ext):  # .ecj: post-encode
                    with open(base + ext, "rb") as sf:
                        sidecars.append((ext, sf.read()))  # noqa: SWFS013 — encode-plane sidecars (.ecx/.vif/.ecj) pushed whole by protocol, bounded by needle count

            def push_sidecars(url: str) -> None:
                try:
                    for ext, payload in sidecars:
                        st, body, _ = http_bytes(
                            "POST",
                            f"{url}/admin/receive_file?volumeId={v.id}"
                            f"&collection={collection}&ext={ext}",
                            payload,
                            headers=self.security.admin_headers(), timeout=60)
                        if st != 200:
                            raise OSError(f"push {ext} to {url}: {st} "
                                          f"{body[:200]!r}")
                except OSError:
                    _note_failed(url)
                    raise
            with ThreadPoolExecutor(
                    max_workers=max(1, len(remote_dests))) as spool:
                list(spool.map(push_sidecars, remote_dests))
            t_sidecars = _time.perf_counter()
            # (3) + (4) commit-and-mount: ONE batched round trip per
            # destination (every shard verified before any rename on
            # the receiving side, one mount rescan + one heartbeat per
            # dest instead of 14 of each), destinations in parallel
            by_dest_sids: dict[str, list[int]] = {}
            for sid in range(ctx.total):
                if sid not in local_sids:
                    by_dest_sids.setdefault(dests[sid], []).append(sid)

            def commit_dest(item):
                url, sids = item
                try:
                    r = http_json(
                        "POST", f"{url}/admin/ec/shard_write_commit",
                        {"volumeId": v.id, "collection": collection,
                         "mount": True,
                         "commits": [{"uploadId": sinks[sid].upload_id,
                                      "shardId": sid,
                                      "crc32": sinks[sid].crc,
                                      "bytes": sinks[sid].bytes}
                                     for sid in sids]},
                        headers=self.security.admin_headers(), timeout=30)
                    if "error" in r:
                        raise OSError(
                            f"commit {sids} on {url}: {r['error']}")
                except OSError:
                    _note_failed(url)
                    raise
                for sid in sids:
                    sinks[sid].mark_committed()
            with ThreadPoolExecutor(
                    max_workers=max(1, len(by_dest_sids))) as pool:
                list(pool.map(commit_dest, by_dest_sids.items()))
            for sid in local_sids:
                sinks[sid].commit()
            if local_sids:
                self.store.mount_ec_shards(v.id, collection,
                                           local_sids)
            else:
                # no shard stays here: drop the staging .ecx so the
                # source is not left resolving a stale EC base for
                # this vid forever (delete_volume only cleans .vif;
                # the destinations own their own sidecar copies)
                try:
                    os.remove(base + ".ecx")
                except OSError:
                    pass
            self._heartbeat_once()
            t_mounted = _time.perf_counter()
        except Exception as e:  # noqa: BLE001 — unwind, then report
            for sink in sinks:
                url = getattr(sink, "url", "")
                if url and (getattr(sink, "_error", None) is not None
                            or url in str(e)):
                    # the sink's send thread failed, or the raised
                    # error names this destination (finish()'s
                    # byte/CRC mismatch carries the dest url)
                    _note_failed(url)
                try:
                    sink.close()  # aborts anything uncommitted
                except OSError:
                    pass
            self._ec_scatter_unwind(v.id, collection, ctx, dests,
                                    base, vif_before)
            # failedDests lets the caller re-plan the stripe around
            # the dead destinations instead of failing the job
            return 500, {"error": f"scatter encode: {e}",
                         "failedDests": sorted(failed_dests)}
        wall = _time.perf_counter() - t_start
        tele = stats.summary(dat_size, wall)
        tele["mode"] = "scatter"
        tele["encodeSeconds"] = round(t_encoded - t_start, 3)
        tele["sidecarSeconds"] = round(t_sidecars - t_encoded, 3)
        tele["commitSeconds"] = round(t_mounted - t_sidecars, 3)
        self._record_scatter_metrics(stats, tele)
        return 200, {"shardIds": list(range(ctx.total)),
                     "placement": {str(s): u for s, u in dests.items()},
                     "localShardIds": local_sids,
                     "telemetry": tele}

    def _ec_scatter_unwind(self, vid: int, collection: str,
                           ctx: ECContext, dests: "dict[int, str]",
                           base: str,
                           vif_before: "bytes | None") -> None:
        """Failure unwind for a scatter encode: tear down anything a
        destination may already hold (committed shards, pushed
        sidecars) plus this node's local artifacts, so the still-live
        volume is the only copy the master serves.  delete_shards is
        idempotent and cleans sidecars when the last shard goes.  The
        .vif is RESTORED to its pre-encode bytes, never just deleted —
        for a tiered volume it is the only pointer to the remote
        .dat."""
        for url in sorted(set(dests.values())):
            try:
                http_json("POST", f"{url}/admin/ec/delete_shards",
                          {"volumeId": vid, "collection": collection,
                           "shardIds": list(range(ctx.total))},
                          headers=self.security.admin_headers(), timeout=30)
            except OSError:
                pass
        try:
            os.remove(base + ".ecx")  # staging index of the aborted run
        except OSError:
            pass
        try:
            if vif_before is not None:
                with open(base + ".vif", "wb") as vf:
                    vf.write(vif_before)
            else:
                os.remove(base + ".vif")
        except OSError:
            pass

    def _record_scatter_metrics(self, stats, tele: dict) -> None:
        """stats.py + telemetry.py emission for one scatter encode:
        the write-amplification claim must be OBSERVABLE in /metrics
        (bytes scattered per destination vs bytes written locally),
        not just inferred from the bench."""
        by_dest, latencies, local_bytes = stats.snapshot()
        for dest, nbytes in by_dest.items():
            self.metrics.counter_add(
                "ec_encode_bytes_scattered_total", float(nbytes),
                help_text="shard bytes streamed to placement targets "
                          "during scatter-encode",
                dest=dest)
        self.metrics.counter_add(
            "ec_encode_local_write_bytes_total", float(local_bytes),
            help_text="shard bytes written to this node's own disks "
                      "during scatter-encode")
        for seconds in latencies:
            self.metrics.histogram_observe(
                "ec_encode_push_slice_seconds", seconds,
                help_text="per-window destination push latency")
        self.metrics.counter_add("ec_scatter_encodes_total", 1.0,
                                 help_text="scatter encodes run")
        self.metrics.gauge_set(
            "ec_encode_volume_gbps", tele["volumeGbps"],
            help_text="volume-bytes/s of the last scatter encode")
        from .. import telemetry as _telemetry
        _telemetry.note_ec_scatter_encode(tele["bytesScatteredTotal"])

    # -- scatter shard_write receivers (the ReceiveFile twin for the
    # streaming encode path: temp + crc while streaming, atomic rename
    # only at explicit commit) ------------------------------------------

    def _ec_shard_write(self, req: Request):
        """Stream one shard's bytes (chunked) into a `.scatter.<id>`
        temp file with an incremental CRC32.  The shard stays invisible
        (unmounted, temp-named) until `shard_write_commit`; a stream
        that dies mid-body leaves nothing registered and the temp is
        removed."""
        import zlib
        vid = int(req.query["volumeId"])
        sid = int(req.query["shardId"])
        collection = req.query.get("collection", "")
        upload_id = req.query.get("uploadId", "")
        try:
            _check_path_fields(collection)
        except ValueError as e:
            return 400, {"error": str(e)}
        if not upload_id.isalnum():
            return 400, {"error": "bad uploadId"}
        self._reap_stale_shard_writes()
        base = self._base_path(vid, collection)
        tmp = f"{base}{to_ext(sid)}.scatter.{upload_id}"
        crc = 0
        n = 0
        ok = False
        try:
            # page-cache writes, like every other ReceiveFile surface
            # (receive_file, ec/copy): the scatter shard's durability
            # contract matches the seed balance-move it replaces —
            # integrity is the CRC + commit handshake, not fsync
            from .. import faults
            with open(tmp, "wb") as f:
                for chunk in req.stream_body():
                    directive = faults.fire("volume.shard_write.recv",
                                            key=f"{vid}.{sid}")
                    if directive is not None:
                        # truncate/drop on the RECEIVER both mean the
                        # stream dies here: the temp is removed, the
                        # upload never registers, the sender errors
                        raise IOError(
                            f"shard_write {vid}.{sid}: fault-injected "
                            f"{directive} mid-stream")
                    f.write(chunk)
                    crc = zlib.crc32(chunk, crc)
                    n += len(chunk)
            ok = True
        finally:
            if not ok:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        with self._pending_lock:
            self._pending_shard_writes[upload_id] = {
                "path": tmp, "crc": crc, "bytes": n, "vid": vid,
                "sid": sid, "collection": collection,
                "stamp": time.monotonic()}
        return 200, {"bytes": n, "crc32": crc}

    def _reap_stale_shard_writes(self, max_age: float = 3600.0) -> None:
        """Drop staged uploads whose sender died without an abort —
        their temps must not accumulate forever."""
        now = time.monotonic()
        with self._pending_lock:
            stale = [k for k, rec in self._pending_shard_writes.items()
                     if now - rec["stamp"] > max_age]
            recs = [self._pending_shard_writes.pop(k) for k in stale]
        for rec in recs:
            try:
                os.remove(rec["path"])
            except OSError:
                pass

    def _ec_shard_write_commit(self, req: Request):
        """Verify the sender's CRC + byte count against what was
        streamed, then atomically rename the temp(s) to their final
        `.ecNN` names; `mount: true` mounts in the same step (the
        scatter source commits only after the whole stripe delivered +
        sidecars landed, so mount-on-commit can never mount a partial
        stripe).  Accepts a single upload ({uploadId, shardId, crc32,
        bytes}) or a batch (`commits: [...]`) — the scatter source
        commits all of one destination's shards in ONE round trip, all
        verified BEFORE any rename, with one mount + one heartbeat."""
        b = req.json()
        vid = int(b["volumeId"])
        collection = b.get("collection", "")
        commits = b.get("commits")
        if commits is None:
            commits = [{"uploadId": b.get("uploadId", ""),
                        "shardId": b.get("shardId", -1),
                        "crc32": b.get("crc32", -1),
                        "bytes": b.get("bytes", -1)}]
        recs: list[tuple[dict, dict]] = []
        with self._pending_lock:
            for c in commits:
                rec = self._pending_shard_writes.pop(
                    str(c.get("uploadId", "")), None)
                if rec is not None:
                    recs.append((c, rec))
        def _discard():
            for _c, rec in recs:
                try:
                    os.remove(rec["path"])
                except OSError:
                    pass
        if len(recs) != len(commits):
            _discard()
            return 404, {"error": f"unknown staged upload in "
                                  f"{[c.get('uploadId') for c in commits]}"}
        for c, rec in recs:
            sid = int(c["shardId"])
            if int(c.get("bytes", -1)) != rec["bytes"] or \
                    int(c.get("crc32", -1)) != rec["crc"] or \
                    vid != rec["vid"] or sid != rec["sid"] or \
                    collection != rec["collection"]:
                _discard()
                return 409, {"error":
                             f"shard {vid}.{sid} upload mismatch: "
                             f"staged {rec['bytes']}B crc "
                             f"{rec['crc']}, caller says "
                             f"{c.get('bytes')}B crc {c.get('crc32')}"}
        base = self._base_path(vid, collection)
        sids = []
        for c, rec in recs:
            sid = int(c["shardId"])
            os.replace(rec["path"], base + to_ext(sid))
            sids.append(sid)
        if b.get("mount"):
            self.store.mount_ec_shards(vid, collection, sids)
            self._heartbeat_once()
        return 200, {"shardIds": sids,
                     "bytes": sum(rec["bytes"] for _c, rec in recs)}

    def _ec_shard_write_abort(self, req: Request):
        b = req.json()
        upload_id = str(b.get("uploadId", ""))
        with self._pending_lock:
            rec = self._pending_shard_writes.pop(upload_id, None)
        if rec is not None:
            try:
                os.remove(rec["path"])
            except OSError:
                pass
        return 200, {}

    def _ec_mount(self, req: Request):
        """:443 VolumeEcShardsMount."""
        b = req.json()
        collection = b.get("collection", "")
        _check_path_fields(collection)
        ev = self.store.mount_ec_shards(
            int(b["volumeId"]), collection,
            [int(s) for s in b.get("shardIds", [])])
        self._heartbeat_once()
        return 200, {"shardIds": ev.shard_ids}

    def _ec_unmount(self, req: Request):
        """:464 VolumeEcShardsUnmount — honors shardIds so a balance
        move unmounts only the migrated shards.  Absent shardIds key =
        full unmount (HTTP-internal convention); present-but-empty =
        no-op (reference wire semantics)."""
        b = req.json()
        self.store.unmount_ec_shards(
            int(b["volumeId"]),
            [int(s) for s in b["shardIds"]]
            if "shardIds" in b else None)
        self._heartbeat_once()
        return 200, {}

    def _ec_copy(self, req: Request):
        """:228 VolumeEcShardsCopy: pull shard/index files from the
        source server's CopyFile endpoint."""
        b = req.json()
        vid = int(b["volumeId"])
        collection = b.get("collection", "")
        source = b["sourceDataNode"]
        base = self._base_path(vid, collection)
        exts = [to_ext(int(s)) for s in b.get("shardIds", [])]
        if exts:
            # streaming rebuild must keep this at zero for survivors;
            # balance moves are the legitimate remaining traffic
            self.metrics.counter_add(
                "ec_shard_whole_file_copies", float(len(exts)),
                help_text="whole shard files pulled via /admin/ec/copy")
        if b.get("copyEcxFile", False):
            exts.append(".ecx")
        if b.get("copyEcjFile", False) :
            exts.append(".ecj")
        if b.get("copyVifFile", False):
            exts.append(".vif")
        for ext in exts:
            status, _hdrs = http_download(
                f"{source}/admin/volume_file?volumeId={vid}"
                f"&collection={collection}&ext={ext}", base + ext,
                headers=self.security.admin_headers(), timeout=600)
            if status != 200:
                if ext == ".ecj":  # journal may legitimately not exist
                    continue
                return 500, {"error":
                             f"copy {ext} from {source}: {status}"}
        return 200, {}

    def _ec_delete_shards(self, req: Request):
        """:327 VolumeEcShardsDelete: remove local shard files; clean up
        index files when no shards remain."""
        b = req.json()
        vid = int(b["volumeId"])
        collection = b.get("collection", "")
        base = self._base_path(vid, collection)
        for s in b.get("shardIds", []):
            try:
                os.remove(base + to_ext(int(s)))
            except FileNotFoundError:
                pass
        vid_has_shards = any(
            os.path.exists(base + to_ext(s)) for s in range(32))
        if not vid_has_shards:
            for ext in (".ecx", ".ecj", ".vif"):
                try:
                    os.remove(base + ext)
                except FileNotFoundError:
                    pass
            self.store.unmount_ec_shards(vid)
        else:
            # refresh the mounted shard set
            self.store.mount_ec_shards(vid, collection, [])
        self._heartbeat_once()
        return 200, {}

    def _ec_rebuild(self, req: Request):
        """:149 VolumeEcShardsRebuild — streaming by default: survivors
        this node lacks are read in slice windows straight off their
        host servers' `/admin/ec/shard_read` (one concurrent prefetching
        stream per source) and fed through the staged GF pipeline, so
        repair never stages whole survivor files on this node's disks
        (arXiv:1908.01527 repair pipelining).  `mode: "local"` keeps the
        seed semantics (every survivor must already be local).  Remote
        survivor locations come from the request's `shardLocations`
        ({shard_id: [urls]}) or, absent that, a master ec_lookup —
        missing both, the handler degrades to the local behavior."""
        t_start = time.perf_counter()
        b = req.json()
        vid = int(b["volumeId"])
        collection = b.get("collection", "")
        base = self._base_path(vid, collection)
        extra_dirs = [loc.directory for loc in self.store.locations]
        if b.get("mode", "stream") == "local":
            generated = ec_encoder.rebuild_ec_files(
                base, additional_dirs=extra_dirs)
            return 200, {"rebuiltShardIds": generated, "mode": "local"}
        from ..storage.erasure_coding.shard_source import (
            LocalShardSource, RebuildStats, RemoteShardSource,
            rebuild_slice_bytes)
        ctx = ec_encoder.scheme_from_vif(base) or ECContext(
            int(b.get("dataShards") or 10),
            int(b.get("parityShards") or 4))
        # file discovery is the correctness anchor: survivors staged
        # by a prior VolumeEcShardsCopy exist on disk UNMOUNTED, and
        # the legacy gRPC copy-then-rebuild flow depends on seeing
        # them.  The mounted-shard registry only contributes the shard
        # size (sparing per-remote-source metadata round-trips).
        present_paths, local_missing = \
            ec_encoder.discover_shard_files(base, ctx, extra_dirs)
        ev = self.store.find_ec_volume(vid)
        size_hint = None
        if ev is not None:
            with ev.lock:
                if ev.shards:
                    size_hint = max(s.size for s in ev.shards.values())
        remote: dict[int, list[str]] = {}
        raw_locs = b.get("shardLocations")
        if raw_locs is None:
            raw_locs = self._master_shard_locations(vid)
        self_urls = {self.http.url, self.store.public_url}
        for sid_s, urls in (raw_locs or {}).items():
            sid = int(sid_s)
            urls = [u for u in urls if u not in self_urls]
            if sid not in present_paths and urls:
                remote[sid] = urls
        targets = [sid for sid in local_missing if sid not in remote]
        if not targets:
            return 200, {"rebuiltShardIds": [], "mode": "stream"}
        sources: dict[int, object] = {
            sid: LocalShardSource(p) for sid, p in present_paths.items()}
        # any d survivors reconstruct (every d x d generator submatrix
        # is invertible), so prefer the free ones: all local shards
        # first, then only (d - local) remote rows, round-robined
        # across donor nodes so no single peer's disk serializes the
        # fetch streams
        want_remote = max(ctx.data_shards - len(present_paths), 0)
        by_donor: dict[str, list[int]] = {}
        for sid in sorted(remote):
            by_donor.setdefault(remote[sid][0], []).append(sid)
        chosen: list[int] = []
        tiers = list(by_donor.values())
        i = 0
        while len(chosen) < want_remote and any(tiers):
            if tiers[i % len(tiers)]:
                chosen.append(tiers[i % len(tiers)].pop(0))
            i += 1
        for sid in chosen:
            sources[sid] = RemoteShardSource(
                remote[sid], vid, sid,
                headers=self.security.admin_headers)
        stats = RebuildStats()
        t0 = time.perf_counter()
        try:
            if size_hint is None and present_paths:
                size_hint = max(os.path.getsize(p)
                                for p in present_paths.values())
            generated = ec_encoder.rebuild_from_sources(
                base, ctx, sources, targets, stats=stats,
                slice_bytes=rebuild_slice_bytes() if chosen else None,
                shard_size=size_hint)
        except ValueError as e:
            return 500, {"error": str(e)}
        wall = time.perf_counter() - t0
        shard_size = os.path.getsize(base + ctx.to_ext(targets[0]))
        tele = stats.summary(ctx.data_shards * shard_size, wall)
        tele["mode"] = "stream"
        tele["rebuiltBytes"] = len(generated) * shard_size
        tele["setupSeconds"] = round(t0 - t_start, 3)
        self._record_rebuild_metrics(stats, tele)
        return 200, {"rebuiltShardIds": generated, "mode": "stream",
                     "telemetry": tele}

    def _master_shard_locations(self, vid: int) -> "dict[str, list[str]]":
        """Survivor locations for a rebuild that arrived without a
        `shardLocations` payload (e.g. over the gRPC bridge, whose proto
        has no such field): ask the master.  Unreachable master degrades
        to local-only rebuild semantics rather than failing repair."""
        from ..topology import fetch_ec_shard_locations, \
            shard_ids_to_urls
        try:
            return shard_ids_to_urls(
                fetch_ec_shard_locations(self.master, vid))
        except OSError:
            return {}

    def _record_rebuild_metrics(self, stats, tele: dict) -> None:
        """stats.py + telemetry.py emission for one streaming rebuild:
        bytes per source, slice latency histogram, effective GB/s."""
        by_source, latencies = stats.snapshot()
        for label, nbytes in by_source.items():
            self.metrics.counter_add(
                "ec_rebuild_bytes_fetched_total", float(nbytes),
                help_text="survivor bytes streamed into EC rebuild",
                source=label)
        for seconds in latencies:
            self.metrics.histogram_observe(
                "ec_rebuild_slice_seconds", seconds,
                help_text="per-slice survivor fetch latency")
        self.metrics.counter_add("ec_rebuilds_total", 1.0,
                                 help_text="streaming EC rebuilds run")
        self.metrics.gauge_set(
            "ec_rebuild_volume_gbps", tele["volumeGbps"],
            help_text="volume-bytes/s of the last streaming rebuild")
        from .. import telemetry as _telemetry
        _telemetry.note_ec_rebuild(tele["bytesFetchedTotal"])

    def _ec_to_volume(self, req: Request):
        """:586 VolumeEcShardsToVolume (decode EC -> normal volume)."""
        b = req.json()
        vid = int(b["volumeId"])
        collection = b.get("collection", "")
        base = self._base_path(vid, collection)
        if not ec_decoder.has_live_needles(base):
            return 400, {"error": f"volume {vid} has no live entries"}
        dat_size = ec_decoder.find_dat_file_size(base, base)
        # decode with the scheme the volume was encoded with
        scheme = ec_encoder.scheme_from_vif(base)
        n_data = scheme.data_shards if scheme else 10
        shard_files = [base + to_ext(i) for i in range(n_data)]
        ec_decoder.write_dat_file(base, dat_size, shard_files)
        ec_decoder.write_idx_file_from_ec_index(base)
        self.store.unmount_ec_shards(vid)
        self.store.mount_volume(vid, collection)
        self._wp_sync_volume(vid)
        self._heartbeat_once()
        return 200, {}

    def _ec_shard_read(self, req: Request):
        """:101 VolumeEcShardRead: raw range read of one local shard.

        Served from a PRIVATE fd over the shard file: shard files are
        immutable post-encode, so ranged reads need no shared-handle
        seek lock — concurrent rebuild slice streams off this node no
        longer serialize on ev.lock — and the FileSlice response rides
        the dispatcher's sendfile(2) zero-copy path instead of staging
        the slice through Python bytes."""
        vid = int(req.query["volumeId"])
        shard_id = int(req.query["shardId"])
        offset = int(req.query.get("offset", 0))
        size = int(req.query.get("size", 0))
        ev = self.store.find_ec_volume(vid)
        if ev is None or shard_id not in ev.shards:
            return 404, {"error": f"shard {vid}.{shard_id} not found"}
        shard = ev.shards[shard_id]
        n = max(0, min(size, shard.size - offset))
        from .. import faults
        directive = faults.fire("volume.shard_read.serve",
                                key=f"{vid}.{shard_id}")
        f = open(shard.path, "rb")
        f.seek(offset)
        if directive in ("truncate", "drop"):
            # a donor dying mid-serve: PROMISE n bytes, deliver fewer
            # (half, or none for drop), and sever the connection so
            # the reader sees EOF short of the Content-Length — the
            # exact signature RemoteShardSource's failover treats as a
            # dead donor, never as a short shard to zero-pad
            served = n // 2 if directive == "truncate" else 0
            req._handler.close_connection = True
            return 200, (FileSlice(f, served),
                         {"Content-Length": str(n)})
        return 200, (FileSlice(f, n), {"Content-Length": str(n)})

    def _scrub(self, req: Request):
        """server/volume_grpc_scrub.go ScrubVolume."""
        vid = int(req.json()["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return 404, {"error": f"volume {vid} not found"}
        count, errors = v.scrub()
        return 200, {"checked": count, "errors": errors}

    def _ec_scrub(self, req: Request):
        """server/volume_grpc_scrub.go ScrubEcVolume; modes index/local
        (shell/command_ec_scrub.go:52)."""
        b = req.json()
        vid = int(b["volumeId"])
        mode = b.get("mode", "local")
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            return 404, {"error": f"ec volume {vid} not mounted"}
        if mode == "index":
            count, errors = ev.scrub_index()
            return 200, {"checked": count, "errors": errors,
                         "brokenShards": []}
        count, broken, errors = ev.scrub_local()
        return 200, {"checked": count, "errors": errors,
                     "brokenShards": broken}

    def _ec_info(self, req: Request):
        """:688 VolumeEcShardsInfo."""
        vid = int(req.query["volumeId"])
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            return 404, {"error": f"ec volume {vid} not mounted"}
        return 200, {
            "volumeId": vid,
            "collection": ev.collection,
            "shardIds": ev.shard_ids,
            "shardSize": ev.shard_size(),
            "dataShards": ev.ctx.data_shards,
            "parityShards": ev.ctx.parity_shards,
        }
