"""Leadership / volume-location push hub (wdclient follow stream).

The reference pushes VolumeLocation + leadership updates to every
connected client over the KeepConnected stream
(weed/wdclient/masterclient.go:417-471, master_grpc_server.go
KeepConnected); clients react instead of polling.  This hub is the
master-side fan-out point: the heartbeat path publishes volume-set
deltas per node, the raft layer publishes leadership changes, and both
the gRPC KeepConnected stream and the HTTP long-poll watch endpoint
read from it.

Delivery is CURSOR-BASED over a bounded ring: every event gets a
monotonically increasing sequence number; readers ask for "events
after cursor C" and get (events, new_cursor, lagged).  A reader that
falls further behind than the ring retains sees lagged=True and must
resync from a full topology snapshot (the reference client likewise
rebuilds its vid map on stream reconnect).  Cursors make delivery
gap-free across long-poll reconnects — a fresh per-poll queue would
silently drop events published between polls.

Events are plain dicts:
    {"url", "publicUrl", "newVids", "deletedVids",
     "newEcVids", "deletedEcVids"}          — volume location delta
    {"leader": "<url>"}                     — leadership change
"""

from __future__ import annotations

import collections
import threading


class LocationHub:
    def __init__(self, capacity: int = 4096):
        self._cond = threading.Condition()
        self._log: "collections.deque[tuple[int, dict]]" = \
            collections.deque(maxlen=capacity)
        self._seq = 0

    @property
    def cursor(self) -> int:
        """The sequence number of the latest event (0 = none yet).
        Read BEFORE building a snapshot so events published while the
        snapshot streams are replayed after it, never lost."""
        with self._cond:
            return self._seq

    def publish(self, event: dict) -> None:
        with self._cond:
            self._seq += 1
            self._log.append((self._seq, event))
            self._cond.notify_all()

    def events_since(self, since: int, timeout: float = 0.0
                     ) -> "tuple[list[dict], int, bool]":
        """(events after `since`, new cursor, lagged).  Blocks up to
        `timeout` seconds for the first event.  lagged=True means the
        ring no longer retains everything after `since` — the caller
        must resync from a snapshot."""
        with self._cond:
            if timeout > 0 and self._seq <= since:
                self._cond.wait_for(lambda: self._seq > since, timeout)
            oldest = self._log[0][0] if self._log else self._seq + 1
            lagged = since + 1 < oldest and self._seq > since
            events = [e for s, e in self._log if s > since]
            return events, self._seq, lagged
