"""Master server: topology registry, file-id assignment, lookups, admin
lock (weed/server/master_server.go, master_grpc_server_assign.go:49,
master_grpc_server_volume.go; proto contract pb/master.proto:12-58).

gRPC methods are mirrored as JSON-over-HTTP endpoints carrying the same
message fields (see server/__init__.py for the transport rationale):

    POST /heartbeat        <- master.proto:12 SendHeartbeat
    GET  /dir/assign       <- master.proto:16 Assign (+ public HTTP API)
    GET  /dir/lookup       <- master.proto:15 LookupVolume
    GET  /dir/ec_lookup    <- master.proto:30 LookupEcVolume
    GET  /vol/list         <- master.proto:28 VolumeList
    POST /vol/grow         <- VolumeGrow
    POST /cluster/lease_admin_token    <- master.proto:44 LeaseAdminToken
    POST /cluster/release_admin_token  <- master.proto:46 ReleaseAdminToken
"""

from __future__ import annotations

import threading
import time
import uuid

from ..util import wlog
from .. import security
from ..sequence import MemorySequencer, SnowflakeSequencer
from ..storage.types import FileId, format_needle_id_cookie
from ..topology import Topology
from ..security import check_path_fields as _check_path_fields
from .httpd import HttpServer, Request, http_json, is_admin_path
from .raft import RaftNode


class _AllocateRefused(Exception):
    """A reachable volume server answered an allocation with an error."""


class MasterServer:
    # file-id block leased through the raft log per checkpoint: ids up
    # to the committed "maxFileKey" bound may be issued without
    # another log round; a restart/failover floors at the bound
    SEQ_CHUNK = 1 << 16

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 volume_size_limit_mb: int = 1024,
                 default_replication: str = "000",
                 sequencer: str = "memory", pulse_seconds: float = 1.0,
                 security_config: "security.SecurityConfig | None" = None,
                 peers: "list[str] | str | None" = None,
                 raft_pulse_seconds: float = 0.25,
                 meta_dir: "str | None" = None):
        self._security_override = security_config
        self.meta_dir = meta_dir
        self.topology = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds)
        self.sequencer = (SnowflakeSequencer()
                          if sequencer == "snowflake"
                          else MemorySequencer())
        self.default_replication = default_replication
        self._grow_lock = threading.Lock()
        self._admin_token: str | None = None
        self._admin_token_ts = 0.0
        self._admin_lock_name = ""
        self.http = HttpServer(host, port)
        r = self.http.route
        r("POST", "/heartbeat", self._heartbeat)
        r("GET", "/dir/assign", self._assign)
        r("POST", "/dir/assign", self._assign)
        r("GET", "/dir/lookup", self._lookup)
        r("GET", "/dir/ec_lookup", self._ec_lookup)
        r("GET", "/dir/status", self._dir_status)
        r("GET", "/vol/list", self._vol_list)
        r("POST", "/vol/grow", self._vol_grow)
        r("GET", "/cluster/status", self._cluster_status)
        r("POST", "/cluster/raft/config", self._raft_config)
        r("POST", "/cluster/raft/transfer", self._raft_transfer)
        r("POST", "/cluster/lease_admin_token", self._lease_admin)
        r("POST", "/cluster/release_admin_token", self._release_admin)
        r("GET", "/metrics", self._metrics)
        from .debug import install_debug_routes
        install_debug_routes(self.http)  # util/grace/pprof.go analog
        self.http.guard = self._guard
        if isinstance(peers, str):
            peers = [s.strip() for s in peers.split(",") if s.strip()]
        import os as _os
        self.raft = RaftNode(
            self.http, self.http.url, peers,
            pulse_seconds=raft_pulse_seconds,
            on_leadership=self._on_leadership,
            auth_headers=lambda: self.security.admin_headers(),
            data_dir=_os.path.join(meta_dir, "raft")
            if meta_dir else None,
            on_apply=self._on_raft_apply)
        self._seq_ckpt_lock = threading.Lock()
        self._seq_ckpt_inflight = False
        self._raft_config_lock = threading.Lock()
        # restart recovery: the replicated sequence bound floors the
        # counter BEFORE any assign can run (a full master-set restart
        # must never reuse a fid, VERDICT r4 weak #6)
        bound = int(self.raft.fsm_get("maxFileKey", 0) or 0)
        if bound:
            self.sequencer.set_max(bound)
        from ..stats import Metrics
        self.metrics = Metrics("master")
        self.http.role = "master"        # tracing + request_seconds
        self.http.metrics = self.metrics
        from .location_hub import LocationHub
        self.hub = LocationHub()
        r("GET", "/cluster/watch", self._watch)
        self.grpc_server = None
        self.grpc_port = 0

    # -- lifecycle --------------------------------------------------------

    def start(self):
        self.http.start()
        self.raft.start()
        # gRPC wire plane (pb/grpc_client_server.go analog): optional —
        # JSON-HTTP stays the always-on surface
        try:
            from ..pb.master_service import start_master_grpc
            self.grpc_server, self.grpc_port = start_master_grpc(
                self, self.http.host)
        except ImportError:  # grpcio absent: HTTP-only mode
            pass
        except Exception as e:  # pragma: no cover — a real defect
            wlog.error(f"master {self.url}: gRPC plane failed to start: "
                  f"{e!r}")
        return self

    def stop(self):
        if self.grpc_server is not None:
            self.grpc_server.stop(grace=0.5)
        self.raft.stop()
        self.http.stop()

    def _watch(self, req: Request):
        """HTTP long-poll leg of the follow stream (for clients without
        grpc).  Cursor-based: `snapshot=1` returns the full topology +
        the current cursor; subsequent calls pass `since=<cursor>` and
        long-poll up to `timeout` seconds for events after it.  Gap-free
        across polls — events published between two polls are retained
        in the hub ring and delivered on the next call; `lagged` tells
        a slow client to resync from a snapshot."""
        timeout = min(float(req.query.get("timeout", 25)), 55.0)
        if req.query.get("snapshot") == "1":
            cursor = self.hub.cursor  # BEFORE the snapshot: anything
            # published while we serialize it replays on the next poll
            return 200, {"events": [], "cursor": cursor,
                         "snapshot": self.topology.to_volume_list(),
                         "leader": self.raft.leader}
        since = int(req.query.get("since", 0))
        events, cursor, lagged = self.hub.events_since(since, timeout)
        return 200, {"events": events, "cursor": cursor,
                     "lagged": lagged, "leader": self.raft.leader}

    def _on_leadership(self, leading: bool) -> None:
        if not leading:
            return
        self.hub.publish({"leader": self.raft.leader or self.url})
        # Layered no-fid-reuse fences on failover: (1) the replicated
        # sequence bound (authoritative, survives full-cluster
        # restart); (2) a time-derived floor (µs) covering ids issued
        # above an uncommitted bound by a crashed leader; (3) heartbeat
        # maxFileKey re-seeding (_heartbeat) as in the reference.
        bound = int(self.raft.fsm_get("maxFileKey", 0) or 0)
        self.sequencer.set_max(max(bound, int(time.time() * 1e6)))
        # durable state proposals must not run on the raft loop thread
        # (propose blocks on commit; the loop drives replication)
        self.raft._pool.submit(self._leader_proposals)

    def _leader_proposals(self) -> None:
        """Replicate leadership-scoped durable state through the log:
        the topology identity (master_server.go:256
        syncRaftForTopologyId) and a fresh sequence bound."""
        try:
            # barrier entry FIRST: a raft leader can only commit
            # entries of its own term directly (§5.4.2), so this no-op
            # commits (and applies) everything inherited from prior
            # terms — the FSM is then authoritative for the identity
            # decision below.  Without it a restarted leader would
            # mint a fresh topology id while the real one sits
            # uncommitted in its own log.
            self.raft.propose("noop", self.raft.term)
            existing = self.raft.fsm_get("topologyId")
            if existing:
                self.raft.topology_id = str(existing)
            else:
                self.raft.propose("topologyId", self.raft.topology_id)
            self._checkpoint_sequence(sync=True)
        except Exception as e:  # noqa: BLE001 — retried on next
            wlog.warning(        # leadership change
                "leader bootstrap incomplete: %s", e,
                component="master")

    def _on_raft_apply(self, key: str, value) -> None:
        """Committed FSM entries: every node (leader + followers)
        floors its sequencer so ANY successor starts above the bound."""
        if key == "maxFileKey":
            try:
                self.sequencer.set_max(int(value))
            except (TypeError, ValueError):
                pass

    def _checkpoint_sequence(self, sync: bool = False) -> None:
        """Propose the next sequence bound when the counter approaches
        the committed one.  `sync` blocks for commit (leadership
        handoff); the assign path tops up asynchronously at
        half-chunk so the hot path never waits on a log round."""
        cur = self.sequencer.peek() if hasattr(self.sequencer, "peek") \
            else 0
        bound = int(self.raft.fsm_get("maxFileKey", 0) or 0)
        if cur + self.SEQ_CHUNK // 2 <= bound:
            return
        target = cur + self.SEQ_CHUNK
        if sync:
            self.raft.propose("maxFileKey", target)
            return
        with self._seq_ckpt_lock:
            if self._seq_ckpt_inflight:
                return
            self._seq_ckpt_inflight = True

        def run():
            try:
                self.raft.propose("maxFileKey", target)
            finally:
                with self._seq_ckpt_lock:
                    self._seq_ckpt_inflight = False

        self.raft._pool.submit(run)

    @property
    def url(self) -> str:
        return self.http.url

    # -- auth (security/guard.go) -----------------------------------------

    @property
    def security(self) -> "security.SecurityConfig":
        return self._security_override or security.current()

    # every master endpoint that reads or mutates topology/sequence state
    # must run on the leader — followers hold no topology (volume servers
    # heartbeat only the leader, as in the reference)
    _LEADER_ONLY = frozenset((
        "/heartbeat", "/dir/assign", "/dir/lookup", "/dir/ec_lookup",
        "/dir/status", "/vol/list", "/vol/grow", "/cluster/status",
        "/cluster/watch", "/cluster/raft/config", "/cluster/raft/transfer",
        "/cluster/lease_admin_token", "/cluster/release_admin_token"))

    def _guard(self, req: Request):
        """Gate the grow/lock/heartbeat plane; assign and lookups stay
        public like the reference's HTTP API (writes are instead gated
        at the volume server by the per-fid jwt from assign).  Followers
        answer leader-only paths with a re-dial hint, the HTTP analog of
        the reference's raft leader redirect (masterclient.go re-dials on
        the leader announced over KeepConnected)."""
        if req.path in self._LEADER_ONLY and not self.raft.lease_valid():
            # lease_valid, not is_leader: a leader partitioned from the
            # quorum must refuse the moment its lease lapses — before a
            # majority-side successor can be elected — or a ~1s dual-
            # leader window serves assigns from both sides (raft lease
            # rule; weed/server/raft_hashicorp.go LeaderLeaseTimeout)
            return 503, {"error": "not leader",
                         "leader": self.raft.leader}
        if is_admin_path(req.path):
            err = self.security.check_admin(req.query, req.headers,
                                            req.remote_ip)
            if err:
                return 401, {"error": err}
        return None

    # -- handlers ---------------------------------------------------------

    def _node_vid_sets(self, url: str) -> "tuple[set, set]":
        node = self.topology.nodes.get(url)
        if node is None:
            return set(), set()
        return set(node.volumes), set(node.ec_shards)

    def _heartbeat(self, req: Request):
        hb = req.json()
        # Sequencer fencing (topology.go FindMaxFileKey + the
        # reference's raft-checkpointed sequence): every heartbeat
        # floors the file-id sequence above the largest needle key the
        # reporting server holds.  A clock-skewed new leader cannot
        # reissue an existing fid once a holder has heartbeated — and
        # assigns cannot succeed before heartbeats arrive, because the
        # post-failover topology is empty until they do.
        mfk = int(hb.get("maxFileKey", 0))
        if mfk:
            self.sequencer.set_max(mfk)
        url = f"{hb.get('ip', '')}:{hb.get('port', '')}"
        old_vids, old_ec = self._node_vid_sets(url)
        self.topology.register_heartbeat(hb)
        new_vids, new_ec = self._node_vid_sets(url)
        if (new_vids, new_ec) != (old_vids, old_ec):
            # push the delta to every follow-stream subscriber
            # (masterclient.go:417 KeepConnected VolumeLocation)
            self.hub.publish({
                "url": url,
                "publicUrl": hb.get("publicUrl", url),
                "newVids": sorted(new_vids - old_vids),
                "deletedVids": sorted(old_vids - new_vids),
                "newEcVids": sorted(new_ec - old_ec),
                "deletedEcVids": sorted(old_ec - new_ec),
            })
        self.metrics.counter_add("heartbeat_total",
                                 help_text="heartbeats received")
        # leader + topology id ride the heartbeat reply so volume servers
        # re-dial on leadership change and re-register on a new topology
        # identity (master.proto SendHeartbeat response leader hint +
        # master_server.go:256 topology-id fencing)
        return 200, {"volumeSizeLimit": self.topology.volume_size_limit,
                     "leader": self.raft.leader,
                     "topologyId": self.raft.topology_id}

    def _assign(self, req: Request):
        """master_grpc_server_assign.go:49 Assign +
        topology.go:322 PickForWrite."""
        count = int(req.query.get("count", 1))
        collection = req.query.get("collection", "")
        try:
            # the collection names .dat/.idx files on every volume
            # server this assign can grow onto — reject traversal at the
            # public front door, not only at each disk
            _check_path_fields(collection)
        except ValueError as e:
            return 400, {"error": str(e)}
        replication = req.query.get("replication",
                                    self.default_replication)
        ttl = req.query.get("ttl", "")
        ttl_u32 = _ttl_u32(ttl)
        try:
            vid, nodes = self.topology.pick_for_write(
                collection, replication, ttl_u32)
        except LookupError:
            try:
                # grow a SET of volumes, not one (volume_growth.go
                # findVolumeCount: 7/6/3 by copy count): a layout that
                # grows a single volume funnels the whole cluster's
                # writes through one disk and one server — write
                # throughput then never scales past one node no matter
                # how many are registered.  Scaled to capacity (one
                # per 16 free slots per copy): small rigs keep the
                # seed's one-volume behavior and other collections'
                # slots are never starved.  Explicit `volume.grow
                # -count=N` requests are NOT capped — only this
                # implicit assign-path round is.
                free = sum(max(0, n.free_space)
                           for n in self.topology.alive_nodes())
                per_round, copies = _growth_plan(replication)
                n_grow = max(1, min(per_round,
                                    free // (16 * copies)))
                self._grow_volume(collection, replication, ttl,
                                  count=n_grow,
                                  only_if_unwritable=True)
            except LookupError as e:
                return 500, {"error": f"cannot grow volume: {e}"}
            vid, nodes = self.topology.pick_for_write(
                collection, replication, ttl_u32)
        # the granted count is only honest when the sequencer reserves
        # a contiguous range clients may derive keys from (assign
        # count contract); a clock-derived sequencer grants 1
        if not getattr(self.sequencer, "reserves_ranges", False):
            count = 1
        key = self.sequencer.next_file_id(count)
        # raft-checkpointed sequence: top up the committed bound before
        # the counter reaches it (off the hot path)
        self._checkpoint_sequence()
        cookie = uuid.uuid4().int & 0xFFFFFFFF
        fid = str(FileId(vid, key, cookie))
        node = nodes[0]
        resp = {
            "fid": fid,
            "url": node.url,
            "publicUrl": node.public_url,
            "count": count,
            "replicas": [{"url": n.url, "publicUrl": n.public_url}
                         for n in nodes[1:]],
        }
        # per-fid write token the client presents to the volume server
        # (master_grpc_server_assign.go: GenJwtForVolumeServer in the
        # Assign response's auth field)
        auth = self.security.write_jwt(fid)
        if auth:
            resp["auth"] = auth
        return 200, resp

    def _grow_volume(self, collection: str, replication: str, ttl: str,
                     count: int = 1,
                     only_if_unwritable: bool = False) -> list[int]:
        """volume_growth.go: pick targets, allocate on each
        (AllocateVolume RPC -> /admin/allocate_volume)."""
        from ..storage.replica_placement import ReplicaPlacement
        from ..topology.topology import VolumeInfo
        with self._grow_lock:
            if only_if_unwritable:
                # double-check under the lock: N concurrent assigns
                # hitting an empty layout must grow ONE volume between
                # them, not N (which exhausts every volume slot)
                try:
                    self.topology.pick_for_write(
                        collection, replication, _ttl_u32(ttl))
                    return []
                except LookupError:
                    pass
            grown = []
            for _ in range(count):
                # an unreachable target is marked dead and planning
                # retries over the remaining nodes (the reference drops a
                # node whose heartbeat stream breaks; allocation failures
                # surface the same fact earlier)
                last_err: object = None
                excluded: set[str] = set()
                for _attempt in range(4):
                    targets = self.topology.plan_growth(
                        replication, exclude=excluded)
                    vid = self.topology.next_volume_id()
                    done = []
                    try:
                        for node in targets:
                            r = http_json(
                                "POST",
                                f"{node.url}/admin/allocate_volume", {
                                    "volumeId": vid,
                                    "collection": collection,
                                    "replication": replication,
                                    "ttl": ttl,
                                }, timeout=10)
                            if "error" in r:
                                # alive but refusing (disk full, perms):
                                # exclude from re-planning, don't kill it
                                excluded.add(node.url)
                                raise _AllocateRefused(
                                    f"{node.url}: {r['error']}")
                            done.append(node)
                            # optimistic registration; heartbeat confirms
                            node.volumes[vid] = VolumeInfo(
                                id=vid, collection=collection,
                                replica_placement=ReplicaPlacement
                                .from_string(replication or "000").byte(),
                                ttl=_ttl_u32(ttl))
                    except _AllocateRefused as e:
                        self._rollback_allocations(vid, done)
                        last_err = e
                        continue
                    except OSError as e:
                        self._rollback_allocations(vid, done)
                        self.topology.mark_dead(node.url)
                        last_err = e
                        continue
                    grown.append(vid)
                    break
                else:
                    if grown:
                        # partial growth (free slots ran out mid-set):
                        # what grew is writable — better than failing
                        # the assign that triggered the round
                        break
                    raise LookupError(f"volume growth failed: {last_err}")
            return grown

    def _rollback_allocations(self, vid: int, done: list) -> None:
        """Undo partial growth: the .dat/.idx already created on the
        succeeded nodes would otherwise be re-registered by their next
        heartbeat and leak a volume slot forever."""
        for n in done:
            n.volumes.pop(vid, None)
            for _attempt in range(2):
                try:
                    r = http_json("POST",
                                  f"{n.url}/admin/delete_volume",
                                  {"volumeId": vid}, timeout=10,
                                  headers=self.security.admin_headers())
                except OSError:
                    break  # node vanished mid-growth; heartbeat re-adds,
                    # and the orphan is volume.fsck territory, not a crash
                if "error" not in r:
                    break

    def _lookup(self, req: Request):
        vid_str = req.query.get("volumeId", "")
        if "," in vid_str:  # allow full fid
            vid_str = vid_str.split(",", 1)[0]
        from .. import faults
        # armed `master.lookup` faults simulate a master that is alive
        # but failing lookups (partition between master and its
        # topology view) — the chaos suite's lookup-degradation lever
        faults.fire("master.lookup", key=vid_str)
        vid = int(vid_str)
        locations = self.topology.lookup(vid)
        if not locations:
            return 404, {"volumeId": vid_str, "error": "volume not found"}
        return 200, {"volumeId": vid_str, "locations": locations}

    def _ec_lookup(self, req: Request):
        """master.proto:30 LookupEcVolume."""
        vid = int(req.query.get("volumeId", "0"))
        shards = self.topology.lookup_ec_shards(vid)
        if not shards:
            return 404, {"error": f"ec volume {vid} not found"}
        return 200, {
            "volumeId": vid,
            "shardIdLocations": [
                {"url": url, "shardIds": sids}
                for url, sids in shards.items()],
        }

    def _dir_status(self, req: Request):
        return 200, self.topology.to_volume_list()

    def _vol_list(self, req: Request):
        """master.proto:28 VolumeList."""
        return 200, self.topology.to_volume_list()

    def _vol_grow(self, req: Request):
        body = req.json()
        vids = self._grow_volume(
            body.get("collection", ""),
            body.get("replication", self.default_replication),
            body.get("ttl", ""), count=int(body.get("count", 1)))
        return 200, {"volumeIds": vids}

    def _cluster_status(self, req: Request):
        nodes = self.topology.alive_nodes()
        return 200, {
            "isLeader": self.raft.is_leader,
            "leader": self.raft.leader,
            "peers": self.raft.peers,
            "term": self.raft.term,
            "topologyId": self.raft.topology_id,
            "dataNodes": [n.url for n in nodes],
            "volumeSizeLimit": self.topology.volume_size_limit,
            # raft log view (shell cluster.raft.status; the reference's
            # RaftListClusterServers surface)
            "raft": {
                "commitIndex": self.raft.commit_index,
                "appliedIndex": self.raft.applied_index,
                "lastLogIndex": self.raft.log.last_index(),
                "snapshotIndex": self.raft.log.snap_index,
                "maxFileKeyBound":
                    int(self.raft.fsm_get("maxFileKey", 0) or 0),
                "persistent": bool(self.raft.data_dir),
            },
        }

    def _raft_transfer(self, req: Request):
        """cluster.raft.leader.transfer (raft LeadershipTransfer): the
        leader steps down; a peer with an up-to-date log wins the next
        election (its append stream is current, so it satisfies the
        §5.4.1 vote restriction)."""
        if not self.raft.is_leader:
            return 400, {"error": "not the leader",
                         "leader": self.raft.leader}
        if len(self.raft.peers) == 1:
            return 400, {"error": "single-master cluster: nothing to "
                                  "transfer to"}
        target = ""
        try:
            target = req.json().get("target", "")
        except (ValueError, AttributeError):
            pass
        if target and target not in self.raft.peers:
            # a typo'd target must FAIL, not silently hand leadership
            # to some other node (possibly the one being drained)
            return 400, {"error": f"target {target} is not a raft "
                                  f"member",
                         "members": self.raft.peers}
        if not self.raft.transfer_leadership(target):
            return 400, {"error": "leadership changed mid-request",
                         "leader": self.raft.leader}
        return 200, {"transferred": True}

    def _raft_config(self, req: Request):
        """Membership change through the log (master.proto:50-56
        RaftAddServer / RaftRemoveServer / RaftListClusterServers;
        shell cluster.raft.*).  Single-entry configuration: the
        committed peer list is adopted by every node."""
        b = req.json()
        add = [s.strip() for s in b.get("add", []) if s.strip()]
        remove = [s.strip() for s in b.get("remove", []) if s.strip()]
        if self.raft.self_url in remove:
            return 400, {"error": "remove the leader by first "
                                  "transferring leadership (stop this "
                                  "master; a peer takes over)"}
        if not (add or remove):
            return 200, {"peers": sorted(self.raft.peers)}
        # serialize read-modify-write-propose: two concurrent changes
        # must not each propose from the same base view and silently
        # drop the other's member
        with self._raft_config_lock:
            peers = set(self.raft.peers) | set(add)
            peers -= set(remove)
            if len(peers) < 1:
                return 400, {"error": "refusing empty membership"}
            ok = self.raft.propose("peers", sorted(peers),
                                   timeout=10.0)
        if not ok:
            return 503, {"error": "membership change not committed"}
        return 200, {"peers": sorted(peers)}

    # -- admin lock (master.proto:44, shell/command_lock_unlock.go) -------

    ADMIN_TOKEN_TTL = 60.0

    def _lease_admin(self, req: Request):
        body = req.json()
        now = time.time()   # wall: lockTsNs is a client-visible record
        mono = time.monotonic()
        prev = int(body.get("previousToken", 0) or 0)
        with self._grow_lock:
            # lease age on the monotonic clock (SWFS011): an NTP step
            # backwards would pin a dead lock alive past its TTL
            expired = mono - self._admin_token_ts > \
                self.ADMIN_TOKEN_TTL
            renewing = self._admin_token is not None and \
                prev == self._admin_token
            if self._admin_token is None or expired or renewing:
                self._admin_token = uuid.uuid4().int & 0x7FFFFFFF
                self._admin_token_ts = mono
                self._admin_lock_name = body.get("lockName", "")
                return 200, {"token": self._admin_token,
                             "lockTsNs": int(now * 1e9)}
            return 409, {"error": "already locked",
                         "lockHolder": self._admin_lock_name}

    def _release_admin(self, req: Request):
        with self._grow_lock:
            self._admin_token = None
            self._admin_token_ts = 0
        return 200, {}

    def _metrics(self, req: Request):
        nodes = self.topology.alive_nodes()
        self.metrics.gauge_set("data_nodes", len(nodes),
                               help_text="alive volume servers")
        self.metrics.gauge_set(
            "volumes_total",
            sum(len(n.volumes) for n in nodes))
        self.metrics.gauge_set("sequence", self.sequencer.peek()
                               if hasattr(self.sequencer, "peek") else 0)
        from ..stats import render_process
        return 200, ((self.metrics.render() +
                      render_process()).encode(),
                     "text/plain; version=0.0.4")


def _ttl_u32(ttl: str) -> int:
    from ..storage.ttl import read_ttl
    return read_ttl(ttl).to_u32() if ttl else 0


def _growth_plan(replication: str) -> "tuple[int, int]":
    """(volumes per growth round, copies per volume)
    (volume_growth.go:32 findVolumeCount): 7 for unreplicated, 6 for
    2-copy, 3 for 3-copy, 1 beyond — enough writable volumes that
    pick_for_write spreads concurrent writers across disks and nodes
    instead of funneling the cluster through one volume."""
    from ..storage.replica_placement import ReplicaPlacement
    try:
        copies = ReplicaPlacement.from_string(
            replication or "000").copy_count()
    except (ValueError, AttributeError):
        return 1, 1
    return {1: 7, 2: 6, 3: 3}.get(copies, 1), copies
