"""Raft for master HA — leader election + replicated log over the
JSON-HTTP control plane.

The reference runs hashicorp/raft (weed/server/raft_hashicorp.go) to
elect a leader and replicate durable cluster state; volume servers
re-dial the leader when their heartbeat stream tells them leadership
moved (weed/server/volume_grpc_client_to_master.go:109) and clients
follow via KeepConnected (weed/wdclient/masterclient.go:471).

Round 4 shipped election only; this round adds the log (VERDICT r4
item 5):

- **Replicated KV FSM.**  Entries are {"term", "key", "value"}; the
  applied state is a flat dict.  The master stores what the reference
  keeps in its raft log: the topology identity
  (master_server.go:256 syncRaftForTopologyId), file-id sequence
  checkpoints (sequence/memory_sequencer raft checkpointing), and the
  cluster membership view (master.proto:50-56 RaftAddServer/
  RaftRemoveServer/RaftListClusterServers).
- **Persistence.**  With `data_dir` set: `raft.state` (currentTerm +
  votedFor, fsynced before any vote/grant — the classic double-vote
  guard), `raft.log` (JSONL, fsynced on append), `raft.snap`
  (FSM snapshot + last included index/term; the log compacts past it).
  Without data_dir everything is in memory (tests, dev clusters).
- **Election safety.**  Votes carry lastLogIndex/lastLogTerm and are
  granted only to candidates whose log is at least as up-to-date.
- **Replication.**  AppendEntries piggybacks on the leader heartbeat:
  per-peer nextIndex/matchIndex, conflict backoff, commit on majority
  match of a current-term entry, snapshot install for peers that fell
  behind the compaction horizon.

Wire protocol (JSON over the master's HTTP server; admin-guarded):
  POST /cluster/raft/vote   {term, candidate, lastLogIndex,
                             lastLogTerm}            -> {granted, term}
  POST /cluster/raft/append {term, leader, prevLogIndex, prevLogTerm,
                             entries, leaderCommit [, snapshot]}
                            -> {ok, term, matchIndex | conflictIndex}
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import uuid
from concurrent.futures import (TimeoutError as FuturesTimeout,
                                ThreadPoolExecutor, as_completed)

from .httpd import HttpServer, Request, http_json

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

# log entries kept beyond the snapshot before compacting again
SNAPSHOT_THRESHOLD = 512


class RaftLog:
    """In-memory log with optional JSONL persistence + snapshotting.
    Indexing is 1-based (index 0 = "before the log"); `start` is the
    index of entries[0] (snapshot.lastIndex + 1 after compaction)."""

    def __init__(self, data_dir: "str | None" = None):
        self.dir = data_dir
        self.entries: list[dict] = []
        self.snap_index = 0
        self.snap_term = 0
        self.snap_fsm: dict = {}
        self._f = None
        self._torn_tail = False
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)
            self._load()
            if self._torn_tail:
                # rewrite to the recovered prefix BEFORE appending:
                # new fsynced entries landing after a torn line would
                # be silently dropped by the next replay
                self._rewrite()
            else:
                self._f = open(self._log_path(), "a")

    def _log_path(self) -> str:
        return os.path.join(self.dir, "raft.log")

    def _snap_path(self) -> str:
        return os.path.join(self.dir, "raft.snap")

    def _load(self) -> None:
        try:
            with open(self._snap_path()) as f:
                snap = json.load(f)
            self.snap_index = int(snap["lastIndex"])
            self.snap_term = int(snap["lastTerm"])
            self.snap_fsm = snap["fsm"]
        except (OSError, ValueError, KeyError):
            pass
        try:
            with open(self._log_path()) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        self._torn_tail = True
                        break   # torn tail write: discard the rest
                    if int(e.get("index", 0)) <= self.snap_index:
                        continue   # already inside the snapshot
                    # replay may contain truncation rewrites: honor the
                    # latest occurrence of each index
                    idx = int(e["index"])
                    pos = idx - self.snap_index - 1
                    if pos < len(self.entries):
                        del self.entries[pos:]
                    self.entries.append(e)
        except OSError:
            pass

    @property
    def start(self) -> int:
        return self.snap_index + 1

    def last_index(self) -> int:
        return self.snap_index + len(self.entries)

    def last_term(self) -> int:
        if self.entries:
            return int(self.entries[-1]["term"])
        return self.snap_term

    def term_at(self, index: int) -> "int | None":
        """Term of entry `index`; snapshot boundary included; None when
        unknown (compacted away or beyond the end)."""
        if index == 0:
            return 0
        if index == self.snap_index:
            return self.snap_term
        pos = index - self.start
        if 0 <= pos < len(self.entries):
            return int(self.entries[pos]["term"])
        return None

    def entry(self, index: int) -> "dict | None":
        pos = index - self.start
        if 0 <= pos < len(self.entries):
            return self.entries[pos]
        return None

    def slice_from(self, index: int) -> list[dict]:
        return self.entries[max(0, index - self.start):]

    def append(self, entries: list[dict]) -> None:
        self.entries.extend(entries)
        if self._f is not None:
            for e in entries:
                self._f.write(json.dumps(e) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())

    def truncate_from(self, index: int) -> None:
        """Drop entries >= index (conflict resolution)."""
        pos = index - self.start
        if pos < len(self.entries):
            del self.entries[max(pos, 0):]
            self._rewrite()

    def install_snapshot(self, last_index: int, last_term: int,
                         fsm: dict) -> None:
        self.snap_index = last_index
        self.snap_term = last_term
        self.snap_fsm = dict(fsm)
        self.entries = []
        self._persist_snapshot()
        self._rewrite()

    def compact(self, applied_index: int, fsm: dict) -> None:
        """Fold entries <= applied_index into the snapshot."""
        if applied_index <= self.snap_index:
            return
        term = self.term_at(applied_index)
        if term is None:
            return
        keep = self.slice_from(applied_index + 1)
        self.snap_index = applied_index
        self.snap_term = term
        self.snap_fsm = dict(fsm)
        self.entries = list(keep)
        self._persist_snapshot()
        self._rewrite()

    def _persist_snapshot(self) -> None:
        if not self.dir:
            return
        tmp = self._snap_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"lastIndex": self.snap_index,
                       "lastTerm": self.snap_term,
                       "fsm": self.snap_fsm}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path())

    def _rewrite(self) -> None:
        if not self.dir:
            return
        if self._f is not None:
            self._f.close()
        tmp = self._log_path() + ".tmp"
        with open(tmp, "w") as f:
            for e in self.entries:
                f.write(json.dumps(e) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._log_path())
        self._f = open(self._log_path(), "a")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class RaftNode:
    def __init__(self, http: HttpServer, self_url: str,
                 peers: list[str] | None = None,
                 pulse_seconds: float = 0.25,
                 on_leadership: "callable | None" = None,
                 auth_headers: "callable | None" = None,
                 data_dir: "str | None" = None,
                 on_apply: "callable | None" = None):
        """`peers` includes every master in the cluster (self included,
        in any order); empty/None means a single-master cluster, which
        is immediately its own leader.  `auth_headers` supplies admin
        credentials for peer RPCs.  `data_dir` enables persistence;
        `on_apply(key, value)` fires (off-lock) for every committed
        entry."""
        self.self_url = self_url
        self.peers = sorted(set(peers or []) | {self_url})
        self.pulse = pulse_seconds
        self.on_leadership = on_leadership
        self.on_apply = on_apply
        self._auth_headers = auth_headers or (lambda: {})
        self.state = FOLLOWER
        self.term = 0
        self.voted_for: str | None = None
        self.leader = ""
        self.topology_id = ""
        self.data_dir = data_dir
        self.log = RaftLog(data_dir)
        # volatile replication state
        self.commit_index = self.log.snap_index
        self.applied_index = self.log.snap_index
        self.fsm: dict = dict(self.log.snap_fsm)
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._commit_cv = threading.Condition()
        # monotonic clocks only: the lease fence and election timers
        # must not move with NTP steps
        self._last_heard = time.monotonic()
        self._last_quorum = time.monotonic()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=max(4, len(self.peers)))
        self._thread: threading.Thread | None = None
        if data_dir:
            with self._lock:
                self._load_state()
        # replay any snapshot/log state into the FSM view
        with self._lock:
            self._apply_committed_locked()
        http.route("POST", "/cluster/raft/vote", self._handle_vote)
        http.route("POST", "/cluster/raft/append", self._handle_append)
        http.route("POST", "/cluster/raft/timeout_now",
                   self._handle_timeout_now)

    # -- persistence of (term, votedFor) --------------------------------

    def _state_path(self) -> str:
        return os.path.join(self.data_dir, "raft.state")

    def _load_state(self) -> None:
        """Caller holds the lock."""
        try:
            with open(self._state_path()) as f:
                st = json.load(f)
            self.term = int(st.get("term", 0))
            self.voted_for = st.get("votedFor") or None
        except (OSError, ValueError):
            pass

    def _persist_state(self) -> None:
        """Caller holds the lock.  Durable BEFORE any vote leaves this
        node — voting twice in one term after a restart elects two
        leaders."""
        if not self.data_dir:
            return
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "votedFor": self.voted_for},
                      f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._state_path())

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "RaftNode":
        if len(self.peers) == 1:
            with self._lock:
                self.state = CANDIDATE
            self._try_become_leader(self.term)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)
        self.log.close()

    # Leader lease in pulses.  MUST be strictly below the minimum
    # election timeout (4 * pulse): a partitioned minority leader stops
    # serving BEFORE any majority-side peer can even begin electing a
    # successor (hashicorp/raft LeaderLeaseTimeout < ElectionTimeout).
    LEASE_PULSES = 3

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def lease_valid(self) -> bool:
        """True iff this node may ACT as leader right now.  Serving
        paths must consult this rather than `is_leader`."""
        if self.state != LEADER:
            return False
        if len(self.peers) == 1:
            return True
        return time.monotonic() - self._last_quorum <= \
            self.LEASE_PULSES * self.pulse

    def majority(self) -> int:
        return len(self.peers) // 2 + 1

    # -- FSM --------------------------------------------------------------

    def fsm_get(self, key: str, default=None):
        with self._lock:
            return self.fsm.get(key, default)

    def propose(self, key: str, value, timeout: float = 5.0) -> bool:
        """Leader-only: append {key: value} to the log, replicate, and
        wait for commit.  False on not-leader / lost leadership /
        timeout (the entry may still commit later)."""
        with self._lock:
            if self.state != LEADER:
                return False
            index = self.log.last_index() + 1
            term = self.term
            self.log.append([{"index": index, "term": term,
                              "key": key, "value": value}])
            self._match_index[self.self_url] = index
            if len(self.peers) == 1:
                self._advance_commit_locked()
        if len(self.peers) > 1:
            self._heartbeat_peers()     # immediate replication round
        deadline = time.monotonic() + timeout
        with self._commit_cv:
            while self.commit_index < index:
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    return False
                self._commit_cv.wait(min(left, 0.25))
        # OUR entry committed only if the entry at `index` still
        # carries our term — a successor may have overwritten it with
        # its own entry at the same index (then commit_index >= index
        # does NOT mean our proposal survived).  An index folded into
        # the snapshot was committed as-is (only committed entries
        # compact).
        with self._lock:
            if index <= self.log.snap_index:
                return True
            return self.log.term_at(index) == term

    def _advance_commit_locked(self) -> None:
        """Leader: commit the highest current-term index replicated on
        a majority (Raft §5.4.2: never count replicas of older terms).
        Caller holds the lock."""
        matches = sorted(
            [self._match_index.get(p, 0) if p != self.self_url
             else self.log.last_index() for p in self.peers],
            reverse=True)
        candidate = matches[self.majority() - 1]
        while candidate > self.commit_index:
            if self.log.term_at(candidate) == self.term:
                self.commit_index = candidate
                break
            candidate -= 1
        self._apply_committed_locked()

    def _apply_committed_locked(self) -> None:
        """Apply entries (snap_index..commit_index] to the FSM dict;
        caller holds the lock.  Callbacks fire off-lock."""
        fired = []
        self.applied_index = max(self.applied_index,
                                 self.log.snap_index)
        self.commit_index = max(self.commit_index, self.log.snap_index)
        while self.applied_index < self.commit_index:
            e = self.log.entry(self.applied_index + 1)
            if e is None:
                break
            self.applied_index += 1
            key, value = e.get("key"), e.get("value")
            if key is None:
                continue
            self.fsm[key] = value
            if key == "topologyId":
                self.topology_id = str(value)
            elif key == "peers" and isinstance(value, list) and value:
                # membership change (single-entry configuration, the
                # shape RaftAddServer/RaftRemoveServer drive): every
                # node adopts the committed view; a node absent from
                # it keeps running but can no longer win elections
                # against the new majority
                self.peers = sorted(set(value))
            fired.append((key, value))
        if len(self.log.entries) > SNAPSHOT_THRESHOLD:
            self.log.compact(self.applied_index, self.fsm)
        if fired:
            with self._commit_cv:
                self._commit_cv.notify_all()
            if self.on_apply is not None:
                cb = self.on_apply
                fired_copy = list(fired)
                self._pool.submit(lambda: [cb(k, v)
                                           for k, v in fired_copy])
        else:
            with self._commit_cv:
                self._commit_cv.notify_all()

    # -- RPC handlers -----------------------------------------------------

    def _handle_vote(self, req: Request):
        b = req.json()
        term, candidate = int(b["term"]), b["candidate"]
        cand_last_idx = int(b.get("lastLogIndex", 0))
        cand_last_term = int(b.get("lastLogTerm", 0))
        with self._lock:
            if term > self.term:
                self._step_down(term)
            # §5.4.1 election restriction: only grant to candidates
            # whose log is at least as up-to-date as ours
            up_to_date = (cand_last_term, cand_last_idx) >= \
                (self.log.last_term(), self.log.last_index())
            granted = (term == self.term and up_to_date and
                       self.voted_for in (None, candidate))
            if granted:
                self.voted_for = candidate
                self._persist_state()
                self._last_heard = time.monotonic()
            return 200, {"granted": granted, "term": self.term}

    def _handle_append(self, req: Request):
        b = req.json()
        term = int(b["term"])
        with self._lock:
            if term < self.term:
                return 200, {"ok": False, "term": self.term}
            if term > self.term or self.state != FOLLOWER:
                self._step_down(term)
            self.leader = b.get("leader", "")
            self._last_heard = time.monotonic()

            snap = b.get("snapshot")
            if snap:
                s_idx = int(snap["lastIndex"])
                s_term = int(snap["lastTerm"])
                # accept unless our log already CONTAINS the
                # snapshot's last entry (same index+term): a follower
                # with a LONGER conflicting uncommitted tail must
                # discard it and install, or it re-rejects the same
                # snapshot forever and never converges
                if s_idx > self.log.snap_index and \
                        self.log.term_at(s_idx) != s_term:
                    self.log.install_snapshot(s_idx, s_term,
                                              snap["fsm"])
                    self.fsm = dict(snap["fsm"])
                    self.commit_index = self.log.snap_index
                    self.applied_index = self.log.snap_index
                    self.topology_id = str(
                        self.fsm.get("topologyId", self.topology_id))

            prev_idx = int(b.get("prevLogIndex", 0))
            prev_term = int(b.get("prevLogTerm", 0))
            have = self.log.term_at(prev_idx)
            if prev_idx > 0 and have is None:
                # gap: ask the leader to back up to our end
                return 200, {"ok": False, "term": self.term,
                             "conflictIndex":
                                 self.log.last_index() + 1}
            if prev_idx > self.log.snap_index and have != prev_term:
                # conflicting history: back up past the bad entry
                return 200, {"ok": False, "term": self.term,
                             "conflictIndex": max(prev_idx,
                                                  self.log.start)}
            match = prev_idx
            for e in b.get("entries", []):
                idx = int(e["index"])
                if idx <= self.log.snap_index:
                    match = max(match, idx)
                    continue
                mine = self.log.term_at(idx)
                if mine is None:
                    self.log.append([e])
                elif mine != int(e["term"]):
                    self.log.truncate_from(idx)
                    self.log.append([e])
                match = idx
            leader_commit = int(b.get("leaderCommit", 0))
            if leader_commit > self.commit_index:
                self.commit_index = min(leader_commit,
                                        self.log.last_index())
                self._apply_committed_locked()
            # legacy field: the topology id rides the FSM now, but a
            # fresh follower may not have the entry yet
            if b.get("topologyId"):
                self.topology_id = b["topologyId"]
            return 200, {"ok": True, "term": self.term,
                         "matchIndex": match}

    # -- state machine ----------------------------------------------------

    def _step_down(self, term: int) -> None:
        """Caller holds the lock."""
        was_leader = self.state == LEADER
        if term != self.term:
            self.term = term
            self.voted_for = None
            self._persist_state()
        self.state = FOLLOWER
        if was_leader and self.on_leadership:
            self._pool.submit(self.on_leadership, False)

    def _try_become_leader(self, term: int) -> bool:
        """Promote ONLY if still the candidate of `term` — a higher-term
        append racing the vote count must win (classic Raft TOCTOU)."""
        with self._lock:
            if self.state != CANDIDATE or self.term != term:
                return False
            self.state = LEADER
            self.leader = self.self_url
            last = self.log.last_index()
            for p in self.peers:
                self._next_index[p] = last + 1
                self._match_index[p] = 0
            self._last_quorum = time.monotonic()
            # topology identity: keep the replicated one across
            # restarts/failovers (master_server.go:256
            # syncRaftForTopologyId); mint one only for a brand-new
            # cluster.  The mint is proposed through the log by the
            # leadership callback.
            if not self.topology_id:
                self.topology_id = str(
                    self.fsm.get("topologyId", "")) or \
                    f"{self.term}-{uuid.uuid4().hex[:8]}"
        if self.on_leadership:
            self.on_leadership(True)
        return True

    def transfer_leadership(self, target: str = "") -> bool:
        """Leadership transfer, TimeoutNow form (raft §3.10 /
        hashicorp LeadershipTransfer): heartbeat once so the
        transferee's log is current, tell it to start an election
        IMMEDIATELY (`timeout_now`), then step down with our own
        timer reset.  The explicit nudge makes the handover take one
        round trip instead of a full election timeout — and the
        chosen peer (most-caught-up by match index unless the
        operator named one) deterministically wins because everyone
        else's timer hasn't fired.  Falls back to plain step-down
        when no peer accepts the nudge."""
        with self._lock:
            if self.state != LEADER:
                return False
            term = self.term
            candidates = [p for p in self.peers if p != self.self_url]
            if target and target in candidates:
                candidates = [target]
            else:
                candidates.sort(
                    key=lambda p: -self._match_index.get(p, 0))
        if candidates:
            self._heartbeat_peers()     # final log currency push
        nudged = False
        for peer in candidates:
            try:
                r = http_json("POST",
                              f"{peer}/cluster/raft/timeout_now",
                              {"term": term, "leader": self.self_url},
                              3.0, self._auth_headers())
                if r.get("ok"):
                    nudged = True
                    break
            except OSError:
                continue
        if not nudged and candidates:
            from ..util import wlog
            wlog.warning("leader transfer: no peer accepted "
                         "timeout_now; falling back to step-down")
        with self._lock:
            if self.state != LEADER:
                return True             # lost it meanwhile: done
            self._step_down(self.term)
            self._last_heard = time.monotonic()
        return True

    def _handle_timeout_now(self, req):
        """TimeoutNow receiver: the leader told us to run an election
        NOW — skip the randomized timeout (we are its chosen, most
        up-to-date successor)."""
        b = req.json()
        with self._lock:
            if int(b.get("term", 0)) < self.term or \
                    self.state == LEADER:
                return 200, {"ok": False, "term": self.term}
        self._run_election()
        return 200, {"ok": self.state == LEADER, "term": self.term}

    def _election_timeout(self) -> float:
        return random.uniform(4, 8) * self.pulse

    def _loop(self) -> None:
        timeout = self._election_timeout()
        while not self._stop.wait(self.pulse):
            if self.state == LEADER:
                self._heartbeat_peers()
            elif time.monotonic() - self._last_heard > timeout:
                timeout = self._election_timeout()
                self._run_election()

    def _run_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.self_url
            self._persist_state()
            term = self.term
            last_idx = self.log.last_index()
            last_term = self.log.last_term()
            # reset the backoff clock: a split vote must wait out a
            # FRESH randomized timeout before retrying
            self._last_heard = time.monotonic()
        votes = 1
        futs = [self._pool.submit(
            http_json, "POST", f"{p}/cluster/raft/vote",
            {"term": term, "candidate": self.self_url,
             "lastLogIndex": last_idx, "lastLogTerm": last_term},
            self._rpc_timeout(), self._auth_headers())
            for p in self.peers if p != self.self_url]
        try:
            for f in as_completed(futs, timeout=self._rpc_timeout() + 1):
                try:
                    r = f.result()
                except (OSError, ValueError):
                    continue          # peer down / bad reply: no vote
                if int(r.get("term", 0)) > term:
                    with self._lock:
                        self._step_down(int(r["term"]))
                    return
                if r.get("granted"):
                    votes += 1
        except (TimeoutError, FuturesTimeout):
            pass
        if votes >= self.majority() and self._try_become_leader(term):
            self._heartbeat_peers()

    def _rpc_timeout(self) -> float:
        """Peer RPC timeout.  Must stay well under the lease."""
        return max(0.5, 2 * self.pulse)

    def _peer_payload(self, peer: str, term: int) -> dict:
        """Caller holds the lock: AppendEntries payload tailored to the
        peer's nextIndex (entries batch, or a snapshot when the peer
        fell behind the compaction horizon)."""
        next_idx = self._next_index.get(peer, self.log.last_index() + 1)
        payload = {"term": term, "leader": self.self_url,
                   "leaderCommit": self.commit_index,
                   "topologyId": self.topology_id}
        if next_idx < self.log.start:
            payload["snapshot"] = {"lastIndex": self.log.snap_index,
                                   "lastTerm": self.log.snap_term,
                                   "fsm": self.log.snap_fsm}
            next_idx = self.log.start
        prev = next_idx - 1
        payload["prevLogIndex"] = prev
        payload["prevLogTerm"] = self.log.term_at(prev) or 0
        payload["entries"] = self.log.slice_from(next_idx)[:256]
        return payload

    def _heartbeat_peers(self) -> None:
        term = self.term
        # The lease clock anchors at round DISPATCH, not completion:
        # followers restart their election timers at append RECEIPT
        # (>= dispatch), so `dispatch + lease < receipt + min election
        # timeout` closes the dual-leader window.
        round_start = time.monotonic()
        acks = 1
        got_quorum = acks >= self.majority()  # single-node cluster
        with self._lock:
            if got_quorum:
                self._last_quorum = round_start
            if self.state != LEADER:
                return
            targets = {p: self._peer_payload(p, term)
                       for p in self.peers if p != self.self_url}
        futs = {self._pool.submit(
            http_json, "POST", f"{p}/cluster/raft/append", payload,
            self._rpc_timeout(), self._auth_headers()): p
            for p, payload in targets.items()}
        try:
            # as_completed, NOT in-order result(): the quorum must
            # refresh the moment a majority acks.
            for f in as_completed(futs,
                                  timeout=self._rpc_timeout() + 1):
                peer = futs[f]
                try:
                    r = f.result()
                except (OSError, ValueError):
                    continue      # peer down / bad reply: no ack
                if int(r.get("term", 0)) > term:
                    with self._lock:
                        self._step_down(int(r["term"]))
                    return
                with self._lock:
                    if self.state != LEADER or self.term != term:
                        return
                    if r.get("ok"):
                        match = int(r.get("matchIndex", 0))
                        if match > self._match_index.get(peer, 0):
                            self._match_index[peer] = match
                        self._next_index[peer] = match + 1
                        self._advance_commit_locked()
                    elif "conflictIndex" in r:
                        self._next_index[peer] = max(
                            1, int(r["conflictIndex"]))
                if r.get("ok"):
                    acks += 1
                    if not got_quorum and acks >= self.majority():
                        got_quorum = True
                        with self._lock:
                            self._last_quorum = round_start
                        # keep draining stragglers' results this round
                        # (replication progress), but the lease is
                        # already refreshed
        except (TimeoutError, FuturesTimeout):
            pass
        if not got_quorum and time.monotonic() - self._last_quorum > \
                self.LEASE_PULSES * self.pulse:
            # leader lease expired: partitioned from the quorum — stop
            # acting as leader so a split brain can't serve assigns.
            with self._lock:
                self._step_down(self.term)
