"""Leader election for master HA — an election-only Raft over the
JSON-HTTP control plane.

The reference runs hashicorp/raft (weed/server/raft_hashicorp.go) to
elect a leader among masters and replicate topology identity; volume
servers re-dial the leader when their heartbeat stream tells them the
leadership moved (weed/server/volume_grpc_client_to_master.go:109
doHeartbeatWithRetry), and clients follow the leader via KeepConnected
(weed/wdclient/masterclient.go:471 KeepConnectedToMaster).

This build keeps Raft's election core — terms, votes, randomized
timeouts, majority quorum, leader lease — but drops log replication:
the only replicated state the reference keeps in the raft log that we
need is *who leads* plus a cluster/topology identity for fencing
(master_server.go:256 syncRaftForTopologyId).  Volume topology itself
is soft state rebuilt from the next round of heartbeats, exactly as the
reference's topology is rebuilt when a new leader takes over, and the
file-id sequence is re-seeded monotonically on every leadership change
instead of being checkpointed through the log.

Wire protocol (JSON over the master's HTTP server):
  POST /cluster/raft/vote   {term, candidate}        -> {granted, term}
  POST /cluster/raft/append {term, leader, topologyId} -> {ok, term}
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor

from .httpd import HttpServer, Request, http_json

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class RaftNode:
    def __init__(self, http: HttpServer, self_url: str,
                 peers: list[str] | None = None,
                 pulse_seconds: float = 0.25,
                 on_leadership: "callable | None" = None,
                 auth_headers: "callable | None" = None):
        """`peers` includes every master in the cluster (self included,
        in any order); empty/None means a single-master cluster, which
        is immediately its own leader.  `auth_headers` supplies admin
        credentials for peer RPCs (the inbound side is gated by the
        master's admin guard)."""
        self.self_url = self_url
        self.peers = sorted(set(peers or []) | {self_url})
        self.pulse = pulse_seconds
        self.on_leadership = on_leadership
        self._auth_headers = auth_headers or (lambda: {})
        self.state = FOLLOWER
        self.term = 0
        self.voted_for: str | None = None
        self.leader = ""
        self.topology_id = ""
        self._last_heard = time.time()
        self._last_quorum = time.time()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=max(4, len(self.peers)))
        self._thread: threading.Thread | None = None
        http.route("POST", "/cluster/raft/vote", self._handle_vote)
        http.route("POST", "/cluster/raft/append", self._handle_append)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "RaftNode":
        if len(self.peers) == 1:
            with self._lock:
                self.state = CANDIDATE
            self._try_become_leader(self.term)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def majority(self) -> int:
        return len(self.peers) // 2 + 1

    # -- RPC handlers -----------------------------------------------------

    def _handle_vote(self, req: Request):
        b = req.json()
        term, candidate = int(b["term"]), b["candidate"]
        with self._lock:
            if term > self.term:
                self._step_down(term)
            granted = (term == self.term and
                       self.voted_for in (None, candidate))
            if granted:
                self.voted_for = candidate
                self._last_heard = time.time()  # don't race the grantee
            return 200, {"granted": granted, "term": self.term}

    def _handle_append(self, req: Request):
        b = req.json()
        term = int(b["term"])
        with self._lock:
            if term < self.term:
                return 200, {"ok": False, "term": self.term}
            if term > self.term or self.state != FOLLOWER:
                self._step_down(term)
            self.leader = b.get("leader", "")
            self.topology_id = b.get("topologyId", self.topology_id)
            self._last_heard = time.time()
            return 200, {"ok": True, "term": self.term}

    # -- state machine ----------------------------------------------------

    def _step_down(self, term: int) -> None:
        """Caller holds the lock."""
        was_leader = self.state == LEADER
        self.term = term
        self.state = FOLLOWER
        self.voted_for = None
        if was_leader and self.on_leadership:
            self._pool.submit(self.on_leadership, False)

    def _try_become_leader(self, term: int) -> bool:
        """Promote ONLY if still the candidate of `term` — a higher-term
        append racing the vote count must win (classic Raft TOCTOU)."""
        with self._lock:
            if self.state != CANDIDATE or self.term != term:
                return False
            self.state = LEADER
            self.leader = self.self_url
            # fresh topology identity per leadership change: volume
            # servers seeing a new id re-register fully (the reference's
            # topology-id fencing, master_server.go:256)
            self.topology_id = f"{self.term}-{uuid.uuid4().hex[:8]}"
            self._last_quorum = time.time()
        if self.on_leadership:
            self.on_leadership(True)
        return True

    def _election_timeout(self) -> float:
        return random.uniform(4, 8) * self.pulse

    def _loop(self) -> None:
        timeout = self._election_timeout()
        while not self._stop.wait(self.pulse):
            if self.state == LEADER:
                self._heartbeat_peers()
            elif time.time() - self._last_heard > timeout:
                timeout = self._election_timeout()
                self._run_election()

    def _run_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.self_url
            term = self.term
            # reset the backoff clock: a split vote must wait out a FRESH
            # randomized timeout before retrying, or symmetric candidates
            # livelock in lockstep
            self._last_heard = time.time()
        votes = 1
        futs = [self._pool.submit(
            http_json, "POST", f"{p}/cluster/raft/vote",
            {"term": term, "candidate": self.self_url}, 2.0,
            self._auth_headers())
            for p in self.peers if p != self.self_url]
        for f in futs:
            try:
                r = f.result(timeout=3)
            except Exception:
                continue
            if int(r.get("term", 0)) > term:
                with self._lock:
                    self._step_down(int(r["term"]))
                return
            if r.get("granted"):
                votes += 1
        if votes >= self.majority() and self._try_become_leader(term):
            self._heartbeat_peers()

    def _heartbeat_peers(self) -> None:
        term = self.term
        acks = 1
        futs = [self._pool.submit(
            http_json, "POST", f"{p}/cluster/raft/append",
            {"term": term, "leader": self.self_url,
             "topologyId": self.topology_id}, 2.0,
            self._auth_headers())
            for p in self.peers if p != self.self_url]
        for f in futs:
            try:
                r = f.result(timeout=3)
            except Exception:
                continue
            if int(r.get("term", 0)) > term:
                with self._lock:
                    self._step_down(int(r["term"]))
                return
            if r.get("ok"):
                acks += 1
        now = time.time()
        if acks >= self.majority():
            self._last_quorum = now
        elif now - self._last_quorum > 10 * self.pulse:
            # leader lease expired: partitioned from the quorum — stop
            # acting as leader so a split brain can't serve assigns
            with self._lock:
                self._step_down(self.term)
