"""Leader election for master HA — an election-only Raft over the
JSON-HTTP control plane.

The reference runs hashicorp/raft (weed/server/raft_hashicorp.go) to
elect a leader among masters and replicate topology identity; volume
servers re-dial the leader when their heartbeat stream tells them the
leadership moved (weed/server/volume_grpc_client_to_master.go:109
doHeartbeatWithRetry), and clients follow the leader via KeepConnected
(weed/wdclient/masterclient.go:471 KeepConnectedToMaster).

This build keeps Raft's election core — terms, votes, randomized
timeouts, majority quorum, leader lease — but drops log replication:
the only replicated state the reference keeps in the raft log that we
need is *who leads* plus a cluster/topology identity for fencing
(master_server.go:256 syncRaftForTopologyId).  Volume topology itself
is soft state rebuilt from the next round of heartbeats, exactly as the
reference's topology is rebuilt when a new leader takes over, and the
file-id sequence is re-seeded monotonically on every leadership change
instead of being checkpointed through the log.

Wire protocol (JSON over the master's HTTP server):
  POST /cluster/raft/vote   {term, candidate}        -> {granted, term}
  POST /cluster/raft/append {term, leader, topologyId} -> {ok, term}
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor, as_completed

from .httpd import HttpServer, Request, http_json

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class RaftNode:
    def __init__(self, http: HttpServer, self_url: str,
                 peers: list[str] | None = None,
                 pulse_seconds: float = 0.25,
                 on_leadership: "callable | None" = None,
                 auth_headers: "callable | None" = None):
        """`peers` includes every master in the cluster (self included,
        in any order); empty/None means a single-master cluster, which
        is immediately its own leader.  `auth_headers` supplies admin
        credentials for peer RPCs (the inbound side is gated by the
        master's admin guard)."""
        self.self_url = self_url
        self.peers = sorted(set(peers or []) | {self_url})
        self.pulse = pulse_seconds
        self.on_leadership = on_leadership
        self._auth_headers = auth_headers or (lambda: {})
        self.state = FOLLOWER
        self.term = 0
        self.voted_for: str | None = None
        self.leader = ""
        self.topology_id = ""
        # monotonic clocks only: the lease fence and election timers
        # must not move with NTP steps (a backward wall-clock step on a
        # partitioned leader would otherwise extend its lease and serve
        # split-brain assigns)
        self._last_heard = time.monotonic()
        self._last_quorum = time.monotonic()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=max(4, len(self.peers)))
        self._thread: threading.Thread | None = None
        http.route("POST", "/cluster/raft/vote", self._handle_vote)
        http.route("POST", "/cluster/raft/append", self._handle_append)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "RaftNode":
        if len(self.peers) == 1:
            with self._lock:
                self.state = CANDIDATE
            self._try_become_leader(self.term)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)

    # Leader lease in pulses.  MUST be strictly below the minimum
    # election timeout (4 * pulse, _election_timeout): a partitioned
    # minority leader then stops serving BEFORE any majority-side peer
    # can even begin electing a successor — the standard raft lease
    # rule (hashicorp/raft LeaderLeaseTimeout < ElectionTimeout,
    # weed/server/raft_hashicorp.go).
    LEASE_PULSES = 3

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def lease_valid(self) -> bool:
        """True iff this node may ACT as leader right now.  Serving
        paths must consult this rather than `is_leader`: the background
        loop only notices a lost quorum at heartbeat-round end (which a
        partition delays by the full HTTP timeout), while the lease
        clock expires in real time."""
        if self.state != LEADER:
            return False
        if len(self.peers) == 1:
            return True
        return time.monotonic() - self._last_quorum <= \
            self.LEASE_PULSES * self.pulse

    def majority(self) -> int:
        return len(self.peers) // 2 + 1

    # -- RPC handlers -----------------------------------------------------

    def _handle_vote(self, req: Request):
        b = req.json()
        term, candidate = int(b["term"]), b["candidate"]
        with self._lock:
            if term > self.term:
                self._step_down(term)
            granted = (term == self.term and
                       self.voted_for in (None, candidate))
            if granted:
                self.voted_for = candidate
                self._last_heard = time.monotonic()  # don't race the grantee
            return 200, {"granted": granted, "term": self.term}

    def _handle_append(self, req: Request):
        b = req.json()
        term = int(b["term"])
        with self._lock:
            if term < self.term:
                return 200, {"ok": False, "term": self.term}
            if term > self.term or self.state != FOLLOWER:
                self._step_down(term)
            self.leader = b.get("leader", "")
            self.topology_id = b.get("topologyId", self.topology_id)
            self._last_heard = time.monotonic()
            return 200, {"ok": True, "term": self.term}

    # -- state machine ----------------------------------------------------

    def _step_down(self, term: int) -> None:
        """Caller holds the lock."""
        was_leader = self.state == LEADER
        self.term = term
        self.state = FOLLOWER
        self.voted_for = None
        if was_leader and self.on_leadership:
            self._pool.submit(self.on_leadership, False)

    def _try_become_leader(self, term: int) -> bool:
        """Promote ONLY if still the candidate of `term` — a higher-term
        append racing the vote count must win (classic Raft TOCTOU)."""
        with self._lock:
            if self.state != CANDIDATE or self.term != term:
                return False
            self.state = LEADER
            self.leader = self.self_url
            # fresh topology identity per leadership change: volume
            # servers seeing a new id re-register fully (the reference's
            # topology-id fencing, master_server.go:256)
            self.topology_id = f"{self.term}-{uuid.uuid4().hex[:8]}"
            self._last_quorum = time.monotonic()
        if self.on_leadership:
            self.on_leadership(True)
        return True

    def _election_timeout(self) -> float:
        return random.uniform(4, 8) * self.pulse

    def _loop(self) -> None:
        timeout = self._election_timeout()
        while not self._stop.wait(self.pulse):
            if self.state == LEADER:
                self._heartbeat_peers()
            elif time.monotonic() - self._last_heard > timeout:
                timeout = self._election_timeout()
                self._run_election()

    def _run_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.self_url
            term = self.term
            # reset the backoff clock: a split vote must wait out a FRESH
            # randomized timeout before retrying, or symmetric candidates
            # livelock in lockstep
            self._last_heard = time.monotonic()
        votes = 1
        futs = [self._pool.submit(
            http_json, "POST", f"{p}/cluster/raft/vote",
            {"term": term, "candidate": self.self_url},
            self._rpc_timeout(), self._auth_headers())
            for p in self.peers if p != self.self_url]
        try:
            for f in as_completed(futs, timeout=self._rpc_timeout() + 1):
                try:
                    r = f.result()
                except Exception:
                    continue
                if int(r.get("term", 0)) > term:
                    with self._lock:
                        self._step_down(int(r["term"]))
                    return
                if r.get("granted"):
                    votes += 1
        except TimeoutError:
            pass
        if votes >= self.majority() and self._try_become_leader(term):
            self._heartbeat_peers()

    def _rpc_timeout(self) -> float:
        """Peer RPC timeout.  Must stay well under the lease: a
        blackholed peer then can't stretch a heartbeat round past the
        lease window or pile hung futures onto the pool (rounds fire
        every pulse)."""
        return max(0.5, 2 * self.pulse)

    def _heartbeat_peers(self) -> None:
        term = self.term
        # The lease clock anchors at round DISPATCH, not completion:
        # followers restart their election timers at append RECEIPT
        # (>= dispatch), so `dispatch + lease < receipt + min election
        # timeout` is the invariant that closes the dual-leader window.
        # Anchoring at completion would let a round stretched by a slow
        # peer extend the lease past a majority-side election.
        round_start = time.monotonic()
        acks = 1
        got_quorum = acks >= self.majority()  # single-node cluster
        if got_quorum:
            self._last_quorum = round_start
        futs = [self._pool.submit(
            http_json, "POST", f"{p}/cluster/raft/append",
            {"term": term, "leader": self.self_url,
             "topologyId": self.topology_id}, self._rpc_timeout(),
            self._auth_headers())
            for p in self.peers if p != self.self_url]
        try:
            # as_completed, NOT in-order result(): the quorum must
            # refresh the moment a majority acks — a healthy cluster
            # with one blackholed peer would otherwise refresh only at
            # round end (after the full RPC timeout) and spend most of
            # each round with a lapsed lease, 503ing assigns despite
            # holding quorum.
            for f in as_completed(futs,
                                  timeout=self._rpc_timeout() + 1):
                try:
                    r = f.result()
                except Exception:
                    continue
                if int(r.get("term", 0)) > term:
                    with self._lock:
                        self._step_down(int(r["term"]))
                    return
                if r.get("ok"):
                    acks += 1
                    if not got_quorum and acks >= self.majority():
                        got_quorum = True
                        self._last_quorum = round_start
                        # Stop waiting on stragglers: a blackholed peer
                        # would stretch the round by its RPC timeout and
                        # push the NEXT dispatch past the lease window.
                        # A higher term in an unread straggler response
                        # still surfaces — that peer rejects appends
                        # without resetting its election timer, times
                        # out, and its vote request deposes us.
                        break
        except TimeoutError:
            pass
        if not got_quorum and time.monotonic() - self._last_quorum > \
                self.LEASE_PULSES * self.pulse:
            # leader lease expired: partitioned from the quorum — stop
            # acting as leader so a split brain can't serve assigns.
            # (lease_valid() already refused serving paths the moment
            # the lease lapsed; this retires the leader state itself)
            with self._lock:
                self._step_down(self.term)
