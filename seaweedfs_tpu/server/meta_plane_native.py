"""Native filer meta-plane wrapper (native/meta_plane.cc).

The filer's second implementation of the plain-file WRITE surface —
the metadata sibling of server/write_plane.py: a C++ epoll loop that
parses the PUT, uploads the single chunk straight to the volume write
plane (C++ -> C++, pipelined persistent connections), frames the
metalog WAL line byte-identically to MetaLog.append_raw, lands the
batch with one O_APPEND write per segment run, publishes the
watermark, and acks `201 {"name":..,"size":..}` — zero Python per
request.

By protocol the plane is just another SIBLING WRITER over the shared
metalog dir: it owns a wid + watermark file minted through
meta_log.alloc_writer_identity, and its lines reach the unmodified
PR 12 machinery (overlay followers, flock-elected applier,
checkpointing) exactly like a pre-fork sibling's.  On the Python side
this wrapper supplies the three things the C++ loop cannot cheaply do
itself:

* a FEEDER thread that batches master assigns and pushes derived
  "addr fid" pairs into the plane's pool (one Python round trip
  amortized over ~hundreds of native requests);
* DIRECTORY knowledge: the filer's own events (via Filer.subscribe)
  and sibling/follower events (via MetaPlane.sink) mark fresh
  directories native-eligible and mark every foreign path ineligible,
  so the plane only ever acks op="create" for provably-new paths;
* the METRICS bridge rendered on the filer's /metrics.

Failure contract: construction returns None-equivalent via
RuntimeError at the call site's try/except; at runtime every
ineligible or doomed request answers the 404 fallback and the client
retries the Python filer port.  SIGKILL at any instant leaves acked
lines durable (the ack is queued only after write(2) returned) and
unacked lines absent-or-torn — torn tails are the WAL's normal
crash debris and the follower/applier skip them.
"""

from __future__ import annotations

import ctypes
import os
import threading

from .. import native, operation
from ..filer.meta_log import alloc_writer_identity
from ..storage.types import FileId, parse_needle_id_cookie
from ..util import wlog

# ack latency histogram bucket bounds (meta_plane.cc kLatBuckets), in
# seconds — rendered on /metrics as filer_meta_plane_native_ack_seconds
ACK_BUCKETS_S = (1e-6, 2e-6, 5e-6, 1e-5, 2e-5, 5e-5, 1e-4, 2e-4,
                 5e-4, 1e-3, 2e-3, 5e-3, 1e-2, 2e-2, 5e-2, 1e-1, 1.0)

# feeder targets: refill toward HIGH once the pool drops under LOW.
# One assign(count=_ASSIGN_N) buys _ASSIGN_N native acks, so the
# steady-state Python cost is ~1/256th of a request each.
_POOL_LOW = 192
_POOL_HIGH = 512
_ASSIGN_N = 256

_STATS_KEYS = ("requests", "fallbacks", "fid_misses", "wal_errors",
               "upstream_errors", "parse_ns", "upload_ns", "wal_ns",
               "wal_batches", "wal_lines")

# flight-record label tables (meta_plane.cc kRecStageNames /
# kRecFallbackNames — the SWFS019 lint pins the literals in sync)
RECORD_STAGES = ("parse", "upload", "wal", "ack")
RECORD_FALLBACKS = ("none", "ineligible", "fid_dry", "upstream",
                    "wal", "oversize", "chunked")


def native_meta_plane_enabled() -> "bool | None":
    """SEAWEEDFS_TPU_FILER_META_PLANE_NATIVE: '0' forces off, '1'
    forces on, unset/other = auto (on when the meta plane is on and
    the toolchain builds the .so)."""
    v = os.environ.get("SEAWEEDFS_TPU_FILER_META_PLANE_NATIVE", "")
    if v == "0":
        return False
    if v == "1":
        return True
    return None


class NativeMetaPlane:
    """One native meta-plane server bound to <host>:<ephemeral>,
    appending into `meta_log_dir` as its own writer instance."""

    def __init__(self, meta_log_dir: str, master: str,
                 host: str = "127.0.0.1", collection: str = "",
                 replication: str = "",
                 feed_interval: float = 0.05):
        self._lib = native.load_meta_plane()
        if self._lib is None:
            raise RuntimeError("native meta plane unavailable")
        self.wid, self.wm_path = alloc_writer_identity(meta_log_dir)
        port = ctypes.c_int(0)
        self._h = self._lib.mp_start(
            host.encode(), 0, meta_log_dir.encode(),
            self.wid.encode(), self.wm_path.encode(),
            ctypes.byref(port))
        if self._h < 0:
            raise RuntimeError("native meta plane failed to start")
        self.host = host
        self.port = port.value
        self.master = master
        self.collection = collection
        self.replication = replication
        self._stop = threading.Event()
        self._armed = False
        self._drainer = None
        self._feeder = threading.Thread(
            target=self._feed_loop, args=(feed_interval,), daemon=True)
        self._feeder.start()

    # -- arming ---------------------------------------------------------

    def arm(self, on: bool = True) -> None:
        """The PR 11 native_on/native_off lever: disarmed, the
        listener stays up but every request answers the 404 fallback
        (clients keep their conns; Python serves)."""
        self._armed = bool(on)
        self._lib.mp_arm(self._h, 1 if on else 0)

    @property
    def armed(self) -> bool:
        return self._armed

    # -- directory knowledge (called from filer listener + plane sink) --

    def mark_dir(self, path: str) -> None:
        """`path` was created fresh (op=create, isDirectory) — its
        direct children become native-eligible."""
        try:
            self._lib.mp_mark_dir(self._h, path.encode())
        except (OSError, UnicodeEncodeError):
            pass

    def mark_path(self, path: str) -> None:
        """`path` was written through any non-native route — future
        native writes to it must fall back (overwrites are Python's)."""
        try:
            self._lib.mp_mark_path(self._h, path.encode())
        except (OSError, UnicodeEncodeError):
            pass

    def clear_dirs(self) -> None:
        """Delete/rename anywhere drops all knowledge — rare, always
        safe, mirrors Filer._known_dirs.clear()."""
        self._lib.mp_clear_dirs(self._h)

    def on_event(self, event: dict) -> None:
        """Filer listener (Filer.subscribe): this process's own
        Python-path events — {op, tsNs, newEntry, oldEntry} dicts with
        entry JSON payloads."""
        try:
            self._learn(event)
        except Exception:  # noqa: SWFS004 — advisory knowledge only;
            pass           # a miss means a fallback, never a bad ack

    def _learn(self, ev: dict) -> None:
        op = ev.get("op", "")
        new = ev.get("newEntry")
        old = ev.get("oldEntry")
        if op in ("delete", "rename") and (
                (new or {}).get("isDirectory") or
                (old or {}).get("isDirectory")):
            self.clear_dirs()
        if not new:
            return
        path = new.get("fullPath", "")
        if not path:
            return
        if new.get("isDirectory"):
            # only a FRESH create proves the directory empty — an
            # update (old != None) may shadow existing children
            if op == "create" and old is None:
                self.mark_dir(path)
        else:
            self.mark_path(path)

    def on_follower_events(self, events) -> None:
        """MetaPlane.sink: the coherence follower's raw poll batches —
        (event, raw_new, pos, wid) tuples for every sibling writer's
        WAL line, INCLUDING this plane's own (the cursor only
        skip-scans the Python MetaLog's wid).  Own lines are harmless
        here (mark_path re-inserts a name the C++ loop already holds),
        so no wid filtering is needed."""
        for item in events:
            try:
                self._learn(item[0] if isinstance(item, tuple)
                            else item)
            except Exception:  # noqa: SWFS004
                pass

    # -- fid feeder -----------------------------------------------------

    def _feed_once(self) -> None:
        level = self._lib.mp_fid_level(self._h)
        if level < 0 or level >= _POOL_LOW or not self._armed:
            return
        lines = []
        while level + len(lines) < _POOL_HIGH:
            a = operation.assign(self.master, count=_ASSIGN_N,
                                 collection=self.collection,
                                 replication=self.replication)
            if a.auth:
                # jwt-gated cluster: the volume plane would refuse the
                # bare native upload — leave the pool dry, every
                # request falls back to the authenticated Python path
                return
            addr = operation._write_plane_addr_for(a.url)
            if addr is None:
                return  # no volume plane to pipe into; stay dry
            vid_s, _, kc = a.fid.partition(",")
            key, cookie = parse_needle_id_cookie(kc)
            vid = int(vid_s)
            n = max(1, int(a.count or 1))
            lines.extend(
                f"{addr} {FileId(vid, key + i, cookie)}"
                for i in range(n))
        if lines:
            self._lib.mp_feed_fids(
                self._h, ("\n".join(lines) + "\n").encode())

    def _feed_loop(self, interval: float) -> None:
        # Exponential backoff on feed failure: an unreachable master
        # (filers booted against a fake or dead one — every in-process
        # test does this) must cost a connect attempt every couple of
        # seconds, not 20 times a second of CPU, log lines and error
        # spans for the life of the process.  Success snaps back to
        # the base interval so a recovered master refills promptly.
        wait = interval
        while not self._stop.wait(wait):
            try:
                self._feed_once()
                wait = interval
            except Exception as e:  # noqa: BLE001 — a dead master just
                # means a dry pool (= fallbacks), never a dead feeder
                wlog.debug(f"meta plane fid feed failed: {e!r}")
                wait = min(max(wait * 2, interval), 2.0)

    # -- telemetry ------------------------------------------------------

    def requests(self) -> int:
        return self._lib.mp_requests(self._h)

    def fallbacks(self) -> int:
        return self._lib.mp_fallbacks(self._h)

    def fid_level(self) -> int:
        return self._lib.mp_fid_level(self._h)

    def stats(self) -> dict:
        out = (ctypes.c_ulonglong * 16)()
        n = self._lib.mp_stats(self._h, out)
        if n <= 0:
            return {k: 0 for k in _STATS_KEYS}
        return {k: int(out[i]) for i, k in enumerate(_STATS_KEYS)}

    def ack_histogram(self) -> "tuple[list[int], int, float]":
        """(cumulative bucket counts aligned with ACK_BUCKETS_S + an
        +Inf cell, total count, sum seconds)."""
        out = (ctypes.c_ulonglong * 20)()
        cells = self._lib.mp_latency(self._h, out)
        if cells <= 0:
            return [], 0, 0.0
        buckets = [int(out[i]) for i in range(cells + 1)]
        return buckets, int(out[cells + 1]), out[cells + 2] / 1e9

    # -- flight records (ISSUE 18) --------------------------------------

    def drain_records(self, sink=None, cap: int = 512):
        """Pull the plane's flight ring (see native.drain_plane_records
        for the sink-vs-list contract).  Single-consumer: concurrent
        pulls must be serialized by the owning PlaneRecordDrainer."""
        if self._h < 0:
            return [] if sink is None else 0
        return native.drain_plane_records(self._lib, "mp", self._h,
                                          sink, cap)

    def records_dropped(self) -> int:
        return int(self._lib.mp_records_dropped(self._h)) \
            if self._h >= 0 else 0

    def set_upload_delay_ms(self, ms: int) -> None:
        """Failpoint: stall the volume upload hop of every native
        request by `ms` (the ISSUE 18 acceptance lever — a slowed
        plane-served write must surface in cluster.slow)."""
        if self._h >= 0:
            self._lib.mp_set_upload_delay_ms(self._h, int(ms))

    def start_record_drain(self, tracker=None,
                           metrics=None) -> "object":
        """Start the flight-record drainer (tick + scrape hook);
        idempotent.  Returns the profiling.PlaneRecordDrainer."""
        if self._drainer is not None:
            return self._drainer
        from .. import profiling
        sink = profiling.PlaneRecordSink(
            "filer", "meta", "POST", RECORD_STAGES, RECORD_FALLBACKS,
            tracker=tracker, metrics=metrics)
        self._drainer = profiling.PlaneRecordDrainer(
            sink, lambda s: self.drain_records(sink=s),
            self.records_dropped).start()
        return self._drainer

    def stop(self) -> None:
        """Feeder + drainer first, then the native server: mp_stop
        frees the Server object, so no wrapper thread may still be
        inside an mp_* call when it runs."""
        if self._h < 0:
            return
        self._stop.set()
        self._feeder.join(timeout=5)
        if self._drainer is not None:
            self._drainer.stop()
        self._lib.mp_stop(self._h)
        self._h = -1
