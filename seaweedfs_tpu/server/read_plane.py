"""Native HTTP read plane wrapper (native/read_plane.cc).

The volume server's second implementation of the needle-read surface —
the role the reference fills with its Rust volume server
(seaweed-volume/, VOLUME_SERVER_RUST_PLAN.md) and RDMA read sidecar
(seaweedfs-rdma-sidecar/): a C++ epoll loop answering `GET /vid,fid`
with sendfile(2) from the .dat fd, no Python on the hot path.

Registration contract: only PLAIN needles are registered (no
compression, no name/mime/pairs, no TTL, not a chunk manifest) — the
plane serves raw payload bytes with octet-stream headers, so any
needle whose HTTP semantics need Python (gzip encoding, mime,
expiry) stays unregistered and the plane 404s it; clients fall back
to the main port.  Entries are added at write time and on first
Python read (lazy warm), dropped on delete; vacuum/EC swap drops the
whole volume (it lazily re-registers against the fresh fd).

Cross-implementation parity is tested the way the reference tests its
Rust server against Go (test/volume_server/rust/): the same requests
are issued to both planes and byte-compared (tests/test_read_plane.py).
"""

from __future__ import annotations

import ctypes

from .. import native
from ..storage import types as storage_types

# byte offset of the data payload inside a needle record:
# header (cookie 4 + id 8 + size 4) + DataSize field (4)
_DATA_OFFSET_IN_RECORD = storage_types.NEEDLE_HEADER_SIZE + 4

# flight-record label tables (read_plane.cc kRecStageNames /
# kRecFallbackNames — the SWFS019 lint pins the literals in sync)
RECORD_STAGES = ("parse", "lookup", "send", "ack")
RECORD_FALLBACKS = ("none", "method", "bad_request", "not_found")


def needle_is_plain(n) -> bool:
    """True when the needle's HTTP semantics are fully captured by raw
    payload bytes + octet-stream headers."""
    return not (n.is_compressed() or n.is_chunked_manifest() or
                n.has_ttl() or n.has_name() or n.has_mime() or
                n.has_pairs())


class ReadPlane:
    """One native read-plane server bound to 127.0.0.1:<ephemeral>."""

    def __init__(self, host: str = "127.0.0.1"):
        self._lib = native.load_read_plane()
        if self._lib is None:
            raise RuntimeError("native read plane unavailable")
        port = ctypes.c_int(0)
        self._h = self._lib.rp_start(host.encode(), 0,
                                     ctypes.byref(port))
        if self._h < 0:
            raise RuntimeError("read plane failed to start")
        self.host = host
        self.port = port.value

    # -- index maintenance (called from the volume server) -------------

    def add_volume(self, vid: int, dat_path: str) -> bool:
        return self._lib.rp_add_volume(self._h, vid,
                                       dat_path.encode()) == 0

    def remove_volume(self, vid: int) -> None:
        self._lib.rp_remove_volume(self._h, vid)

    def register_needle(self, vid: int, stored_offset: int,
                        needle) -> None:
        """Register a parsed needle at its .idx stored offset; silently
        skips non-plain needles and unregistered volumes."""
        if not needle_is_plain(needle):
            return
        data_off = storage_types.to_actual_offset(stored_offset) + \
            _DATA_OFFSET_IN_RECORD
        self._lib.rp_put(self._h, vid, needle.id, needle.cookie,
                         data_off, len(needle.data))

    def register_raw(self, vid: int, needle_id: int, cookie: int,
                     data_off: int, data_len: int) -> None:
        """Register from already-known record geometry (the native
        write plane's journal carries exactly these fields) — no
        needle parse, no flush: the writer's pwrite already made the
        bytes visible to this plane's fd."""
        self._lib.rp_put(self._h, vid, needle_id, cookie, data_off,
                         data_len)

    def delete_needle(self, vid: int, needle_id: int) -> None:
        self._lib.rp_del(self._h, vid, needle_id)

    def served(self) -> int:
        return self._lib.rp_served(self._h)

    # -- flight records (ISSUE 18) --------------------------------------

    def drain_records(self, sink=None, cap: int = 512):
        """Pull the plane's flight ring (see native.drain_plane_records
        for the sink-vs-list contract).  Single-consumer: concurrent
        pulls must be serialized by the owning PlaneRecordDrainer."""
        if self._h < 0:
            return [] if sink is None else 0
        return native.drain_plane_records(self._lib, "rp", self._h,
                                          sink, cap)

    def records_dropped(self) -> int:
        return int(self._lib.rp_records_dropped(self._h)) \
            if self._h >= 0 else 0

    def start_record_drain(self, tracker=None,
                           metrics=None) -> "object":
        """Start the flight-record drainer (tick + scrape hook);
        idempotent.  The read plane's tracker defaults to the hedge
        read_tracker so plane-served reads train the hedged-read p95
        (the ISSUE 18 'plane traffic trains the thresholds' goal)."""
        if getattr(self, "_drainer", None) is not None:
            return self._drainer
        from .. import profiling
        if tracker is None:
            from ..util import hedge
            tracker = hedge.read_tracker
        sink = profiling.PlaneRecordSink(
            "volume", "read", "GET", RECORD_STAGES, RECORD_FALLBACKS,
            tracker=tracker, metrics=metrics)
        self._drainer = profiling.PlaneRecordDrainer(
            sink, lambda s: self.drain_records(sink=s),
            self.records_dropped).start()
        return self._drainer

    def stop(self) -> None:
        if self._h >= 0:
            if getattr(self, "_drainer", None) is not None:
                self._drainer.stop()
            self._lib.rp_stop(self._h)
            self._h = -1
