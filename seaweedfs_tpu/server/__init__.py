"""Server roles: master + volume server over HTTP/JSON.

The reference speaks gRPC for control and HTTP for data
(pb/grpc_client_server.go); this image has no Python gRPC runtime, so
the control-plane RPCs are mirrored 1:1 as JSON-over-HTTP endpoints
carrying the same message shapes as the .proto definitions (each
handler cites its proto counterpart).  The public data path (assign /
upload / read) keeps the reference's HTTP API exactly.
"""

from .master_server import MasterServer  # noqa: F401
from .volume_server import VolumeServer  # noqa: F401
