"""Native filer read-plane wrapper (native/filer_read_plane.cc).

The read sibling of server/meta_plane_native.py: a C++ epoll loop that
serves eligible warm `GET /path` with zero Python per request — parse,
C-side entry-map lookup, chunk fetch from the volume's native read
plane over the shared persistent plane-socket pool, 200 to the client.
Everything else answers the 404 `{"error":"read plane fallback"}` and
the client replays against the Python filer port.

The C side holds only ADVISORY knowledge: path -> (volume read-plane
addr, fid, size, mime).  This wrapper supplies the three things the
C++ loop cannot cheaply do itself:

* COHERENCE: every mutation event — the filer's own (Filer.subscribe)
  and every sibling writer's (MetaPlane.sink follower tap) —
  invalidates the touched paths SYNCHRONOUSLY, before anything else
  runs on the event.  Fills are fenced by the plane's generation
  clock (`begin_fill` token captured before the entry was read, the
  meta-cache protocol): a fill that lost a race with a later
  invalidation is refused by the C side, so the map can only
  under-serve (fallback), never serve a pre-overwrite chunk.
* FILLS: a background thread resolves each fill's volume read-plane
  address (vid -> master lookup -> /status readPlanePort, memoized
  with a short TTL) and registers the entry.  Fills come from two
  places — mutation events (the write just told us the geometry) and
  the Python read path (a warm read that passed the full eligibility
  check re-registers the path it just served).
* the METRICS bridge rendered on the filer's /metrics.

Failure contract: construction raises RuntimeError when the toolchain
can't build the .so (the call site degrades to Python-only serving);
at runtime a dead volume plane, stale registration, or SIGKILL'd
worker shows up as clean fallbacks or connection errors — never a
truncated 200 (the C side buffers the full chunk before framing).
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
import time

from .. import native, operation, security
from ..filer.filer import CHUNK_SIZE
from ..util import wlog

# response latency histogram bucket bounds (filer_read_plane.cc
# kLatBuckets), in seconds — rendered on /metrics as
# filer_read_plane_native_response_seconds
RESPONSE_BUCKETS_S = (1e-6, 2e-6, 5e-6, 1e-5, 2e-5, 5e-5, 1e-4, 2e-4,
                      5e-4, 1e-3, 2e-3, 5e-3, 1e-2, 2e-2, 5e-2, 1e-1,
                      1.0)

_STATS_KEYS = ("requests", "fallbacks", "stale_misses",
               "upstream_errors", "parse_ns", "lookup_ns", "fetch_ns",
               "send_ns")

# flight-record label tables (filer_read_plane.cc kRecStageNames /
# kRecFallbackNames — the SWFS019 lint pins the literals in sync)
RECORD_STAGES = ("parse", "lookup", "fetch", "send")
RECORD_FALLBACKS = ("none", "ineligible", "unknown_path", "stale",
                    "upstream")

_ADDR_TTL_S = 10.0      # vid -> read-plane-addr memo lifetime
_FILL_QUEUE_MAX = 4096  # beyond this, fills drop (reads just fall back)


def native_read_plane_enabled() -> "bool | None":
    """SEAWEEDFS_TPU_FILER_READ_PLANE_NATIVE: '0' forces off, '1'
    forces on, unset/other = auto (on when the meta plane is on and
    the toolchain builds the .so)."""
    v = os.environ.get("SEAWEEDFS_TPU_FILER_READ_PLANE_NATIVE", "")
    if v == "0":
        return False
    if v == "1":
        return True
    return None


def _path_bytes_ok(path: str) -> bool:
    """Mirror of the C side's request-target byte filter: only fill
    paths the plane could actually be asked for verbatim (printable
    ASCII, no quote/backslash/percent/query/fragment — a percent in
    the URL means the Python front sees a DIFFERENT, decoded path)."""
    return all(0x21 <= ord(ch) <= 0x7E and ch not in '"\\%?#'
               for ch in path)


class NativeReadPlane:
    """One native filer read plane bound to <host>:<ephemeral>,
    fetching chunks from volume read planes located via `master`."""

    def __init__(self, master: str, host: str = "127.0.0.1"):
        self._lib = native.load_filer_read_plane()
        if self._lib is None:
            raise RuntimeError("native read plane unavailable")
        port = ctypes.c_int(0)
        self._h = self._lib.frp_start(host.encode(), 0,
                                      ctypes.byref(port))
        if self._h < 0:
            raise RuntimeError("native read plane failed to start")
        self.host = host
        self.port = port.value
        self.master = master
        self._armed = False
        self._drainer = None
        self._addr_memo: "dict[int, tuple[str | None, float]]" = {}
        self._fills: "queue.Queue" = queue.Queue(_FILL_QUEUE_MAX)
        self._stop_evt = threading.Event()
        self._filler = threading.Thread(target=self._fill_loop,
                                        daemon=True)
        self._filler.start()

    # -- arming ---------------------------------------------------------

    def arm(self, on: bool = True) -> None:
        """The PR 11 native_on/native_off lever: disarmed, the
        listener stays up but every request answers the 404 fallback
        (clients keep their conns; Python serves)."""
        self._armed = bool(on)
        self._lib.frp_arm(self._h, 1 if on else 0)

    @property
    def armed(self) -> bool:
        return self._armed

    # -- coherence (called from filer listener + plane sink) ------------

    def begin_fill(self) -> int:
        """Generation token for a warm fill; capture BEFORE reading
        the entry that will be registered (the meta-cache begin_fill
        protocol — the C side refuses the fill if any invalidation of
        the path lands after this point)."""
        return int(self._lib.frp_gen(self._h))

    def invalidate(self, path: str) -> None:
        try:
            self._lib.frp_invalidate(self._h, path.encode())
        except (OSError, UnicodeEncodeError):
            pass

    def clear(self) -> None:
        self._lib.frp_clear(self._h)

    def on_event(self, event: dict) -> None:
        """Filer listener (Filer.subscribe): this process's own
        mutation events.  Invalidation is SYNCHRONOUS — it completes
        before the write's ack returns to the client — so a reader
        who observed the ack can never be served pre-mutation bytes;
        the refill rides the async fill queue behind its fence
        token."""
        try:
            self._learn(event)
        except Exception:  # noqa: SWFS004 — advisory knowledge only;
            pass           # a missed fill means fallbacks, never
            #                stale bytes (invalidation is the first
            #                statement and does not allocate)

    def _learn(self, ev: dict) -> None:
        new = ev.get("newEntry")
        old = ev.get("oldEntry")
        for side in (new, old):
            p = (side or {}).get("fullPath", "")
            if p:
                self.invalidate(p)
        if not new or new.get("isDirectory") or \
                ev.get("op", "") not in ("create", "update"):
            return
        fill = self._eligible_json(new)
        if fill is None:
            return
        # token AFTER the invalidation above: a later mutation still
        # fences this fill out, an earlier one no longer can
        token = self.begin_fill()
        self._enqueue_fill(new.get("fullPath", ""), fill, token)

    def on_follower_events(self, events) -> None:
        """MetaPlane.sink: the coherence follower's raw poll batches —
        (event, raw_new, pos, wid) tuples for every sibling writer's
        WAL line (including the native meta plane's own acks, which is
        exactly how natively-written files become natively
        readable)."""
        for item in events:
            try:
                self._learn(item[0] if isinstance(item, tuple)
                            else item)
            except Exception:  # noqa: SWFS004
                pass

    # -- fills ----------------------------------------------------------

    def _eligible_json(self, new: dict) -> "tuple[str, int, str] | None":
        """(fid, size, mime) when the event-JSON entry is servable
        natively: exactly one whole-file plain chunk, no TTL, no
        extended attributes (SSE markers live there), no read-auth."""
        chunks = new.get("chunks") or []
        if len(chunks) != 1:
            return None
        c = chunks[0]
        size = int(c.get("size", 0))
        if int(c.get("offset", 0)) != 0 or size <= 0 or \
                size > CHUNK_SIZE:
            return None
        attrs = new.get("attributes") or {}
        if int(attrs.get("ttlSec", 0) or 0) != 0:
            return None
        if attrs.get("symlinkTarget", ""):
            return None
        if new.get("extended"):
            return None
        fid = c.get("fileId", "")
        if not fid or "," not in fid:
            return None
        return fid, size, attrs.get("mime", "")

    def eligible_entry(self, entry) -> "tuple[str, int, str] | None":
        """Same check over a live filer Entry (the Python read path's
        warm-fill hook)."""
        if entry.is_directory or len(entry.chunks) != 1:
            return None
        c = entry.chunks[0]
        if c.offset != 0 or c.size <= 0 or c.size > CHUNK_SIZE:
            return None
        a = entry.attributes
        if a.ttl_sec or a.symlink_target or entry.extended:
            return None
        if not c.file_id or "," not in c.file_id:
            return None
        return c.file_id, c.size, a.mime

    def warm_fill(self, path: str, entry, token: int) -> None:
        """Register `path` after the Python front served it warm;
        `token` must have been captured via begin_fill() BEFORE the
        entry was looked up."""
        fill = self.eligible_entry(entry)
        if fill is not None:
            self._enqueue_fill(path, fill, token)

    def _enqueue_fill(self, path: str, fill, token: int) -> None:
        if not path or not _path_bytes_ok(path):
            return
        if security.current().volume_read_key:
            return  # read-jwt cluster: the bare native GET would 401
        try:
            self._fills.put_nowait((path, fill[0], fill[1], fill[2],
                                    token))
        except queue.Full:
            pass  # dropped fill = fallbacks until re-read, never stale

    def _addr_for_vid(self, vid: int) -> "str | None":
        memo = self._addr_memo
        hit = memo.get(vid)
        now = time.monotonic()
        if hit is not None and hit[1] > now:
            return hit[0]
        addr = None
        try:
            for loc in operation.lookup(self.master, vid):
                addr = operation._read_plane_addr_for(loc["url"])
                if addr is not None:
                    break
        except Exception:  # noqa: BLE001 — dead master = dry fills
            addr = None
        if len(memo) > 1024:
            memo.clear()
        memo[vid] = (addr, now + _ADDR_TTL_S)
        return addr

    def _fill_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                item = self._fills.get(timeout=0.2)
            except queue.Empty:
                continue
            path, fid, size, mime, token = item
            try:
                vid = int(fid.partition(",")[0])
                addr = self._addr_for_vid(vid)
                if addr is None:
                    continue  # no volume plane: path stays fallback
                self._lib.frp_put_entry(
                    self._h, path.encode(), addr.encode(),
                    fid.encode(), (mime or "").encode(),
                    int(size), int(token))
            except Exception as e:  # noqa: BLE001
                wlog.debug(f"read plane fill failed: {e!r}")

    # -- telemetry ------------------------------------------------------

    def requests(self) -> int:
        return self._lib.frp_requests(self._h)

    def fallbacks(self) -> int:
        return self._lib.frp_fallbacks(self._h)

    def entries(self) -> int:
        return max(0, self._lib.frp_entries(self._h))

    def stats(self) -> dict:
        out = (ctypes.c_ulonglong * 8)()
        n = self._lib.frp_stats(self._h, out)
        if n <= 0:
            return {k: 0 for k in _STATS_KEYS}
        return {k: int(out[i]) for i, k in enumerate(_STATS_KEYS)}

    def response_histogram(self) -> "tuple[list[int], int, float]":
        """(cumulative bucket counts aligned with RESPONSE_BUCKETS_S +
        an +Inf cell, total count, sum seconds)."""
        out = (ctypes.c_ulonglong * 20)()
        cells = self._lib.frp_latency(self._h, out)
        if cells <= 0:
            return [], 0, 0.0
        buckets = [int(out[i]) for i in range(cells + 1)]
        return buckets, int(out[cells + 1]), out[cells + 2] / 1e9

    # -- flight records (ISSUE 18) --------------------------------------

    def drain_records(self, sink=None, cap: int = 512):
        """Pull the plane's flight ring (see native.drain_plane_records
        for the sink-vs-list contract).  Single-consumer: concurrent
        pulls must be serialized by the owning PlaneRecordDrainer."""
        if self._h < 0:
            return [] if sink is None else 0
        return native.drain_plane_records(self._lib, "frp", self._h,
                                          sink, cap)

    def records_dropped(self) -> int:
        return int(self._lib.frp_records_dropped(self._h)) \
            if self._h >= 0 else 0

    def set_fetch_delay_ms(self, ms: int) -> None:
        """Failpoint: stall the volume fetch hop of every native
        request by `ms` (chaos tests widen the in-flight window with
        this before delivering SIGKILL)."""
        if self._h >= 0:
            self._lib.frp_set_fetch_delay_ms(self._h, int(ms))

    def start_record_drain(self, tracker=None,
                           metrics=None) -> "object":
        """Start the flight-record drainer (tick + scrape hook);
        idempotent.  Returns the profiling.PlaneRecordDrainer."""
        if self._drainer is not None:
            return self._drainer
        from .. import profiling
        sink = profiling.PlaneRecordSink(
            # plane label "filer_read": the VOLUME read plane already
            # owns "read" in the plane_stage_seconds family, and the
            # two share stage names ("parse"/"lookup"/"send") that
            # would silently merge under one label
            "filer", "filer_read", "GET", RECORD_STAGES,
            RECORD_FALLBACKS,
            tracker=tracker, metrics=metrics)
        self._drainer = profiling.PlaneRecordDrainer(
            sink, lambda s: self.drain_records(sink=s),
            self.records_dropped).start()
        return self._drainer

    def stop(self) -> None:
        """Filler + drainer first, then the native server: frp_stop
        frees the Server object, so no wrapper thread may still be
        inside an frp_* call when it runs."""
        if self._h < 0:
            return
        self._stop_evt.set()
        self._filler.join(timeout=5)
        if self._drainer is not None:
            self._drainer.stop()
        self._lib.frp_stop(self._h)
        self._h = -1
