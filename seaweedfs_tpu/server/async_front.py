"""Asyncio front for a role's HTTP funnel (ISSUE 12 tentpole, part 2).

The threaded server (httpd.py) spends a thread — stack, scheduler
churn, GIL convoying — per connection; at gateway concurrency the
recv/route/assign/proxy funnel is host-bound long before the disks
are.  This front multiplexes every connection of a role on ONE event
loop: HTTP framing (request parse, body recv, response write) runs on
the loop, handlers execute on a small bounded thread pool (they are
synchronous by design — sqlite, pooled-client hops), and everything
observable is SHARED with the threaded front: the owner HttpServer's
route tables, guard, QoS admission hook, tracing spans, request-id
propagation, requests_in_flight gauge and request_seconds histogram.
`SEAWEEDFS_TPU_ASYNC_FRONT=1` selects it for the filer gateway
(a comma list names other roles); default stays the threaded server.

Handler-facing requests duck-type httpd.Request: `.method`, `.path`,
`.query`, `.headers` (case-insensitive), `.body` (pre-read on the
loop — the recv is the part worth multiplexing), `.json()`,
`.stream_body()`, `.drain()`, and a `._handler.close_connection` shim
for handlers that poison-pill their connection.

SWFS014 polices this file's contract: an `async def` handler here must
never block the loop — time.sleep, sync pooled-client calls, and
un-executor'd file reads belong on the pool.
"""

from __future__ import annotations

import asyncio
import http.client
import json
import os
import threading
import urllib.parse
from concurrent.futures import ThreadPoolExecutor

from .. import tracing
from ..util.request_id import HEADER as _RID_HEADER
from ..util.request_id import ensure_request_id
from .httpd import normalize_payload

_MAX_HEADER = 64 << 10


class _Headers:
    """Case-insensitive header map preserving original spellings
    (the email.Message surface the handlers actually use)."""

    __slots__ = ("_d",)

    def __init__(self):
        self._d: dict = {}

    def add(self, k: str, v: str) -> None:
        lk = k.lower()
        if lk in self._d:
            # duplicate headers: comma-join (RFC 9110 §5.2), matching
            # what handlers would see from email.Message.get
            self._d[lk] = (self._d[lk][0], self._d[lk][1] + ", " + v)
        else:
            self._d[lk] = (k, v)

    def get(self, k: str, default=None):
        t = self._d.get(k.lower())
        return t[1] if t is not None else default

    def __getitem__(self, k: str):
        t = self._d.get(k.lower())
        if t is None:
            raise KeyError(k)
        return t[1]

    def __contains__(self, k) -> bool:
        return isinstance(k, str) and k.lower() in self._d

    def __iter__(self):
        for orig, _v in self._d.values():
            yield orig

    def keys(self):
        return [orig for orig, _v in self._d.values()]

    def values(self):
        return [v for _o, v in self._d.values()]

    def items(self):
        return [(orig, v) for orig, v in self._d.values()]


class _HandlerShim:
    """Handlers poke `req._handler.close_connection` to poison-pill a
    connection (mid-stream failure injection); the front honors it."""

    __slots__ = ("close_connection",)

    def __init__(self):
        self.close_connection = False


class AsyncRequest:
    """httpd.Request duck-type over a fully-received async request."""

    __slots__ = ("method", "path", "remote_ip", "headers", "_raw_query",
                 "_query", "_body", "_handler")

    def __init__(self, method: str, target: str, headers: _Headers,
                 body: bytes, remote_ip: str):
        path, _, query = target.partition("?")
        if path[:4] == "http" and "://" in path[:8]:
            rest = path.split("://", 1)[1]
            slash = rest.find("/")
            path = rest[slash:] if slash >= 0 else "/"
        self.method = method
        self.path = path
        self.remote_ip = remote_ip
        self.headers = headers
        self._raw_query = query
        self._query = None
        self._body = body
        self._handler = _HandlerShim()

    @property
    def query(self) -> dict:
        if self._query is None:
            self._query = {
                k: v[0] for k, v in urllib.parse.parse_qs(
                    self._raw_query, keep_blank_values=True).items()} \
                if self._raw_query else {}
        return self._query

    @property
    def body(self) -> bytes:
        return self._body

    def json(self) -> dict:
        return json.loads(self._body or b"{}")

    def stream_body(self, chunk_size: int = 4 << 20):
        # the loop already received the body; yield it once (the same
        # fallback httpd.Request.stream_body takes for buffered
        # bodies) — handlers that stream see identical semantics
        if self._body:
            yield self._body

    def drain(self, max_drain: int = 64 << 20) -> None:
        pass   # nothing unread: the loop consumed the framing


class AsyncFront:
    """One event loop + bounded handler pool serving an HttpServer's
    routes (shared guard/admission/metrics/tracing)."""

    def __init__(self, owner, ssl_context=None):
        self.owner = owner
        self.ssl_context = ssl_context
        self._loop: "asyncio.AbstractEventLoop | None" = None
        self._server = None
        self._thread: "threading.Thread | None" = None
        self._transports: set = set()
        try:
            workers = max(1, int(os.environ.get(
                "SEAWEEDFS_TPU_ASYNC_WORKERS", "") or 16))
        except ValueError:
            workers = 16
        self._pool = ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix=f"async-{owner.role or 'front'}")
        self._ready = threading.Event()

    # -- lifecycle ------------------------------------------------------

    def start(self, sock) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(sock,), daemon=True)
        self._thread.start()
        self._ready.wait(timeout=10)

    def _run(self, sock) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def _serve():
            sock.setblocking(False)
            self._server = await asyncio.start_server(
                self._handle_conn, sock=sock, ssl=self.ssl_context,
                limit=_MAX_HEADER)
            self._ready.set()

        loop.run_until_complete(_serve())
        try:
            loop.run_forever()
        finally:
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            except RuntimeError:
                pass
            loop.close()

    def stop(self) -> None:
        loop = self._loop
        if loop is None:
            return

        def _shutdown():
            if self._server is not None:
                self._server.close()
            for tr in list(self._transports):
                try:
                    tr.close()
                except (OSError, RuntimeError):
                    pass   # teardown: transport already dead
            loop.stop()

        try:
            loop.call_soon_threadsafe(_shutdown)
        except RuntimeError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._pool.shutdown(wait=False)

    # -- connection handling --------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        self._transports.add(writer.transport)
        peer = writer.get_extra_info("peername") or ("", 0)
        remote_ip = peer[0] if isinstance(peer, tuple) else ""
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError,
                        asyncio.LimitOverrunError):
                    return
                lines = head[:-4].decode("latin-1").split("\r\n")
                try:
                    method, target, _version = lines[0].split(" ", 2)
                except ValueError:
                    return
                headers = _Headers()
                for line in lines[1:]:
                    k, sep, v = line.partition(":")
                    if sep:
                        headers.add(k.strip(), v.strip())
                try:
                    body = await self._read_body(reader, headers)
                except (ValueError, asyncio.IncompleteReadError):
                    return
                req = AsyncRequest(method, target, headers, body,
                                   remote_ip)
                keep = await self._dispatch(req, writer)
                want_close = (
                    not keep or req._handler.close_connection or
                    (headers.get("Connection") or "").lower() ==
                    "close")
                if want_close:
                    return
        except (ConnectionError, TimeoutError, OSError):
            pass
        finally:
            self._transports.discard(writer.transport)
            try:
                writer.close()
            except (OSError, RuntimeError):
                pass   # teardown: transport already dead

    async def _read_body(self, reader: asyncio.StreamReader,
                         headers: _Headers) -> bytes:
        te = (headers.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            out = bytearray()
            while True:
                line = await reader.readline()
                size = int(line.split(b";")[0], 16)   # ValueError: up
                if size == 0:
                    while True:
                        t = await reader.readline()
                        if t in (b"\r\n", b"\n", b""):
                            break
                    break
                out += await reader.readexactly(size)
                await reader.readexactly(2)
            return bytes(out)
        length = int(headers.get("Content-Length") or 0)
        if length:
            return await reader.readexactly(length)
        return b""

    # -- dispatch -------------------------------------------------------

    def _sync_process(self, req: AsyncRequest):
        """Everything between framing and response write, on a pool
        thread: request-id adoption, server span, QoS admission,
        guard, route — the same ladder as the threaded dispatcher.
        Returns the flight-recorder material (verdict, pool-thread
        CPU, deadline doc, stage summary, notes) alongside, since the
        contextvars it rides live on THIS thread, not the loop's."""
        import time as _time

        from .. import profiling as _prof
        outer = self.owner
        rid = ensure_request_id(req.headers.get(_RID_HEADER, ""))
        # deadline plane (util/deadline): same ingress contract as the
        # threaded front — adopt (or clear a stale binding on this
        # reused pool thread) before any work, 504 an expired budget
        # before admission/guard/route spend anything; the
        # maintenance plane is exempt from the operator DEFAULT
        # (explicit budgets still honored)
        from ..util import deadline as _dl
        dl = _dl.adopt(req.headers.get(_dl.HEADER),
                       site=outer.role or "server",
                       allow_default=not req.path.startswith(
                           ("/admin/", "/debug/")))
        flight_on = _prof.recorder_enabled()
        if flight_on:
            _prof.arm_flight_notes()
        # sampled CPU attribution, same rule as the threaded front:
        # deadline-carrying requests always pay the thread-CPU clock,
        # budget-less ones every Nth — and the k<=0 kill switch
        # gates both (cpu_attr_front)
        cpu0 = _time.thread_time() \
            if _prof.cpu_attr_front(dl is not None) else None
        verdict = "ok"
        route = outer.routes.get((req.method, req.path))
        if route is None and outer.prefix_routes:
            route = outer._prefix_route(req.method, req.path)
        _, parent_span = tracing.parse_traceparent(
            req.headers.get(tracing.HEADER, ""))
        sp = tracing.start_span(
            f"{req.method} {req.path}", role=outer.role,
            parent=parent_span, trace_id=rid)
        if dl is not None:
            sp.set("deadlineMs", int(dl.remaining() * 1e3))
        qos_release = None
        try:
            throttled = None
            if dl is not None and dl.expired():
                throttled = _dl.expired_response(
                    f"{outer.role or 'server'}.ingress")
                verdict = "deadline"
            if throttled is None and outer.admission is not None:
                throttled, qos_release = outer.admission(req)
                if throttled is not None:
                    verdict = "shed"
            if throttled is not None:
                status, payload = throttled
            elif (denied := outer.guard(req)
                  if outer.guard else None) is not None:
                status, payload = denied
            elif route is not None:
                status, payload = route(req)
            elif outer.fallback is not None:
                status, payload = outer.fallback(req)
            else:
                status, payload = 404, {"error": "not found"}
        except _dl.DeadlineExceeded as e:
            # budget died mid-handler: 504, matching the threaded front
            status, payload = _dl.handler_exceeded_response()
            verdict = "deadline"
            sp.set_error(e)
        except Exception as e:  # noqa: BLE001 — server must answer
            status, payload = 500, {"error": str(e)}
            verdict = "error"
            sp.set_error(e)
        # cpu rides OUTSIDE the flight dict: the request_cpu_seconds
        # histogram must not vanish when the recorder is disarmed
        # (the threaded front emits it unconditionally).  The summary
        # drain is likewise unconditional — a finished track's
        # summary left behind while disarmed would be attributed to a
        # later request on this reused pool thread after re-arming.
        cpu = (_time.thread_time() - cpu0) if cpu0 is not None \
            else None
        summary = _prof.take_last_summary()
        flight = None
        if flight_on:
            dl_doc = None
            if dl is not None:
                dl_doc = {"budgetMs": int(dl.budget * 1e3),
                          "remainingMs": int(dl.remaining() * 1e3)}
            flight = {"verdict": verdict,
                      "deadline": dl_doc,
                      "stages": summary,
                      "notes": _prof.take_flight_notes()}
        return status, payload, sp, rid, qos_release, cpu, flight

    async def _dispatch(self, req: AsyncRequest,
                        writer: asyncio.StreamWriter) -> bool:
        """Returns True to keep the connection alive."""
        outer = self.owner
        loop = asyncio.get_running_loop()
        with outer._inflight_lock:
            outer._inflight += 1
            inflight = outer._inflight
        if outer.metrics is not None:
            outer.metrics.gauge_set(
                "requests_in_flight", inflight,
                help_text="requests currently being handled")
        sp = None
        status = 0
        qos_release = None
        stream_body = None
        cpu = None
        flight = None
        keep = True
        try:
            status, payload, sp, rid, qos_release, cpu, flight = \
                await loop.run_in_executor(self._pool,
                                           self._sync_process, req)
            body, ctype, extra_headers = normalize_payload(payload)
            reason = http.client.responses.get(status, "")
            head = [f"HTTP/1.1 {status} {reason}",
                    f"Content-Type: {ctype}",
                    f"{_RID_HEADER}: {rid}"]
            for hk, hv in extra_headers.items():
                head.append(f"{hk}: {hv}")
            if hasattr(body, "read"):
                stream_body = body
                # file-like bodies must carry Content-Length in
                # extra_headers (the threaded front's rule; these
                # responses are never chunked)
                writer.write(("\r\n".join(head) + "\r\n\r\n")
                             .encode("latin-1"))
                if req.method != "HEAD":
                    while True:
                        chunk = await loop.run_in_executor(
                            self._pool, stream_body.read, 1 << 20)
                        if not chunk:
                            break
                        writer.write(chunk)
                        await writer.drain()
                await writer.drain()
                return keep
            if "Content-Length" not in extra_headers:
                head.append(f"Content-Length: {len(body)}")
            writer.write(("\r\n".join(head) + "\r\n\r\n")
                         .encode("latin-1"))
            if req.method != "HEAD":
                writer.write(body)
            await writer.drain()
            return keep
        except (ConnectionError, TimeoutError, OSError):
            keep = False
            return False
        finally:
            if stream_body is not None:
                try:
                    stream_body.close()
                except OSError:
                    pass
            if qos_release is not None:
                try:
                    qos_release()
                except Exception as e:  # noqa: BLE001 — accounting
                    # must never break a reply
                    from ..util import wlog
                    wlog.warning("qos release failed: %s", e,
                                 component="qos")
            if sp is not None:
                sp.set("status", status)
                sp.finish()
            with outer._inflight_lock:
                outer._inflight -= 1
                inflight = outer._inflight
            if outer.metrics is not None:
                outer.metrics.gauge_set("requests_in_flight",
                                        inflight)
                if sp is not None:
                    outer.metrics.histogram_observe(
                        "request_seconds", sp.duration,
                        help_text="HTTP request handling latency",
                        method=req.method, code=str(status))
                    if cpu is not None:
                        from .. import profiling as _prof
                        outer.metrics.histogram_observe(
                            "request_cpu_seconds", cpu,
                            buckets=_prof.STAGE_BUCKETS,
                            help_text="handler-thread CPU per request"
                                      " (thread_time, sampled — see "
                                      "SEAWEEDFS_TPU_CPU_SAMPLE); "
                                      "request_seconds minus this is "
                                      "GIL/lock/IO wait",
                            method=req.method, code=str(status))
            if flight is not None and sp is not None:
                # after sp.finish(): the capture's span-tree pull must
                # see the server span in the ring.  The wall covers
                # the response write (sp.duration does); the CPU is
                # the pool thread's handler share — the loop's framing
                # cost is the front's, not this request's.
                from .. import profiling as _prof
                try:
                    _prof.flight_recorder().observe(
                        role=outer.role or "server",
                        method=req.method, path=req.path,
                        status=status, wall_s=sp.duration,
                        cpu_s=cpu,
                        verdict=flight["verdict"], trace_id=rid,
                        deadline=flight["deadline"],
                        stages=flight["stages"],
                        notes=flight["notes"])
                except Exception as e:  # noqa: BLE001 —
                    # observability must never break a reply
                    from ..util import wlog
                    wlog.warning("flight capture failed: %s", e,
                                 component="profiling")
