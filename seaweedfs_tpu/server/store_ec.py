"""EC read path with remote shards and on-the-fly degraded-read
reconstruction (weed/storage/store_ec.go:141-443).

Resolution order per interval (store_ec.go:207 readOneEcShardInterval):
local shard -> remote shard (locations cached from the master with
tiered TTL freshness, :248 cachedLookupEcShardLocations) -> reconstruct
from >= data_shards surviving shards fetched in parallel (:366
recoverOneRemoteEcShardInterval).  Reconstruction uses the CPU RS twin:
single-needle degraded reads are latency-bound, so the TPU batch path is
reserved for bulk rebuild (SURVEY §7 hard part 3).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..ops import rs_cpu, rs_native
from ..storage import types
from ..storage.erasure_coding import EcVolume
from ..storage.erasure_coding.ec_context import (LARGE_BLOCK_SIZE,
                                                 SMALL_BLOCK_SIZE)
from ..storage.erasure_coding.ec_volume import NotFoundError
from ..storage.needle import Needle
from ..util.deadline import DeadlineExceeded as _DeadlineExceeded
from .httpd import http_bytes, http_json

# tiered freshness (store_ec.go:248): incomplete -> 11s, full -> 37min,
# enough-to-read -> 7min
_TTL_INCOMPLETE = 11.0
_TTL_FULL = 37 * 60.0
_TTL_ENOUGH = 7 * 60.0

# degraded-read latency histogram: loopback slice decode sits well
# under DEFAULT_BUCKETS' floor, WAN survivor fan-outs above it
_DEGRADED_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _degraded_enabled() -> bool:
    """``SEAWEEDFS_TPU_EC_DEGRADED_READS`` kill switch (default on):
    an operator riding out a cascading failure can turn the d-way
    survivor fan-outs into fast 404s instead of amplifying load."""
    import os
    return os.environ.get("SEAWEEDFS_TPU_EC_DEGRADED_READS",
                          "1") not in ("0", "false")


def _degraded_stream_bytes() -> int:
    """Window size for the STREAMED degraded path; intervals at or
    under one window keep the one-shot latency shape
    (``SEAWEEDFS_TPU_DEGRADED_SLICE_MB``, default 1)."""
    import os
    try:
        mb = float(os.environ.get("SEAWEEDFS_TPU_DEGRADED_SLICE_MB",
                                  "") or 1.0)
    except ValueError:
        mb = 1.0
    return max(int(mb * (1 << 20)), 4 << 10)


class _ShardLocationCache:
    def __init__(self):
        self.locations: dict[int, list[str]] = {}
        self.refreshed = 0.0
        self.lock = threading.Lock()


class EcReader:
    """Serves needle reads over an EcVolume whose shards may live on
    other servers; owned by the volume server."""

    def __init__(self, master: str, self_url: str,
                 security_headers=None):
        self.master = master
        self.self_url = self_url
        # callable -> admin headers for cross-server shard reads (the
        # owning volume server's per-instance security config; the
        # global-config auto-attach covers the default case)
        self._security_headers = security_headers or (lambda: {})
        self._caches: dict[int, _ShardLocationCache] = {}
        self._codecs: dict[tuple[int, int], object] = {}
        self._pool = ThreadPoolExecutor(max_workers=14)

    # -- public -----------------------------------------------------------

    def read_needle(self, ev: EcVolume, needle_id: int,
                    cookie: int | None = None) -> Needle:
        """store_ec.go:141 ReadEcShardNeedle: the local read path with
        this reader's scatter/reconstruct interval resolution.  The
        returned needle is tagged `was_degraded` when any interval
        reconstructed — the volume server's hot-cache promotion policy
        (SEAWEEDFS_TPU_DEGRADED_PROMOTE) keys off it."""
        degraded = [False]
        n = ev.read_needle_with(
            lambda iv: self._read_interval(ev, needle_id, iv,
                                           degraded),
            needle_id, cookie=cookie)
        n.was_degraded = degraded[0]
        return n

    # -- interval resolution ---------------------------------------------

    def _read_interval(self, ev: EcVolume, needle_id: int, iv,
                       degraded: "list | None" = None) -> bytes:
        sid, off = iv.to_shard_id_and_offset(
            LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, ev.ctx.data_shards)
        # 1. local
        shard = ev.shards.get(sid)
        if shard is not None:
            with ev.lock:
                return shard.read_at(off, iv.size)
        # 2. remote direct
        locs = self._shard_locations(ev)
        for url in locs.get(sid, []):
            data = self._remote_read(url, ev.id, sid, off, iv.size)
            if data is not None:
                return data
        # 3. reconstruct from survivors — the DEGRADED read path: make
        # it countable (the SLO difference between "one dead peer" and
        # "every read pays a d-way fan-out" lives in this counter)
        from .. import stats
        if not _degraded_enabled():
            raise NotFoundError(
                f"volume {ev.id}: shard {sid} unreachable and degraded "
                f"reads are disabled")
        if degraded is not None:
            degraded[0] = True
        stats.PROCESS.counter_add(
            "ec_degraded_reads_total", 1.0,
            help_text="needle reads served by interval reconstruction "
                      "instead of a direct shard read", vid=ev.id)
        # flight-recorder note: a slow read that RECONSTRUCTED is a
        # different incident from a slow direct shard read
        from .. import profiling
        profiling.flight_note(
            "ecDegraded", {"vid": ev.id, "shard": sid,
                           "bytes": iv.size})
        t0 = time.perf_counter()
        try:
            step = _degraded_stream_bytes()
            if iv.size > step:
                # large interval: decode-on-read in slice windows
                # through the GF kernel — survivor fetch overlaps the
                # matrix apply (arXiv:1908.01527 repair pipelining
                # applied to the READ path), nothing is written to
                # disk, and memory stays bounded at d x window
                try:
                    return self._recover_interval_streamed(
                        ev, sid, off, iv.size, locs, step)
                except _DeadlineExceeded:
                    raise   # budget verdict: re-planning cannot
                    # conjure time — surface the 504 now
                except (OSError, ValueError, KeyError):
                    # a survivor died mid-stream past its internal
                    # failover: the one-shot path below re-plans from
                    # everything reachable rather than failing the read
                    pass
            return self._recover_interval(ev, sid, off, iv.size)
        finally:
            stats.PROCESS.histogram_observe(
                "ec_degraded_read_seconds",
                time.perf_counter() - t0, buckets=_DEGRADED_BUCKETS,
                help_text="wall time of degraded (reconstructing) "
                          "needle interval reads")

    def _remote_read(self, url: str, vid: int, sid: int, offset: int,
                     size: int) -> bytes | None:
        """volume_server.proto:101 VolumeEcShardRead.  Returns None on
        any transport failure — a dead shard server must degrade to
        reconstruction, not surface a 500 (store_ec.go falls through).
        Consults the peer's circuit breaker first (an open peer is
        skipped without burning a timeout) and cache-busts this
        volume's shard locations on failure so the NEXT read re-looks
        up placement instead of retrying the same dead peer until the
        37-minute TTL expires."""
        if url == self.self_url:
            return None
        from ..util import deadline as _deadline
        from ..util import retry as _retry
        if not _retry.peer_available(url):
            self._note_failover(url)
            return None
        # budget derived OUTSIDE the try: an expired deadline must
        # surface as the budget verdict it is, not read as a dead
        # shard server (failover + location bust would punish a
        # healthy peer for the client's clock)
        t = _deadline.io_timeout(10.0, site="ec.shard_read")
        try:
            status, body, _ = http_bytes(
                "GET",
                f"{url}/admin/ec/shard_read?volumeId={vid}&shardId={sid}"
                f"&offset={offset}&size={size}", timeout=t,
                headers=self._security_headers())
        except _deadline.DeadlineExceeded:
            raise               # budget verdict, not a peer verdict
        except OSError:
            # the budget can also die MID-call (a budget-capped socket
            # timeout on a healthy-but-slower peer): same rule
            _deadline.reraise_if_expired("ec.shard_read")
            self._note_failover(url)
            self._bust_locations(vid, url)
            return None
        if status == 200 and len(body) == size:
            return body
        self._note_failover(url)
        return None

    def _note_failover(self, url: str) -> None:
        from .. import stats
        stats.PROCESS.counter_add(
            "ec_read_source_failovers_total", 1.0,
            help_text="EC reads that abandoned a shard source "
                      "(transport failure, short body, open breaker)",
            peer=url)

    def _bust_locations(self, vid: int, dead_url: str) -> None:
        """Drop a dead peer from this volume's cached shard locations
        and expire the cache: the next read refreshes placement from
        the master rather than re-timing-out on the same peer."""
        cache = self._caches.get(vid)
        if cache is None:
            return
        with cache.lock:
            for sid, urls in list(cache.locations.items()):
                if dead_url in urls:
                    cache.locations[sid] = \
                        [u for u in urls if u != dead_url]
            cache.refreshed = 0.0

    def _recover_interval_streamed(self, ev: EcVolume,
                                   missing_sid: int, offset: int,
                                   size: int, locs: dict,
                                   step: int) -> bytes:
        """Streamed decode-on-read for one lost-shard interval: pick d
        survivors (local shards free, remote donors round-robined),
        stream ONLY the requested byte range in slice windows through
        the cached reconstruction matrix, and return the missing
        shard's bytes for that range.  The same seams as the rebuild
        pipeline (`MultiSourceFetcher` prefetch + `apply_matrix_lazy`
        when the codec stages launches), but the only output is the
        response — no shard file is written, no full rebuild runs in
        the request path."""
        from ..ops import rs_matrix
        from ..storage.erasure_coding.shard_source import (
            LocalShardSource, MultiSourceFetcher, RemoteShardSource)
        d = ev.ctx.data_shards
        total = ev.ctx.total
        sources: dict[int, object] = {}
        with ev.lock:
            local = {sid: s.path for sid, s in ev.shards.items()}
        try:
            for sid in sorted(local):
                if sid != missing_sid and len(sources) < d:
                    sources[sid] = LocalShardSource(local[sid])
            if len(sources) < d:
                # remote rows round-robined across donors, like the
                # rebuild planner: no single peer's disk serializes
                # the fetch streams
                by_donor: dict[str, list[int]] = {}
                for sid in sorted(locs):
                    if sid == missing_sid or sid in sources or \
                            sid >= total or not locs[sid]:
                        continue
                    by_donor.setdefault(locs[sid][0], []).append(sid)
                tiers = list(by_donor.values())
                i = 0
                while len(sources) < d and any(tiers):
                    tier = tiers[i % len(tiers)]
                    if tier:
                        sid = tier.pop(0)
                        sources[sid] = RemoteShardSource(
                            locs[sid], ev.id, sid,
                            headers=self._security_headers)
                    i += 1
            if len(sources) < d:
                raise NotFoundError(
                    f"volume {ev.id}: only {len(sources)} shards "
                    f"reachable, need {d} to recover shard "
                    f"{missing_sid}")
            present = tuple(sid in sources for sid in range(total))
            mat, survivor_rows = \
                rs_matrix.cached_reconstruction_matrix(
                    d, ev.ctx.parity_shards, present, (missing_sid,))
            used = {sid: sources[sid] for sid in survivor_rows}
            for sid, src in sources.items():
                if sid not in used:
                    src.close()
            sources = used
        except BaseException:
            for src in sources.values():
                src.close()
            raise
        work = [(offset + pos, min(step, size - pos))
                for pos in range(0, size, step)]
        codec = self._codec(d, ev.ctx.parity_shards)
        lazy = getattr(codec, "apply_matrix_lazy", None)
        out = bytearray(size)
        fetcher = MultiSourceFetcher(used, work)
        try:
            buf = None
            for pos, n in work:
                if buf is None or buf.shape != (len(survivor_rows), n):
                    buf = np.empty((len(survivor_rows), n),
                                   dtype=np.uint8)
                filled = fetcher.get(
                    (pos, n),
                    rows={sid: memoryview(buf[row])
                          for row, sid in enumerate(survivor_rows)})
                for row, sid in enumerate(survivor_rows):
                    got = filled[sid]
                    if got < n:
                        buf[row, got:] = 0  # EOF zero-padding
                rec = lazy(mat, buf) if lazy is not None \
                    else codec.apply_matrix(mat, buf)
                rec = np.asarray(rec, dtype=np.uint8)
                lo = pos - offset
                out[lo:lo + n] = rec[0, :n].tobytes()
        finally:
            fetcher.close()
        return bytes(out)

    def _recover_interval(self, ev: EcVolume, missing_sid: int,
                          offset: int, size: int) -> bytes:
        """store_ec.go:366: parallel reads of the same range from every
        other shard, then ReconstructData."""
        total = ev.ctx.total
        d = ev.ctx.data_shards
        locs = self._shard_locations(ev, force_if_missing=missing_sid)
        bufs = np.zeros((total, size), dtype=np.uint8)
        present = [False] * total

        def fetch(sid: int):
            if sid == missing_sid:
                return sid, None
            shard = ev.shards.get(sid)
            if shard is not None:
                with ev.lock:
                    return sid, shard.read_at(offset, size)
            for url in locs.get(sid, []):
                data = self._remote_read(url, ev.id, sid, offset, size)
                if data is not None:
                    return sid, data
            return sid, None

        for sid, data in self._pool.map(fetch, range(total)):
            if data is not None and len(data) == size:
                bufs[sid] = np.frombuffer(data, dtype=np.uint8)
                present[sid] = True
        if sum(present) < d:
            raise NotFoundError(
                f"volume {ev.id}: only {sum(present)} shards reachable, "
                f"need {d} to recover shard {missing_sid}")
        codec = self._codec(d, ev.ctx.parity_shards)
        # intervals only ever target data shards (block_index %
        # data_shards < d), so ReconstructData semantics apply
        # (store_ec.go:435): skip regenerating missing parity rows.
        rec = codec.reconstruct(bufs, present, data_only=True)
        return rec[missing_sid].tobytes()

    # -- shard location cache (store_ec.go:248) ---------------------------

    def _shard_locations(self, ev: EcVolume,
                         force_if_missing: int | None = None
                         ) -> dict[int, list[str]]:
        cache = self._caches.setdefault(ev.id, _ShardLocationCache())
        with cache.lock:
            n = len(cache.locations)
            age = time.monotonic() - cache.refreshed
            fresh = ((n < ev.ctx.data_shards and age < _TTL_INCOMPLETE) or
                     (n == ev.ctx.total and age < _TTL_FULL) or
                     (ev.ctx.data_shards <= n < ev.ctx.total and
                      age < _TTL_ENOUGH))
            if force_if_missing is not None and \
                    force_if_missing not in cache.locations:
                fresh = fresh and age < _TTL_INCOMPLETE
            if not fresh:
                from ..operation import master_json
                from ..util import deadline as _deadline
                # budget derived OUTSIDE the try (shard_read rule): a
                # spent deadline fails fast here instead of proceeding
                # with stale/empty locations on a dead budget
                t = _deadline.io_timeout(5.0, site="master.ec_lookup")
                try:
                    r = master_json(
                        self.master, "GET",
                        f"/dir/ec_lookup?volumeId={ev.id}", timeout=t)
                except _deadline.DeadlineExceeded:
                    raise       # budget verdict, not master-unreachable
                except OSError:
                    _deadline.reraise_if_expired("master.ec_lookup")
                    r = {}
                locs: dict[int, list[str]] = {}
                for entry in r.get("shardIdLocations", []):
                    for sid in entry["shardIds"]:
                        locs.setdefault(sid, []).append(entry["url"])
                if locs:
                    cache.locations = locs
                    cache.refreshed = time.monotonic()
            return dict(cache.locations)

    def _codec(self, d: int, p: int):
        """Native C++ engine when built (the latency path deserves it);
        numpy twin otherwise."""
        key = (d, p)
        if key not in self._codecs:
            if rs_native.available():
                self._codecs[key] = rs_native.ReedSolomonNative(d, p)
            else:
                self._codecs[key] = rs_cpu.ReedSolomonCPU(d, p)
        return self._codecs[key]

    def forget(self, vid: int) -> None:
        self._caches.pop(vid, None)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
