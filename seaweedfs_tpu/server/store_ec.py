"""EC read path with remote shards and on-the-fly degraded-read
reconstruction (weed/storage/store_ec.go:141-443).

Resolution order per interval (store_ec.go:207 readOneEcShardInterval):
local shard -> remote shard (locations cached from the master with
tiered TTL freshness, :248 cachedLookupEcShardLocations) -> reconstruct
from >= data_shards surviving shards fetched in parallel (:366
recoverOneRemoteEcShardInterval).  Reconstruction uses the CPU RS twin:
single-needle degraded reads are latency-bound, so the TPU batch path is
reserved for bulk rebuild (SURVEY §7 hard part 3).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..ops import rs_cpu, rs_native
from ..storage import types
from ..storage.erasure_coding import EcVolume
from ..storage.erasure_coding.ec_context import (LARGE_BLOCK_SIZE,
                                                 SMALL_BLOCK_SIZE)
from ..storage.erasure_coding.ec_volume import NotFoundError
from ..storage.needle import Needle
from .httpd import http_bytes, http_json

# tiered freshness (store_ec.go:248): incomplete -> 11s, full -> 37min,
# enough-to-read -> 7min
_TTL_INCOMPLETE = 11.0
_TTL_FULL = 37 * 60.0
_TTL_ENOUGH = 7 * 60.0


class _ShardLocationCache:
    def __init__(self):
        self.locations: dict[int, list[str]] = {}
        self.refreshed = 0.0
        self.lock = threading.Lock()


class EcReader:
    """Serves needle reads over an EcVolume whose shards may live on
    other servers; owned by the volume server."""

    def __init__(self, master: str, self_url: str,
                 security_headers=None):
        self.master = master
        self.self_url = self_url
        # callable -> admin headers for cross-server shard reads (the
        # owning volume server's per-instance security config; the
        # global-config auto-attach covers the default case)
        self._security_headers = security_headers or (lambda: {})
        self._caches: dict[int, _ShardLocationCache] = {}
        self._codecs: dict[tuple[int, int], object] = {}
        self._pool = ThreadPoolExecutor(max_workers=14)

    # -- public -----------------------------------------------------------

    def read_needle(self, ev: EcVolume, needle_id: int,
                    cookie: int | None = None) -> Needle:
        """store_ec.go:141 ReadEcShardNeedle: the local read path with
        this reader's scatter/reconstruct interval resolution."""
        return ev.read_needle_with(
            lambda iv: self._read_interval(ev, needle_id, iv),
            needle_id, cookie=cookie)

    # -- interval resolution ---------------------------------------------

    def _read_interval(self, ev: EcVolume, needle_id: int, iv) -> bytes:
        sid, off = iv.to_shard_id_and_offset(
            LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, ev.ctx.data_shards)
        # 1. local
        shard = ev.shards.get(sid)
        if shard is not None:
            with ev.lock:
                return shard.read_at(off, iv.size)
        # 2. remote direct
        locs = self._shard_locations(ev)
        for url in locs.get(sid, []):
            data = self._remote_read(url, ev.id, sid, off, iv.size)
            if data is not None:
                return data
        # 3. reconstruct from survivors — the DEGRADED read path: make
        # it countable (the SLO difference between "one dead peer" and
        # "every read pays a d-way fan-out" lives in this counter)
        from .. import stats
        stats.PROCESS.counter_add(
            "ec_degraded_reads_total", 1.0,
            help_text="needle reads served by interval reconstruction "
                      "instead of a direct shard read", vid=ev.id)
        return self._recover_interval(ev, sid, off, iv.size)

    def _remote_read(self, url: str, vid: int, sid: int, offset: int,
                     size: int) -> bytes | None:
        """volume_server.proto:101 VolumeEcShardRead.  Returns None on
        any transport failure — a dead shard server must degrade to
        reconstruction, not surface a 500 (store_ec.go falls through).
        Consults the peer's circuit breaker first (an open peer is
        skipped without burning a timeout) and cache-busts this
        volume's shard locations on failure so the NEXT read re-looks
        up placement instead of retrying the same dead peer until the
        37-minute TTL expires."""
        if url == self.self_url:
            return None
        from ..util import retry as _retry
        if not _retry.peer_available(url):
            self._note_failover(url)
            return None
        try:
            status, body, _ = http_bytes(
                "GET",
                f"{url}/admin/ec/shard_read?volumeId={vid}&shardId={sid}"
                f"&offset={offset}&size={size}", timeout=10,
                headers=self._security_headers())
        except OSError:
            self._note_failover(url)
            self._bust_locations(vid, url)
            return None
        if status == 200 and len(body) == size:
            return body
        self._note_failover(url)
        return None

    def _note_failover(self, url: str) -> None:
        from .. import stats
        stats.PROCESS.counter_add(
            "ec_read_source_failovers_total", 1.0,
            help_text="EC reads that abandoned a shard source "
                      "(transport failure, short body, open breaker)",
            peer=url)

    def _bust_locations(self, vid: int, dead_url: str) -> None:
        """Drop a dead peer from this volume's cached shard locations
        and expire the cache: the next read refreshes placement from
        the master rather than re-timing-out on the same peer."""
        cache = self._caches.get(vid)
        if cache is None:
            return
        with cache.lock:
            for sid, urls in list(cache.locations.items()):
                if dead_url in urls:
                    cache.locations[sid] = \
                        [u for u in urls if u != dead_url]
            cache.refreshed = 0.0

    def _recover_interval(self, ev: EcVolume, missing_sid: int,
                          offset: int, size: int) -> bytes:
        """store_ec.go:366: parallel reads of the same range from every
        other shard, then ReconstructData."""
        total = ev.ctx.total
        d = ev.ctx.data_shards
        locs = self._shard_locations(ev, force_if_missing=missing_sid)
        bufs = np.zeros((total, size), dtype=np.uint8)
        present = [False] * total

        def fetch(sid: int):
            if sid == missing_sid:
                return sid, None
            shard = ev.shards.get(sid)
            if shard is not None:
                with ev.lock:
                    return sid, shard.read_at(offset, size)
            for url in locs.get(sid, []):
                data = self._remote_read(url, ev.id, sid, offset, size)
                if data is not None:
                    return sid, data
            return sid, None

        for sid, data in self._pool.map(fetch, range(total)):
            if data is not None and len(data) == size:
                bufs[sid] = np.frombuffer(data, dtype=np.uint8)
                present[sid] = True
        if sum(present) < d:
            raise NotFoundError(
                f"volume {ev.id}: only {sum(present)} shards reachable, "
                f"need {d} to recover shard {missing_sid}")
        codec = self._codec(d, ev.ctx.parity_shards)
        # intervals only ever target data shards (block_index %
        # data_shards < d), so ReconstructData semantics apply
        # (store_ec.go:435): skip regenerating missing parity rows.
        rec = codec.reconstruct(bufs, present, data_only=True)
        return rec[missing_sid].tobytes()

    # -- shard location cache (store_ec.go:248) ---------------------------

    def _shard_locations(self, ev: EcVolume,
                         force_if_missing: int | None = None
                         ) -> dict[int, list[str]]:
        cache = self._caches.setdefault(ev.id, _ShardLocationCache())
        with cache.lock:
            n = len(cache.locations)
            age = time.monotonic() - cache.refreshed
            fresh = ((n < ev.ctx.data_shards and age < _TTL_INCOMPLETE) or
                     (n == ev.ctx.total and age < _TTL_FULL) or
                     (ev.ctx.data_shards <= n < ev.ctx.total and
                      age < _TTL_ENOUGH))
            if force_if_missing is not None and \
                    force_if_missing not in cache.locations:
                fresh = fresh and age < _TTL_INCOMPLETE
            if not fresh:
                from ..operation import master_json
                try:
                    r = master_json(
                        self.master, "GET",
                        f"/dir/ec_lookup?volumeId={ev.id}", timeout=5)
                except OSError:
                    r = {}
                locs: dict[int, list[str]] = {}
                for entry in r.get("shardIdLocations", []):
                    for sid in entry["shardIds"]:
                        locs.setdefault(sid, []).append(entry["url"])
                if locs:
                    cache.locations = locs
                    cache.refreshed = time.monotonic()
            return dict(cache.locations)

    def _codec(self, d: int, p: int):
        """Native C++ engine when built (the latency path deserves it);
        numpy twin otherwise."""
        key = (d, p)
        if key not in self._codecs:
            if rs_native.available():
                self._codecs[key] = rs_native.ReedSolomonNative(d, p)
            else:
                self._codecs[key] = rs_cpu.ReedSolomonCPU(d, p)
        return self._codecs[key]

    def forget(self, vid: int) -> None:
        self._caches.pop(vid, None)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
