"""WebDAV gateway over the filer (weed/server/webdav_server.go — the
reference serves golang.org/x/net/webdav on a filer-backed FileSystem).

Implemented verbs (RFC 4918 level 1 + MOVE/COPY):
  OPTIONS                — capability advertisement (DAV: 1)
  PROPFIND (Depth 0/1)   — multistatus with resourcetype/length/dates
  GET / HEAD             — ranged file reads via the filer
  PUT                    — file upload (auto-chunked by the filer)
  MKCOL                  — directory creation
  DELETE                 — file / recursive directory delete
  MOVE                   — atomic rename (filer AtomicRenameEntry)
  COPY                   — read-through copy
"""

from __future__ import annotations

import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate

from ..filer import Entry, Filer
from ..filer.filechunks import total_size
from .httpd import HttpServer, Request

DAV_NS = "DAV:"


def _href(path: str, is_dir: bool) -> str:
    out = urllib.parse.quote(path)
    if is_dir and not out.endswith("/"):
        out += "/"
    return out


def _prop_response(parent: ET.Element, entry: Entry) -> None:
    resp = ET.SubElement(parent, f"{{{DAV_NS}}}response")
    ET.SubElement(resp, f"{{{DAV_NS}}}href").text = \
        _href(entry.full_path, entry.is_directory)
    propstat = ET.SubElement(resp, f"{{{DAV_NS}}}propstat")
    prop = ET.SubElement(propstat, f"{{{DAV_NS}}}prop")
    rt = ET.SubElement(prop, f"{{{DAV_NS}}}resourcetype")
    if entry.is_directory:
        ET.SubElement(rt, f"{{{DAV_NS}}}collection")
    else:
        ET.SubElement(prop, f"{{{DAV_NS}}}getcontentlength").text = \
            str(total_size(entry.chunks))
        mime = entry.attributes.mime or "application/octet-stream"
        ET.SubElement(prop, f"{{{DAV_NS}}}getcontenttype").text = mime
    ET.SubElement(prop, f"{{{DAV_NS}}}getlastmodified").text = \
        formatdate(entry.attributes.mtime, usegmt=True)
    ET.SubElement(prop, f"{{{DAV_NS}}}displayname").text = entry.name
    ET.SubElement(propstat, f"{{{DAV_NS}}}status").text = \
        "HTTP/1.1 200 OK"


class WebDavServer:
    def __init__(self, master: str, filer: Filer | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.filer = filer or Filer(master)
        self.http = HttpServer(host, port)
        self.http.fallback = self._dispatch

    def start(self) -> "WebDavServer":
        self.http.start()
        return self

    def stop(self) -> None:
        self.http.stop()

    @property
    def url(self) -> str:
        return self.http.url

    # -- dispatch ---------------------------------------------------------

    def _dispatch(self, req: Request):
        path = urllib.parse.unquote(req.path).rstrip("/") or "/"
        method = req.method
        if method == "OPTIONS":
            return 200, (b"", {"DAV": "1,2", "MS-Author-Via": "DAV",
                               "Allow": "OPTIONS, PROPFIND, GET, HEAD,"
                               " PUT, DELETE, MKCOL, MOVE, COPY"})
        if method == "PROPFIND":
            return self._propfind(req, path)
        if method in ("GET", "HEAD"):
            return self._get(req, path)
        if method == "PUT":
            entry = self.filer.write_file(
                path, req.body,
                mime=req.headers.get("Content-Type", ""))
            return 201, (b"", {"ETag":
                               f'"{entry.attributes.mtime}"'})
        if method == "MKCOL":
            if self.filer.find_entry(path) is not None:
                return 405, {"error": "already exists"}
            self.filer.create_entry(Entry(path, is_directory=True))
            return 201, b""
        if method == "DELETE":
            entry = self.filer.find_entry(path)
            if entry is None:
                return 404, b""
            self.filer.delete_entry(path, recursive=True)
            return 204, b""
        if method in ("MOVE", "COPY"):
            return self._move_copy(req, path, copy=(method == "COPY"))
        return 405, {"error": f"method {method} not allowed"}

    def _propfind(self, req: Request, path: str):
        entry = self.filer.find_entry(path)
        if entry is None:
            return 404, b""
        depth = req.headers.get("Depth", "1")
        root = ET.Element(f"{{{DAV_NS}}}multistatus")
        _prop_response(root, entry)
        if depth != "0" and entry.is_directory:
            last = ""
            while True:
                batch = self.filer.list_directory(
                    path, start_file=last, limit=1000)
                for child in batch:
                    _prop_response(root, child)
                if len(batch) < 1000:
                    break
                last = batch[-1].name
        ET.register_namespace("D", DAV_NS)
        body = b'<?xml version="1.0" encoding="utf-8"?>' + \
            ET.tostring(root)
        return 207, (body, "application/xml; charset=utf-8")

    def _get(self, req: Request, path: str):
        entry = self.filer.find_entry(path)
        if entry is None:
            return 404, b""
        if entry.is_directory:
            return 405, {"error": "is a collection; use PROPFIND"}
        size = total_size(entry.chunks)
        rng = req.headers.get("Range", "")
        offset, want = 0, None
        if rng.startswith("bytes="):
            try:
                lo, _, hi = rng[6:].partition("-")
                if lo:
                    offset = int(lo)
                    want = (int(hi) - offset + 1) if hi else None
                elif hi:
                    want = min(int(hi), size)
                    offset = size - want
                else:
                    raise ValueError(rng)
            except ValueError:
                offset, want = 0, None
                rng = ""
        if rng and (offset >= size or (want is not None and want <= 0)):
            # unsatisfiable range (RFC 9110 §15.5.17) — a fabricated
            # 206 with end < start would make resume-probing clients
            # (davfs2 HEAD+Range) conclude the resource is empty
            return 416, (b"", {"Content-Range": f"bytes */{size}"})
        length = min(want if want is not None else size - offset,
                     size - offset)
        data = b"" if req.method == "HEAD" else \
            self.filer.read_file(path, offset, want)
        mime = entry.attributes.mime or "application/octet-stream"
        headers = {"Content-Type": mime,
                   "Content-Length": str(length if rng or
                                         req.method == "HEAD"
                                         else len(data)),
                   "Last-Modified": formatdate(entry.attributes.mtime,
                                               usegmt=True)}
        if rng:
            headers["Content-Range"] = \
                f"bytes {offset}-{offset + length - 1}/{size}"
            return 206, (data, headers)
        return 200, (data, headers)

    def _move_copy(self, req: Request, path: str, copy: bool):
        dest = req.headers.get("Destination", "")
        if not dest:
            return 400, {"error": "missing Destination header"}
        # Destination is an absolute URL or absolute path
        parsed = urllib.parse.urlparse(dest)
        dst = urllib.parse.unquote(parsed.path).rstrip("/") or "/"
        overwrite = req.headers.get("Overwrite", "T") != "F"
        existing = self.filer.find_entry(dst)
        if existing is not None and not overwrite:
            return 412, {"error": "destination exists (Overwrite: F)"}
        src = self.filer.find_entry(path)
        if src is None:
            return 404, b""
        if copy:
            if src.is_directory:
                return 501, {"error": "COPY of collections "
                                      "not implemented"}
            data = self.filer.read_file(path)
            self.filer.write_file(dst, data,
                                  mime=src.attributes.mime)
        else:
            if existing is not None and not existing.is_directory:
                # rename replaces the destination ENTRY only; the old
                # file's chunks must be reclaimed or every
                # save-via-rename cycle leaks needles forever
                self.filer.delete_entry(dst)
            try:
                self.filer.rename(path, dst)
            except FileNotFoundError:
                return 404, b""
        return 204 if existing is not None else 201, b""
