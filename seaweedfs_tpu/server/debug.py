"""Debug/profiling plane (the analog of util/grace/pprof.go:16
StartDebugServer — every reference role can expose a localhost pprof
endpoint).

Routes (admin-gated when the security plane is on, see
httpd.is_admin_path):

  GET /debug/stacks            — every thread's current stack
  GET /debug/vars              — gc / thread / rss counters (expvar)
  GET /debug/profile?seconds=N — statistical sampling profile:
      samples sys._current_frames at ~10ms for N seconds and returns
      collated (frames -> sample count), most-sampled first — the
      Python stand-in for a CPU pprof.
  GET /debug/traces?request_id=R — spans of one trace from this
      process's ring buffer (tracing.py); without request_id the
      most recent spans (?limit=N, default 200).  The shell's
      `trace.show` fans this endpoint out across the cluster and
      merges the results into one tree.
  GET/POST /debug/faults — the failpoint plane (faults.py): GET lists
      armed sites + trigger counts; POST arms ({"spec": "..."} or the
      explicit {"site","action",...} form) or clears ({"clear": true
      or "site"}).  The chaos suite's runtime lever on every role.
  GET /debug/health — this process's per-peer circuit-breaker map and
      retry budget (util/retry); `trace.show` appends it so a chaos
      run is debuggable from the shell.
  GET/POST /debug/pprof — the sampling wall-clock profiler
      (profiling.Sampler): POST {"action": "start", "hz": N} arms it,
      {"action": "stop"} disarms and returns the final snapshot,
      {"action": "reset"} clears the folded table; GET returns the
      snapshot (?top=N limits the folded table,
      ?format=collapsed returns flamegraph.pl input as text/plain).
      Off by default; SEAWEEDFS_TPU_PROFILE_HZ arms it at boot.  The
      shell's `cluster.profile` arms every node, waits, and merges
      the folded stacks into one cluster-wide flame view.
  GET/POST /debug/slow — the flight recorder's ring
      (profiling.FlightRecorder): complete records of the tail —
      requests slower than the self-tracked p95 threshold, errored,
      deadline-exceeded, or QoS/brownout-shed — each with its span
      tree, per-stage wall+cpu split, deadline verdict and flight
      notes.  POST {"clear": true} empties it.  `cluster.slow` fans
      this out and merges by trace id across roles.
"""

from __future__ import annotations

import gc
import sys
import threading
import time
import traceback
from collections import Counter

from .httpd import HttpServer, Request


def install_debug_routes(http: HttpServer) -> None:
    http.route("GET", "/debug/stacks", _stacks)
    http.route("GET", "/debug/vars", _vars)
    http.route("GET", "/debug/profile", _profile)
    http.route("GET", "/debug/traces", _traces)
    http.route("GET", "/debug/faults", _faults_get)
    http.route("POST", "/debug/faults", _faults_post)
    http.route("GET", "/debug/health", _health)
    http.route("GET", "/debug/qos", _qos_get)
    http.route("POST", "/debug/qos", _qos_post)
    http.route("GET", "/debug/pprof", _pprof_get)
    http.route("POST", "/debug/pprof", _pprof_post)
    http.route("GET", "/debug/slow", _slow_get)
    http.route("POST", "/debug/slow", _slow_post)
    http.route("GET", "/debug/attribution", _attr_get)
    http.route("POST", "/debug/attribution", _attr_post)


def install_autopilot_routes(http: HttpServer, ap) -> None:
    """The SLO autopilot's runtime lever (autopilot.py, ISSUE 20),
    registered by the roles that run a loop (filer, volume).  GET is
    the controller's whole state — knobs with bounds and current
    values, plane-guard state, the bounded action log.  POST:
    {"enabled": bool} flips the loop; {"knob": name, "value": v}
    force-actuates ONE knob through the registry (still
    bounds-clamped — the lever is an operator override, not a bounds
    escape); {"tick": true} runs one synchronous control step (chaos
    tests pin the cadence with it)."""
    def _ap_get(req: Request):
        return 200, ap.snapshot()

    def _ap_post(req: Request):
        b = req.json()
        try:
            if "enabled" in b:
                ap.set_enabled(bool(b["enabled"]))
            if "knob" in b:
                name = str(b["knob"])
                if name not in ap.actuators:
                    return 400, {"error": f"unknown knob {name!r}"}
                ap.actuate(name, float(b["value"]),
                           "debug lever", force=True)
            if b.get("tick"):
                ap.tick()
        except (TypeError, ValueError, KeyError) as e:
            return 400, {"error": str(e)}
        return 200, ap.snapshot()

    http.route("GET", "/debug/autopilot", _ap_get)
    http.route("POST", "/debug/autopilot", _ap_post)
    from .. import profiling
    profiling.maybe_autostart()  # SEAWEEDFS_TPU_PROFILE_HZ boot arming
    profiling.maybe_start_sched_probe()  # gil_wait_ratio gauge


def _pprof_get(req: Request):
    from .. import profiling
    s = profiling.sampler()
    if req.query.get("format") == "collapsed":
        return 200, (s.collapsed().encode(), "text/plain")
    try:
        top = int(req.query.get("top", 0))
    except ValueError:
        top = 0
    return 200, s.snapshot(top=top)


def _pprof_post(req: Request):
    from .. import profiling
    s = profiling.sampler()
    b = req.json()
    action = str(b.get("action", ""))
    if action == "start":
        hz = b.get("hz")
        try:
            hz = float(hz) if hz is not None else None
        except (TypeError, ValueError):
            return 400, {"error": f"bad hz {b.get('hz')!r}"}
        started = s.start(hz)
        return 200, {"running": s.running, "hz": s.hz,
                     "started": started}
    if action == "stop":
        s.stop()
        return 200, s.snapshot()
    if action == "reset":
        s.reset()
        return 200, s.snapshot()
    return 400, {"error": "body needs action: start|stop|reset"}


def _slow_get(req: Request):
    """The flight recorder's ring (profiling.FlightRecorder): the
    captured slow/error/deadline/shed requests with their span trees,
    stage wall+cpu splits, deadline verdicts and flight notes.
    `weed shell cluster.slow` fans this endpoint out and merges
    records by trace id across roles."""
    from .. import profiling
    # drain the native-plane flight rings first (ISSUE 18): a scrape
    # must see plane requests that finished since the last drainer
    # tick, or cluster.slow races the tick
    profiling.run_scrape_hooks()
    return 200, profiling.flight_recorder().snapshot()


def _slow_post(req: Request):
    """{"clear": true} empties the ring and latency history (chaos
    runs reset between scenarios the way /debug/faults does)."""
    from .. import profiling
    if req.json().get("clear"):
        profiling.flight_recorder().reset()
        return 200, profiling.flight_recorder().snapshot()
    return 400, {"error": "body needs clear: true"}


def _attr_get(req: Request):
    from .. import profiling
    scope = profiling.attribution_disarmed()
    return 200, {"disarmed": scope is not None,
                 "scope": scope or "",
                 "drainEnabled": profiling.plane_drain_enabled()}


def _attr_post(req: Request):
    """{"disarmed": true|false, "scope": "all"|"plane"|"drain"} —
    runtime kill/restore switch for the cost-attribution plane in
    this process, no restart needed.  Scope "all" (default) disarms
    everything including the wall-stage decomposition; "plane"
    disarms only the ISSUE 15 additions (CPU clocks, flight
    recorder); "drain" disarms only the ISSUE 18 native-plane
    flight-record drain (records keep accumulating C-side and age
    off the ring).  Also the lever behind bench.py's within-cluster
    overhead A/Bs: separate clusters cannot resolve a ~1% cost under
    arm-to-arm boot noise, alternating armed/disarmed traffic
    windows on ONE cluster can."""
    from .. import profiling
    b = req.json()
    if "disarmed" not in b:
        return 400, {"error": "body needs disarmed: true|false"}
    scope_in = str(b.get("scope", "all"))
    if scope_in == "drain":
        profiling.set_plane_drain_disarmed(bool(b["disarmed"]))
    else:
        profiling.set_attribution_disarmed(
            bool(b["disarmed"]), scope=scope_in)
    scope = profiling.attribution_disarmed()
    return 200, {"disarmed": scope is not None,
                 "scope": scope or "",
                 "drainEnabled": profiling.plane_drain_enabled()}


def _faults_get(req: Request):
    from .. import faults
    return 200, {"armed": faults.armed(),
                 "triggered": faults.triggered()}


def _faults_post(req: Request):
    from .. import faults
    b = req.json()
    clear = b.get("clear")
    if clear:
        faults.disarm(None if clear is True else str(clear))
        return 200, {"armed": faults.armed()}
    try:
        if "spec" in b:
            n = faults.arm_spec(str(b["spec"]))
        elif "site" in b:
            faults.arm(
                str(b["site"]), str(b.get("action", "error")),
                p=float(b.get("p", 1.0)),
                n=None if b.get("n") is None else int(b["n"]),
                ms=float(b.get("ms", 0.0)),
                seed=None if b.get("seed") is None else int(b["seed"]),
                match=str(b.get("match", "")))
            n = 1
        else:
            return 400, {"error": "body needs spec/site/clear"}
    except ValueError as e:
        return 400, {"error": str(e)}
    return 200, {"armedCount": n, "armed": faults.armed()}


def _qos_get(req: Request):
    from .. import qos
    snap = qos.controller().snapshot()
    snap["throttle"] = qos.throttle().snapshot()
    return 200, snap


def _qos_post(req: Request):
    """The QoS plane's runtime lever (qos.py), mirroring
    /debug/faults: set per-tenant limits ({"tenant": ..., "rps": ...,
    "burst": ..., "inflightMb": ...}; tenant "default"/"*" sets the
    default, {"remove": name} drops one), flip enforcement
    ({"enabled": bool}), retune the EC feedback throttle
    ({"sloP99Ms": ..., "paceMinMs"/"paceMaxMs"/"checkIntervalMs"}),
    or reset everything ({"clear": true}).  Responds with the same
    snapshot GET serves, so a lever call round-trips."""
    from .. import qos
    b = req.json()
    ctl = qos.controller()
    try:
        if b.get("clear"):
            qos.configure(None)
            # a pace forced via the paceMs big-red-button has no
            # watcher thread to decay it once the config is inert —
            # "reset everything" must include it
            qos.throttle().set_pace(0.0)
        if "enabled" in b:
            ctl.set_enabled(bool(b["enabled"]))
        if b.get("remove"):
            ctl.set_tenant(str(b["remove"]), None)
        if b.get("tenant"):
            ctl.set_tenant(str(b["tenant"]),
                           qos.TenantLimit.from_json(b))
        cfg = ctl.config()
        for key, attr in (("sloP99Ms", "slo_p99_ms"),
                          ("paceMinMs", "pace_min_ms"),
                          ("paceMaxMs", "pace_max_ms"),
                          ("checkIntervalMs", "check_interval_ms")):
            if key in b:
                setattr(cfg, attr, float(b[key]))
        if "sloP99Ms" in b:
            if cfg.slo_p99_ms <= 0:
                qos.throttle().set_pace(0.0)
            qos.throttle().maybe_start()
        if "paceMs" in b:               # direct pace override (tests /
            qos.throttle().set_pace(    # operator big-red-button)
                float(b["paceMs"]) / 1e3)
    except (TypeError, ValueError) as e:
        return 400, {"error": str(e)}
    return _qos_get(req)


def _health(req: Request):
    from ..util import retry
    return 200, {"peers": retry.health_snapshot(),
                 "retryBudgetRemaining": retry.budget_remaining()}


def _traces(req: Request):
    from .. import tracing
    rid = req.query.get("request_id", "")
    if rid:
        spans = tracing.spans_for(rid)
    else:
        spans = tracing.recent_spans(
            int(req.query.get("limit", 200)))
    return 200, {"requestId": rid, "spans": spans}


def _stacks(req: Request):
    out = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        out.extend(line.rstrip() for line in
                   traceback.format_stack(frame))
    return 200, ("\n".join(out).encode(), "text/plain")


def _vars(req: Request):
    rss_kb = 0
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss_kb = int(line.split()[1])
    except OSError:
        pass
    counts = gc.get_count()
    return 200, {
        "threads": threading.active_count(),
        "gcCounts": list(counts),
        "gcObjects": len(gc.get_objects()),
        "rssKb": rss_kb,
        "uptimeHint": time.process_time(),
    }


def _profile(req: Request):
    seconds = min(float(req.query.get("seconds", 2)), 30.0)
    interval = 0.01
    samples: Counter = Counter()
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    n = 0
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < 24:
                stack.append(f"{f.f_code.co_filename.rsplit('/', 1)[-1]}"
                             f":{f.f_lineno}:{f.f_code.co_name}")
                f = f.f_back
            samples[";".join(reversed(stack))] += 1
        n += 1
        time.sleep(interval)
    lines = [f"samples: {n} over {seconds}s @ {interval * 1000:.0f}ms"]
    for stack, count in samples.most_common(50):
        lines.append(f"{count:6d}  {stack}")
    return 200, ("\n".join(lines).encode(), "text/plain")
