"""Native HTTP write plane wrapper (native/write_plane.cc).

The volume server's second implementation of the needle-WRITE surface
— the sibling of server/read_plane.py: a C++ epoll loop that recvs
the framed upload, serializes the v3 needle record, appends to the
.dat fd it owns, and acks — no Python, no GIL, on the hot path.
arXiv:1709.05365's host-side per-request overhead, removed at the
source.

Contract highlights (details in write_plane.cc):

* While a volume is attached, the plane owns the .dat TAIL.  Python
  appends (overwrites, tombstones, replication, repair) route through
  `append()` — the same per-volume mutex — so records never
  interleave.
* Completed native appends are journaled; `drain()` hands them back
  for NeedleMap + .idx application (the .dat is the WAL, the .idx a
  checkpoint, `Volume._replay_dat_tail` recovers after a crash).
* Anything non-plain — named/mimed uploads, authenticated writes,
  overwrites of seen keys, unregistered volumes — answers 404 and the
  client falls back to the Python port (the read plane's contract).
* Durability: write(2) is page-cache durable before the ack (the
  group-commit flush guarantee); on the -fsync tier acks park on a
  flush epoch that the handshake thread resolves by running the
  volume's CommitBarrier — group commit across the language boundary.

Failure contract: every method degrades to "plane unavailable"
(False/-1/[]) rather than raising into the write path; the volume
server keeps serving through Python exactly as if the .so had never
built.
"""

from __future__ import annotations

import ctypes
import threading
from collections import namedtuple

from .. import native
from ..util import wlog

# ack latency histogram bucket bounds (write_plane.cc kLatBuckets), in
# seconds — rendered on /metrics as write_plane_ack_seconds
ACK_BUCKETS_S = (1e-6, 2e-6, 5e-6, 1e-5, 2e-5, 5e-5, 1e-4, 2e-4,
                 5e-4, 1e-3, 2e-3, 5e-3, 1e-2, 2e-2, 5e-2, 1e-1, 1.0)

NativeWrite = namedtuple(
    "NativeWrite", "key offset append_ns vid cookie size data_len")

# flight-record label tables (write_plane.cc kRecStageNames /
# kRecFallbackNames — the SWFS019 lint pins the literals in sync)
RECORD_STAGES = ("recv", "append", "index", "ack")
RECORD_FALLBACKS = ("none", "not_plain", "unregistered", "seen_key",
                    "journal_full", "io_error")


class WritePlane:
    """One native write-plane server bound to <host>:<ephemeral>.

    `on_tick` (pump thread, ~40Hz) lets the owner drain attached
    volumes' journals into their needle maps; `on_epoch(vid, epoch)`
    (handshake thread) must make the volume's acked bytes as durable
    as its CommitBarrier promises — the wrapper always calls
    wp_epoch_done afterwards, releasing the parked acks."""

    _DRAIN_CAP = 4096

    def __init__(self, host: str = "127.0.0.1", on_tick=None,
                 on_epoch=None, tick_interval: float = 0.025):
        self._lib = native.load_write_plane()
        if self._lib is None:
            raise RuntimeError("native write plane unavailable")
        port = ctypes.c_int(0)
        self._h = self._lib.wp_start(host.encode(), 0,
                                     ctypes.byref(port))
        if self._h < 0:
            raise RuntimeError("write plane failed to start")
        self.host = host
        self.port = port.value
        self._on_tick = on_tick
        self._on_epoch = on_epoch
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._drainer = None
        self._epoch_started = False
        self._epoch_lock = threading.Lock()
        if on_tick is not None:
            t = threading.Thread(target=self._pump_loop,
                                 args=(tick_interval,), daemon=True)
            t.start()
            self._threads.append(t)

    # -- volume attachment (called from storage.Volume under its lock) --

    def add_volume(self, vid: int, dat_path: str, tail: int,
                   last_append_ns: int, fsync: bool) -> bool:
        if fsync:
            # the handshake thread exists only once an -fsync volume
            # can park acks on a flush epoch: most deployments (and
            # every default-tier test teardown) never pay its
            # wait-loop join at stop()
            self._ensure_epoch_thread()
        try:
            return self._lib.wp_add_volume(
                self._h, vid, dat_path.encode(), tail,
                last_append_ns, 1 if fsync else 0) == 0
        except OSError:
            return False

    def _ensure_epoch_thread(self) -> None:
        with self._epoch_lock:
            if self._epoch_started:
                return
            self._epoch_started = True
            t = threading.Thread(target=self._epoch_loop, daemon=True)
            t.start()
            self._threads.append(t)

    def mark_keys(self, vid: int, keys) -> None:
        """Seed the plane's seen-key fallback set.  `keys` is any
        iterable of needle ids; array.array avoids materializing a
        second full Python list for multi-million-needle volumes."""
        import array
        a = array.array("Q", keys)
        if not a:
            return
        buf = (ctypes.c_ulonglong * len(a)).from_buffer(a)
        self._lib.wp_mark_keys(self._h, vid, buf, len(a))

    def arm(self, vid: int) -> bool:
        """Open the volume for native HTTP writes — strictly after
        mark_keys, or an overwrite could slip past the seen-key
        fallback in the handshake window."""
        return self._lib.wp_arm(self._h, vid) == 0

    def remove_volume(self, vid: int) -> None:
        self._lib.wp_remove_volume(self._h, vid)

    def append(self, vid: int, key: int, record: bytes,
               append_ns: int) -> int:
        """Append a fully-serialized record through the plane's tail
        mutex; returns the byte offset or -1 (not attached)."""
        return self._lib.wp_append(self._h, vid, key, record,
                                   len(record), append_ns)

    def drain(self, vid: int) -> "list[NativeWrite]":
        out: list[NativeWrite] = []
        buf = (native.WpEntry * self._DRAIN_CAP)()
        while True:
            n = self._lib.wp_drain(self._h, vid, buf, self._DRAIN_CAP)
            for i in range(n):
                e = buf[i]
                out.append(NativeWrite(e.key, e.offset, e.append_ns,
                                       e.vid, e.cookie, e.size,
                                       e.data_len))
            if n < self._DRAIN_CAP:
                return out

    def pending(self, vid: int) -> int:
        return self._lib.wp_pending(self._h, vid)

    # -- telemetry ------------------------------------------------------

    def requests(self) -> int:
        return self._lib.wp_requests(self._h)

    def fallbacks(self) -> int:
        return self._lib.wp_fallbacks(self._h)

    def ack_histogram(self) -> "tuple[list[int], int, float]":
        """(cumulative bucket counts aligned with ACK_BUCKETS_S + an
        +Inf cell, total count, sum seconds)."""
        out = (ctypes.c_ulonglong * 20)()
        cells = self._lib.wp_latency(self._h, out)
        buckets = [int(out[i]) for i in range(cells)]
        return buckets, int(out[cells]), out[cells + 1] / 1e9

    # -- flight records (ISSUE 18) --------------------------------------

    def drain_records(self, sink=None, cap: int = 512):
        """Pull the plane's flight ring (see native.drain_plane_records
        for the sink-vs-list contract).  Single-consumer: concurrent
        pulls must be serialized by the owning PlaneRecordDrainer."""
        if self._h < 0:
            return [] if sink is None else 0
        return native.drain_plane_records(self._lib, "wp", self._h,
                                          sink, cap)

    def records_dropped(self) -> int:
        return int(self._lib.wp_records_dropped(self._h)) \
            if self._h >= 0 else 0

    def start_record_drain(self, tracker=None,
                           metrics=None) -> "object":
        """Start the flight-record drainer (tick + scrape hook);
        idempotent.  Returns the profiling.PlaneRecordDrainer."""
        if getattr(self, "_drainer", None) is not None:
            return self._drainer
        from .. import profiling
        sink = profiling.PlaneRecordSink(
            "volume", "write", "POST", RECORD_STAGES,
            RECORD_FALLBACKS, tracker=tracker, metrics=metrics)
        self._drainer = profiling.PlaneRecordDrainer(
            sink, lambda s: self.drain_records(sink=s),
            self.records_dropped).start()
        return self._drainer

    # -- background threads ---------------------------------------------

    def _pump_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                self._on_tick()
            except Exception:  # noqa: SWFS004 — journal upkeep must
                pass           # never kill the pump
        # final tick so a stop() mid-window leaves nothing undrained
        try:
            self._on_tick()
        except Exception:  # noqa: SWFS004
            pass

    def _epoch_loop(self) -> None:
        vid = ctypes.c_uint(0)
        epoch = ctypes.c_ulonglong(0)
        while not self._stop.is_set():
            got = self._lib.wp_wait_epoch(self._h, 200,
                                          ctypes.byref(vid),
                                          ctypes.byref(epoch))
            if not got:
                continue
            try:
                if self._on_epoch is not None:
                    self._on_epoch(vid.value, epoch.value)
            except Exception as e:  # noqa: BLE001 — parked acks must
                # be released even when the barrier helper dies; the
                # bytes are page-cache durable regardless
                wlog.warning(f"write plane epoch flush failed: {e!r}")
            finally:
                self._lib.wp_epoch_done(self._h, vid.value,
                                        epoch.value)

    def stop(self) -> None:
        """Threads first, then the native server: wp_stop frees the
        Server object, so no wrapper thread may still be inside a
        wp_* call when it runs."""
        if self._h < 0:
            return
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        if getattr(self, "_drainer", None) is not None:
            self._drainer.stop()
        self._lib.wp_stop(self._h)
        self._h = -1
