"""Minimal threaded HTTP server + JSON routing used by all roles.

Python-idiomatic stand-in for the reference's mux+gRPC server plumbing
(weed/server/*): handlers are (method, path-prefix) routes returning
(status, payload).  Bodies are JSON for control endpoints and raw bytes
for the data path.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler):
        # hot-path parse: one partition instead of a full urlparse.
        # Targets are origin-form (RFC 9112 §3.2.1) except the
        # absolute-form a forward proxy may send (§3.2.2 requires
        # accepting it) — strip scheme+authority for that rare shape
        path, _, query = handler.path.partition("?")
        if path[:4] == "http" and "://" in path[:8]:
            rest = path.split("://", 1)[1]
            slash = rest.find("/")
            path = rest[slash:] if slash >= 0 else "/"
        self.method = handler.command
        self.path = path
        self.remote_ip = handler.client_address[0]
        self._raw_query = query
        self._query: "dict[str, str] | None" = None
        self.headers = handler.headers
        self._handler = handler
        self._body: bytes | None = None

    @property
    def query(self) -> "dict[str, str]":
        """Parsed query params, lazily: the hot data path (needle
        POSTs, filer PUTs) usually carries none, and parse_qs per
        request was measurable funnel overhead.  keep_blank_values:
        S3-style marker params (?uploads=, ?delete=) must survive
        parsing."""
        if self._query is None:
            self._query = {
                k: v[0] for k, v in urllib.parse.parse_qs(
                    self._raw_query, keep_blank_values=True).items()} \
                if self._raw_query else {}
        return self._query

    @property
    def body(self) -> bytes:
        if self._body is None:
            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                # RFC 9112 §7.1 — curl -T and many WebDAV clients
                # stream uploads chunked with no Content-Length
                self._body = self._read_chunked()
            else:
                length = int(self.headers.get("Content-Length") or 0)
                self._body = self._handler.rfile.read(length) \
                    if length else b""
        return self._body

    def _read_chunked(self) -> bytes:
        rfile = self._handler.rfile
        out = bytearray()
        while True:
            size_line = rfile.readline(1024).strip()
            try:
                size = int(size_line.split(b";")[0], 16)
            except ValueError:
                # malformed framing: the stream position is unknown —
                # poison-proof the connection by closing it after this
                # response
                self._handler.close_connection = True
                break
            if size == 0:
                # drain optional trailers up to the blank line
                while True:
                    line = rfile.readline(1024)
                    if line in (b"\r\n", b"\n", b""):
                        break
                break
            out += rfile.read(size)
            rfile.readline(8)  # CRLF after each chunk
        return bytes(out)

    def json(self) -> dict:
        return json.loads(self.body or b"{}")

    def stream_body(self, chunk_size: int = 4 << 20):
        """Yield the request body in chunks without buffering it whole
        (the bulk-data receive path: a 30GB volume file must stream to
        disk, volume_server.proto:69 CopyFile / ReceiveFile), for both
        Content-Length and chunked framing.  After clean exhaustion
        `self.body` is b"" so the dispatcher's drain is a no-op; while
        streaming, the connection is marked close-on-response so a
        handler that fails MID-stream (ENOSPC) can never leave unread
        body bytes to be parsed as the next request on a keep-alive
        connection.  Mutually exclusive with touching `.body` first."""
        if self._body is not None:
            # body already buffered (small request): yield it once
            if self._body:
                yield self._body
            return
        self._body = b""
        # abandoned-generator safety: assume poisoned until proven
        # fully drained
        self._handler.close_connection = True
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            yield from self._stream_chunked(chunk_size)
            return
        length = int(self.headers.get("Content-Length") or 0)
        remaining = length
        while remaining > 0:
            chunk = self._handler.rfile.read(min(chunk_size, remaining))
            if not chunk:
                raise IOError(
                    f"short body: {remaining} of {length} bytes missing")
            remaining -= len(chunk)
            yield chunk
        self._handler.close_connection = False

    def _stream_chunked(self, chunk_size: int):
        """Chunk-at-a-time RFC 9112 §7.1 parser: unlike _read_chunked
        (small control bodies) nothing is accumulated, so chunked bulk
        uploads (`curl -T`) stream with bounded memory too."""
        rfile = self._handler.rfile
        while True:
            size_line = rfile.readline(1024).strip()
            try:
                size = int(size_line.split(b";")[0], 16)
            except ValueError:
                raise IOError(f"malformed chunk framing: "
                              f"{size_line[:64]!r}") from None
            if size == 0:
                while True:  # drain optional trailers
                    line = rfile.readline(1024)
                    if line in (b"\r\n", b"\n", b""):
                        break
                break
            remaining = size
            while remaining > 0:
                piece = rfile.read(min(chunk_size, remaining))
                if not piece:
                    raise IOError("short chunked body")
                remaining -= len(piece)
                yield piece
            rfile.readline(8)  # CRLF after each chunk
        self._handler.close_connection = False

    def drain(self, max_drain: int = 64 << 20) -> None:
        """Discard any unread body with bounded memory.  Oversized or
        chunked unread bodies are not read at all — the connection is
        closed instead (cheaper than consuming 30GB to keep one
        keep-alive socket)."""
        if self._body is not None:
            return
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        length = int(self.headers.get("Content-Length") or 0)
        if "chunked" in te or length > max_drain:
            self._body = b""
            self._handler.close_connection = True
            return
        remaining = length
        while remaining > 0:
            chunk = self._handler.rfile.read(min(1 << 20, remaining))
            if not chunk:
                self._handler.close_connection = True
                break
            remaining -= len(chunk)
        self._body = b""


Route = Callable[[Request], "tuple[int, object]"]


def normalize_payload(payload) -> "tuple[object, str, dict]":
    """One payload contract for both server fronts (threaded
    dispatcher below, async_front.py): handlers return dict/list
    (json), bytes, str, a (body, headers-dict) or (body, ctype) tuple,
    or a file-like body inside either tuple form.  Returns
    (body_or_stream, content_type, extra_headers)."""
    if isinstance(payload, (dict, list)):
        return json.dumps(payload).encode(), "application/json", {}
    if isinstance(payload, tuple):
        body, second = payload
        if isinstance(second, dict):
            extra = dict(second)
            ctype = extra.pop("Content-Type",
                              "application/octet-stream")
            return body, ctype, extra
        return body, second, {}
    body = payload if isinstance(payload, bytes) else \
        str(payload).encode()
    return body, "application/octet-stream", {}


def async_front_roles() -> "set[str]":
    """Roles served by the asyncio front (SEAWEEDFS_TPU_ASYNC_FRONT):
    "1"/"true" selects the filer gateway (the GIL-bound recv/route/
    assign/proxy funnel the front exists for); a comma list names
    roles explicitly (e.g. "filer,s3").  Empty/0 keeps every role on
    the threaded server."""
    import os
    v = os.environ.get("SEAWEEDFS_TPU_ASYNC_FRONT", "").strip().lower()
    if v in ("", "0", "false"):
        return set()
    if v in ("1", "true"):
        return {"filer"}
    return {r.strip() for r in v.split(",") if r.strip()}


class FileSlice:
    """A file-like over [current position, current position + size) of
    an open file, for streaming byte-range responses; closes the
    underlying file with it."""

    def __init__(self, f, size: int):
        self._f = f
        self._remaining = max(size, 0)

    def read(self, n: int = -1) -> bytes:
        if self._remaining <= 0:
            return b""
        if n < 0 or n > self._remaining:
            n = self._remaining
        chunk = self._f.read(n)
        self._remaining -= len(chunk)
        if not chunk:
            self._remaining = 0
        return chunk

    def close(self) -> None:
        self._f.close()


class HttpServer:
    """Routes: exact-path dict + prefix handlers + fallback.

    `reuse_port=True` binds with SO_REUSEPORT so N sibling processes
    can share one listener (the filer's pre-fork worker mode: the
    kernel distributes connections across the workers' accept
    queues — one gateway address, N GILs)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 reuse_port: bool = False):
        self.routes: dict[tuple[str, str], Route] = {}
        # pre-parsed prefix table, compiled at registration: method ->
        # [(prefix, handler)] longest-first.  Role servers used to
        # re-match their path prefixes inside the fallback on every
        # request; hot-path dispatch now resolves exact -> prefix ->
        # fallback from tables built once at boot.
        self.prefix_routes: dict[str, list] = {}
        self.fallback: Route | None = None
        # optional auth hook (security/guard.go Guard): returns None to
        # continue or a (status, payload) response to short-circuit
        self.guard: "Callable[[Request], tuple[int, object] | None] | None" \
            = None
        # optional QoS admission hook (qos.install): called before the
        # guard, returns (deny_response | None, release | None) — the
        # deny response carries Retry-After via the (body, headers)
        # payload form; release (in-flight byte accounting) runs when
        # the request finishes, success or failure
        self.admission: "Callable[[Request], tuple] | None" = None
        # observability hooks, set by the owning role server: `role`
        # labels this listener's server spans (tracing.py), `metrics`
        # receives the uniform request_seconds histogram (stats.py) —
        # one middleware, every role (master/volume/filer/s3 alike)
        self.role: str = ""
        self.metrics = None
        # in-flight request count for the cluster.top live view: the
        # gauge that distinguishes "idle" from "every handler thread
        # parked on a slow disk" at a glance
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # per-(method, code) pre-resolved request histogram observers
        # (stats.Metrics.observer, ROADMAP 1d): the middleware below
        # observes two histograms on EVERY request, and the label-set
        # space is tiny (~methods x codes) — resolve each cell once
        self._req_obs: dict = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # small request/response pairs (1KB needles) must not sit
            # in Nagle's 40ms window behind delayed ACKs
            disable_nagle_algorithm = True

            def _dispatch(self):
                req = Request(self)
                # request-id propagation (util/request_id): adopt the
                # caller's X-Request-ID or mint one at this edge; the
                # contextvar follows this handler thread so outbound
                # hops and log lines inherit it
                from ..util.request_id import HEADER as _RID_HEADER
                from ..util.request_id import ensure_request_id
                from .. import tracing
                from ..util import deadline as _dl
                rid = ensure_request_id(
                    req.headers.get(_RID_HEADER, ""))
                # deadline plane (util/deadline): adopt the caller's
                # remaining budget (or the operator default) BEFORE
                # anything spends time on this request; the adopt
                # also clears any stale deadline this reused handler
                # thread carried from its previous request.  The
                # maintenance plane only ever runs under an EXPLICIT
                # budget — a tenant-facing default must not 504 a
                # multi-minute volume copy or EC rebuild mid-pull.
                dl = _dl.adopt(req.headers.get(_dl.HEADER),
                               site=outer.role or "server",
                               allow_default=not req.path.startswith(
                                   ("/admin/", "/debug/")))
                # flight recorder (profiling.py): arm the per-request
                # notes dict so hedge/QoS/plane verdicts down the
                # handler chain have somewhere to land, and sample
                # this thread's CPU clock — wall − cpu at the end is
                # the request's GIL/lock/syscall wait.  The clock is
                # a trapped syscall on sandboxed kernels, so only
                # deadline-carrying and every-Nth budget-less
                # requests pay it (cpu_sample_every)
                from .. import profiling as _prof
                flight_on = _prof.recorder_enabled()
                if flight_on:
                    _prof.arm_flight_notes()
                cpu0 = time.thread_time() \
                    if _prof.cpu_attr_front(dl is not None) else None
                verdict = "ok"
                route = outer.routes.get((req.method, req.path))
                if route is None and outer.prefix_routes:
                    route = outer._prefix_route(req.method, req.path)
                # server span: trace id = request id, parent from the
                # caller's X-Trace-Parent (tracing.py); every role's
                # handler is wrapped by this one middleware
                _, parent_span = tracing.parse_traceparent(
                    req.headers.get(tracing.HEADER, ""))
                sp = tracing.start_span(
                    f"{req.method} {req.path}", role=outer.role,
                    parent=parent_span, trace_id=rid)
                if dl is not None:
                    sp.set("deadlineMs", int(dl.remaining() * 1e3))
                status = 0
                qos_release = None
                stream_cleanup = None   # file-like response body
                with outer._inflight_lock:
                    outer._inflight += 1
                    inflight = outer._inflight
                if outer.metrics is not None:
                    outer.metrics.gauge_set(
                        "requests_in_flight", inflight,
                        help_text="requests currently being handled")
                try:
                    # the span (and request_seconds) covers handler
                    # execution AND the response-body write: for the
                    # bulk serve paths (FileSlice sendfile) the write
                    # IS the dominant cost, and closing the span at
                    # handler return would record a multi-second
                    # stream as ~0ms
                    try:
                        # expired budget: 504 + Retry-After BEFORE
                        # admission spends a rate token, the guard
                        # verifies anything, or the handler queues —
                        # work the client already abandoned is shed
                        # at the cheapest possible point
                        throttled = None
                        if dl is not None and dl.expired():
                            throttled = _dl.expired_response(
                                f"{outer.role or 'server'}.ingress")
                            verdict = "deadline"
                        # QoS admission next (qos.py): an over-limit
                        # tenant is rejected with 503 + Retry-After
                        # BEFORE auth or routing spends anything on it
                        if throttled is None and \
                                outer.admission is not None:
                            throttled, qos_release = \
                                outer.admission(req)
                            if throttled is not None:
                                verdict = "shed"
                        if throttled is not None:
                            status, payload = throttled
                        elif (denied := outer.guard(req)
                              if outer.guard else None) is not None:
                            status, payload = denied
                        elif route is not None:
                            status, payload = route(req)
                        elif outer.fallback is not None:
                            status, payload = outer.fallback(req)
                        else:
                            status, payload = 404, \
                                {"error": "not found"}
                    except _dl.DeadlineExceeded as e:
                        # budget died mid-handler (an outbound hop's
                        # io_timeout raised): the honest status is
                        # 504, not a generic 500
                        status, payload = \
                            _dl.handler_exceeded_response()
                        verdict = "deadline"
                        sp.set_error(e)
                    except Exception as e:  # noqa: BLE001 — server
                        # must answer
                        status, payload = 500, {"error": str(e)}
                        verdict = "error"
                        sp.set_error(e)
                    # drain any unread request body: a handler that
                    # ignores its body (e.g. PROPFIND's XML) would
                    # otherwise leave the bytes in the keep-alive
                    # stream to be parsed as the NEXT request line,
                    # poisoning the connection.  Bounded: an unread
                    # 30GB upload (rejected by the guard or a 400)
                    # closes the connection instead of buffering —
                    # the drain must never re-introduce the
                    # whole-body OOM the streaming path exists to
                    # avoid.
                    try:
                        req.drain()
                    except Exception:  # noqa: BLE001 — close instead
                        self.close_connection = True
                    body, ctype, extra_headers = \
                        normalize_payload(payload)
                    if hasattr(body, "read"):
                        # register for the OUTER finally: a header
                        # write dying on a reset connection would
                        # otherwise skip the stream branch's own
                        # close, leaking the body's resources (fd,
                        # QoS in-flight bytes riding close()) —
                        # close() is idempotent on every body type
                        stream_cleanup = body
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header(_RID_HEADER, rid)
                    for hk, hv in extra_headers.items():
                        self.send_header(hk, hv)
                    if hasattr(body, "read"):
                        # file-like payload: stream without buffering
                        # (the bulk-data serve path).  Content-Length
                        # must be in extra_headers — these responses
                        # are never chunked.
                        self.end_headers()
                        try:
                            if req.method == "HEAD":
                                return
                            # sendfile(2) fast path for FileSlice
                            # needle reads: zero-copy kernel transfer
                            # from the .dat fd (the RDMA-sidecar
                            # idea's in-server sibling;
                            # socket.sendfile falls back to a send
                            # loop under TLS).  No mid-stream
                            # fallback: a partial sendfile that then
                            # re-sent bytes would corrupt the
                            # response, so errors close the
                            # connection instead.
                            f = getattr(body, "_f", None)
                            count = getattr(body, "_remaining", 0)
                            if f is not None and count > 0 and \
                                    hasattr(f, "fileno"):
                                try:
                                    self.wfile.flush()
                                    # offset defaults to 0, NOT the
                                    # file position — ranged needle
                                    # reads start mid-.dat
                                    self.connection.sendfile(
                                        f, offset=f.tell(),
                                        count=count)
                                except (OSError, ValueError):
                                    self.close_connection = True
                                return
                            while True:
                                chunk = body.read(1 << 20)
                                if not chunk:
                                    break
                                self.wfile.write(chunk)
                        finally:
                            body.close()
                        return
                    if "Content-Length" not in extra_headers:
                        self.send_header("Content-Length",
                                         str(len(body)))
                    self.end_headers()
                    if req.method != "HEAD":
                        self.wfile.write(body)
                finally:
                    if stream_cleanup is not None:
                        try:
                            stream_cleanup.close()
                        except OSError:
                            pass   # cleanup must never break a reply
                    if qos_release is not None:
                        try:
                            qos_release()
                        except Exception as e:  # noqa: BLE001 —
                            # accounting must never break a reply
                            from ..util import wlog
                            wlog.warning(
                                "qos release failed: %s", e,
                                component="qos")
                    sp.set("status", status)
                    sp.finish()
                    # this thread's CPU for the whole request —
                    # handler AND response write (the streamed-body
                    # paths run above on this same thread); None when
                    # this request didn't draw the attribution sample
                    cpu = (time.thread_time() - cpu0) \
                        if cpu0 is not None else None
                    with outer._inflight_lock:
                        outer._inflight -= 1
                        inflight = outer._inflight
                    if outer.metrics is not None:
                        outer.metrics.gauge_set(
                            "requests_in_flight", inflight)
                        cell = (req.method, status)
                        obs = outer._req_obs.get(cell)
                        if obs is None:
                            obs = outer._req_obs[cell] = (
                                outer.metrics.observer(
                                    "request_seconds",
                                    help_text="HTTP request handling "
                                              "latency",
                                    method=req.method,
                                    code=str(status)),
                                outer.metrics.observer(
                                    "request_cpu_seconds",
                                    buckets=_prof.STAGE_BUCKETS,
                                    help_text="handler-thread CPU per "
                                              "request (thread_time, "
                                              "sampled — see SEAWEED"
                                              "FS_TPU_CPU_SAMPLE); "
                                              "request_seconds minus "
                                              "this is GIL/lock/IO "
                                              "wait",
                                    method=req.method,
                                    code=str(status)))
                        obs[0](sp.duration)
                        if cpu is not None:
                            obs[1](cpu)
                    # ALWAYS drain the finished-track summary: tracks
                    # run whether or not the recorder is armed, and a
                    # summary left behind while disarmed would be
                    # attributed to a later request on this reused
                    # thread after re-arming
                    summary = _prof.take_last_summary()
                    if flight_on:
                        # AFTER sp.finish(): the capture pulls this
                        # trace's spans from the ring, and the server
                        # span must be among them
                        dl_doc = None
                        if dl is not None:
                            dl_doc = {
                                "budgetMs": int(dl.budget * 1e3),
                                "remainingMs":
                                    int(dl.remaining() * 1e3)}
                        try:
                            _prof.flight_recorder().observe(
                                role=outer.role or "server",
                                method=req.method, path=req.path,
                                status=status, wall_s=sp.duration,
                                cpu_s=cpu, verdict=verdict,
                                trace_id=rid, deadline=dl_doc,
                                stages=summary,
                                notes=_prof.take_flight_notes())
                        except Exception as e:  # noqa: BLE001 —
                            # observability must never break a reply
                            from ..util import wlog
                            wlog.warning(
                                "flight capture failed: %s", e,
                                component="profiling")

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _dispatch
            do_OPTIONS = _dispatch  # CORS preflight (S3 gateway)
            # WebDAV verbs (server/webdav_server.go)
            do_PROPFIND = do_MKCOL = do_MOVE = do_COPY = _dispatch
            do_PATCH = _dispatch  # TUS resumable uploads

            def log_message(self, *args):  # quiet
                pass

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True
            reuse_port = False   # set below before construction
            ssl_context = None  # set by start() when the TLS plane is on

            def server_bind(self):
                if self.reuse_port:
                    import socket as _socket
                    self.socket.setsockopt(_socket.SOL_SOCKET,
                                           _socket.SO_REUSEPORT, 1)
                super().server_bind()

            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                # established keep-alive connections, so stop() can
                # sever them: shutdown() only ends the ACCEPT loop,
                # and with pooled clients a "stopped" server would
                # otherwise keep serving (and acking writes!) over
                # existing sockets — breaking every stop-means-stop
                # invariant (e.g. the MQ broker's stop-then-flush)
                self._conns: set = set()
                self._conns_lock = threading.Lock()

            def finish_request(self, request, client_address):
                # TLS handshake PER CONNECTION in its own handler
                # thread — wrapping the listening socket would
                # handshake inside the single accept loop, letting one
                # silent client stall every role and wedge shutdown.
                # The raw socket joins _conns BEFORE the handshake so
                # stop() can sever a connection parked mid-handshake
                # (previously only handshaken sockets were severable),
                # and a failed handshake is counted but never reaches
                # _dispatch — the requests_in_flight gauge only ever
                # counts handshaken, dispatched requests.
                raw = request
                with self._conns_lock:
                    self._conns.add(raw)
                try:
                    if self.ssl_context is not None:
                        import ssl as _ssl
                        try:
                            request.settimeout(10)
                            request = self.ssl_context.wrap_socket(
                                request, server_side=True)
                            request.settimeout(None)
                        except (_ssl.SSLError, OSError) as e:
                            from ..stats import PROCESS
                            PROCESS.counter_add(
                                "tls_handshake_failures_total", 1.0,
                                help_text="inbound TLS handshakes "
                                          "that never completed",
                                reason=type(e).__name__)
                            try:
                                request.close()
                            except OSError:
                                pass
                            return
                        with self._conns_lock:
                            # track the wrapped socket: close() on it
                            # tears down the TLS layer AND the raw fd
                            self._conns.discard(raw)
                            self._conns.add(request)
                    super().finish_request(request, client_address)
                finally:
                    with self._conns_lock:
                        self._conns.discard(request)
                        self._conns.discard(raw)

            def close_established(self):
                import socket as _socket
                with self._conns_lock:
                    conns = list(self._conns)
                for c in conns:
                    try:
                        c.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        c.close()
                    except OSError:
                        pass

            def handle_error(self, request, client_address):
                # a client (or close_established) dropping the socket
                # mid-response is normal teardown, not a stack trace
                import sys as _sys
                exc = _sys.exc_info()[1]
                if isinstance(exc, (BrokenPipeError,
                                    ConnectionResetError,
                                    ConnectionAbortedError)):
                    return
                super().handle_error(request, client_address)

        Server.reuse_port = bool(reuse_port)
        self._httpd = Server((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None
        self._async = None   # asyncio front, when selected (start())

    def route(self, method: str, path: str, fn: Route) -> None:
        self.routes[(method, path)] = fn

    def route_prefix(self, method: str, prefix: str, fn: Route) -> None:
        """Register a handler for every path under `prefix`.  The
        per-method table is kept longest-prefix-first so nested
        prefixes resolve to the most specific handler."""
        table = self.prefix_routes.setdefault(method, [])
        table[:] = [(p, f) for p, f in table if p != prefix]
        table.append((prefix, fn))
        table.sort(key=lambda pf: -len(pf[0]))

    def _prefix_route(self, method: str, path: str) -> "Route | None":
        for prefix, fn in self.prefix_routes.get(method, ()):
            if path.startswith(prefix):
                return fn
        return None

    def start(self) -> None:
        tls = _tls_config()
        if self.role and self.role in async_front_roles():
            # asyncio front (async_front.py): one event loop
            # multiplexes every connection of this role's funnel —
            # same routes, guard, QoS admission, tracing spans and
            # request_seconds, different concurrency substrate.  The
            # already-bound listener socket is handed over so the
            # port the owner advertised stays the port served.
            from .async_front import AsyncFront
            self._async = AsyncFront(
                self, ssl_context=(tls.server_context()
                                   if tls is not None else None))
            self._async.start(self._httpd.socket)
            return
        if tls is not None:
            # TLS plane (weed/security/tls.go); connections handshake
            # in their handler threads (Server.finish_request), with
            # mTLS only CA-signed peers get through
            self._httpd.ssl_context = tls.server_context()
        # poll_interval: serve_forever's shutdown() handshake waits
        # for the accept loop's next selector tick — the 0.5 s
        # default parked EVERY server stop for ~0.25 s on average,
        # which across a tier-1 run's hundreds of role teardowns was
        # tens of seconds of pure sleep.  Accepts use the selector,
        # so a short tick costs ~nothing while serving.
        self._thread = threading.Thread(
            target=lambda: self._httpd.serve_forever(
                poll_interval=0.02), daemon=True)
        self._thread.start()

    def abort(self) -> None:
        """Close a bound listener that never served (owner-constructor
        failure unwind).  stop() is wrong here: shutdown() waits on
        the serve_forever loop's acknowledgement, which never comes
        from a loop that never started."""
        self._httpd.server_close()

    def stop(self) -> None:
        a = getattr(self, "_async", None)
        if a is not None:
            self._async = None
            a.stop()
            try:
                self._httpd.server_close()  # shared socket: idempotent
            except OSError:
                pass
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        # sever established keep-alive connections: in-flight handlers
        # see a dead socket, pooled clients get a connection error and
        # re-dial elsewhere — a stopped server must never ack another
        # request
        self._httpd.close_established()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"


# --- tiny client helpers -------------------------------------------------

def _tls_config():
    from .. import security
    return security.current().tls


def _dial(url: str) -> "tuple[str, object | None]":
    """(full url, ssl context) — https with the cluster CA pinned when
    the TLS plane is on; plain http otherwise.  Single funnel: every
    role's client traffic passes through http_bytes/http_json."""
    tls = _tls_config()
    if url.startswith("http"):
        return url, (tls.client_context() if tls and
                     url.startswith("https") else None)
    if tls is not None:
        return "https://" + url, tls.client_context()
    return "http://" + url, None


def _auth_for(url: str, headers: dict | None) -> dict:
    """Attach the process admin JWT to admin-plane requests — the analog
    of the reference's gRPC client factory applying the global security
    config to every dial (pb/grpc_client_server.go), so call sites don't
    plumb credentials."""
    from .. import security
    sec = security.current()
    if not sec.admin_key:
        return headers or {}
    path = urllib.parse.urlparse(
        url if url.startswith("http") else "http://" + url).path
    if not is_admin_path(path):
        return headers or {}
    headers = dict(headers or {})
    headers.setdefault("Authorization", f"Bearer {sec.admin_jwt()}")
    return headers


def is_admin_path(path: str) -> bool:
    """The admin/maintenance plane: volume+filer /admin/*, master grow /
    lock / raft endpoints, and heartbeats (all gRPC-only surfaces in the
    reference, gated there by grpc credentials — an unauthenticated
    raft RPC would let an outsider depose the leader)."""
    return path.startswith(("/admin/", "/cluster/raft/",
                            "/debug/")) or path in (
        "/vol/grow", "/cluster/lease_admin_token",
        "/cluster/release_admin_token", "/heartbeat")


def http_json(method: str, url: str, payload: dict | None = None,
              timeout: float = 30.0,
              headers: dict | None = None) -> dict:
    """JSON request; non-2xx responses return their parsed error body
    (callers check for an "error" key, mirroring gRPC status handling).
    Explicit `headers` win over the global-config auto-attach (a server
    with a per-instance security override passes its own tokens)."""
    data = json.dumps(payload).encode() if payload is not None else None
    headers = dict(headers or {})
    if data:
        headers.setdefault("Content-Type", "application/json")
    status, body, _ = _pooled_request(method, url, data,
                                      _auth_for(url, headers), timeout)
    try:
        parsed = json.loads(body or b"{}")
    except ValueError:
        parsed = {"error": body.decode(errors="replace")}
    if status >= 300 and isinstance(parsed, dict):
        parsed.setdefault("error", f"HTTP {status}")
    return parsed


def parse_range(header: str, total: int
                ) -> "tuple[int, int] | None | str":
    """One shared parser for `Range: bytes=...` (RFC 9110 §14):
    returns (offset, size), None for absent/malformed (serve the full
    body), or "unsatisfiable" for a well-formed range beyond EOF.
    Handles the suffix form bytes=-N (last N bytes)."""
    if not header.startswith("bytes="):
        return None
    spec = header[6:]
    if "," in spec:
        return None            # multipart ranges: serve full body
    lo, dash, hi = spec.partition("-")
    if not dash:
        return None
    try:
        if lo:
            offset = int(lo)
            if offset >= total > 0 or offset < 0:
                return "unsatisfiable"
            stop = min(int(hi) + 1, total) if hi else total
            if stop <= offset:
                return None
            return offset, stop - offset
        if hi:                 # suffix: last N bytes
            size = min(int(hi), total)
            if size <= 0:
                return None    # bytes=-0 / bytes=--5: not a range
            return total - size, size
    except ValueError:
        return None
    return None


class _RelaySourceError(OSError):
    """http_relay: the SOURCE leg died (real or injected) — the
    destination never answered, so no verdict probe is possible."""


def _fire_fault(site: str, key: str = "") -> "str | None":
    """faults.py hook for the client funnel (late import: httpd is on
    every role's startup path).  Returns the directive for
    truncate/drop arms; raises FaultInjected for error arms."""
    from .. import faults
    return faults.fire(site, key=key)


def http_download(url: str, dest_path: str,
                  headers: dict | None = None, timeout: float = 60.0,
                  chunk_size: int = 4 << 20) -> tuple[int, dict]:
    """GET `url` streaming the response body to `dest_path` in chunks —
    bounded memory no matter the file size (the worker's bulk volume
    pull; the reference streams CopyFile the same way,
    volume_server.proto:69).  Returns (status, response headers); on a
    non-2xx status dest_path is removed and the (small) error body is
    left unconsumed.

    `timeout` is a per-socket-operation stall bound, not a transfer
    bound: a 30GB pull may run for hours as long as bytes keep
    arriving, but a peer that goes silent costs 60s, not the old 600s
    (deadline plane satellite: a hung peer must not park a caller for
    minutes even with the plane disabled).  When the request carries a
    deadline the stall bound shrinks to the remaining budget."""
    import os as _os
    from ..util import deadline as _dl
    timeout = _dl.io_timeout(timeout, site="httpd.download")
    full_url, ctx = _dial(url)
    req = urllib.request.Request(
        full_url, headers=_dl.stamp_headers(_auth_for(url, headers)))
    # download into a sibling temp file and os.replace on success: a
    # mid-transfer failure (connection reset at 10GB of a 30GB pull)
    # must never leave a truncated file at dest_path for the store to
    # later mount, and an error must never clobber a pre-existing dest
    import uuid as _uuid
    tmp = f"{dest_path}.download.{_uuid.uuid4().hex}"
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=ctx) as resp:
            with open(tmp, "wb") as f:
                while True:
                    if _fire_fault("httpd.download.chunk",
                                   key=full_url) is not None:
                        # truncate/drop both mean "the source died
                        # mid-body": surface it, never os.replace a
                        # short file into place
                        raise IOError(
                            f"download {url}: fault-injected "
                            f"mid-body failure")
                    chunk = resp.read(chunk_size)
                    if not chunk:
                        break
                    f.write(chunk)
            _os.replace(tmp, dest_path)
            return resp.status, dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers)
    finally:
        try:
            _os.remove(tmp)
        except OSError:
            pass


def http_relay(src_url: str, dst_method: str, dst_url: str,
               headers: dict | None = None, timeout: float = 60.0,
               chunk_size: int = 4 << 20
               ) -> "tuple[int, int, bytes]":
    """Stream a GET of `src_url` straight into a chunked-encoded
    `dst_method dst_url` body: the push starts at the first downloaded
    chunk, so the two transfer legs overlap instead of staging the
    whole file through a temp relay, and RAM stays bounded by one
    chunk.  Returns (src_status, dst_status, dst_body); on a non-2xx
    source the upload never starts (dst_status 0).  `timeout` is a
    per-socket-operation stall bound (see http_download), deadline-
    derived when the request carries a budget."""
    import http.client

    from ..util import deadline as _dl
    timeout = _dl.io_timeout(timeout, site="httpd.relay")
    full_src, src_ctx = _dial(src_url)
    req = urllib.request.Request(
        full_src,
        headers=_dl.stamp_headers(_auth_for(src_url, headers)))
    try:
        resp = urllib.request.urlopen(req, timeout=timeout,
                                      context=src_ctx)
    except urllib.error.HTTPError as e:
        e.close()
        return e.code, 0, b""
    with resp:
        if resp.status != 200:
            return resp.status, 0, b""
        full_dst, dst_ctx = _dial(dst_url)
        parsed = urllib.parse.urlsplit(full_dst)
        target = parsed.path or "/"
        if parsed.query:
            target += "?" + parsed.query
        if parsed.scheme == "https":
            conn = http.client.HTTPSConnection(
                parsed.netloc, timeout=timeout, context=dst_ctx)
        else:
            conn = http.client.HTTPConnection(parsed.netloc,
                                              timeout=timeout)
        up_headers = dict(_dl.stamp_headers(
            _auth_for(dst_url, headers)))
        up_headers["Transfer-Encoding"] = "chunked"
        expected = resp.length  # None when the source streams chunked

        def chunks():
            # every SOURCE-side failure (real or fault-injected)
            # raises _RelaySourceError: the destination is then still
            # waiting for chunks, so the caller must NOT probe it for
            # a verdict — only send-socket failures mean the
            # destination spoke first
            sent = 0
            while True:
                try:
                    directive = _fire_fault("httpd.relay.chunk",
                                            key=full_dst)
                except OSError as e:  # armed `error`: source died
                    raise _RelaySourceError(str(e)) from None
                if directive == "truncate":
                    # simulated source death: raising (not returning)
                    # keeps the no-truncated-but-clean-upload rule —
                    # the aborted chunked stream errors on the dest
                    raise _RelaySourceError(
                        f"relay {src_url}: fault-injected "
                        f"truncation at {sent} bytes")
                if directive == "drop":
                    resp.close()
                    raise _RelaySourceError(
                        f"relay {src_url}: fault-injected "
                        f"connection drop at {sent} bytes")
                try:
                    chunk = resp.read(chunk_size)
                except OSError as e:
                    raise _RelaySourceError(
                        f"relay source {src_url} died at {sent} "
                        f"bytes: {e}") from None
                if not chunk:
                    if expected is not None and sent != expected:
                        # a source dying mid-body reads as plain EOF
                        # (no IncompleteRead with sized reads) — raise
                        # instead of finalizing a truncated upload as
                        # success; the aborted chunked stream also
                        # errors on the destination
                        raise _RelaySourceError(
                            f"relay source truncated at {sent} of "
                            f"{expected} bytes")
                    return
                sent += len(chunk)
                yield chunk

        try:
            try:
                conn.request(dst_method, target, body=chunks(),
                             headers=up_headers, encode_chunked=True)
            except _RelaySourceError:
                raise
            except OSError as send_err:
                # the send socket failed: the DESTINATION may have
                # rejected the upload mid-body (4xx/5xx + close) —
                # its verdict, not this broken pipe, is the root
                # cause; surface it when the response is readable
                # (http_stream_request's rule)
                import http.client as _hc
                try:
                    r = conn.getresponse()
                    return 200, r.status, r.read()
                except (OSError, _hc.HTTPException):
                    raise send_err from None
            r = conn.getresponse()
            return 200, r.status, r.read()
        finally:
            conn.close()


def http_stream_request(method: str, url: str, chunks,
                        headers: dict | None = None,
                        timeout: float = 60.0
                        ) -> "tuple[int, bytes]":
    """Send an iterable of byte windows as ONE chunked-encoded request
    body — the producer side of `Request.stream_body`.  The request is
    on the wire from the first window, so a producer that generates
    bytes incrementally (the scatter-encode GF pipeline) streams at
    wire speed with bounded memory instead of staging a whole shard.
    A producer exception tears the connection down mid-body — the
    receiver sees a short chunked stream and errors, never a
    truncated-but-clean upload.  Returns (status, body).  `timeout`
    is a per-socket-operation stall bound (see http_download),
    deadline-derived when the request carries a budget."""
    import http.client

    from ..util import deadline as _dl
    timeout = _dl.io_timeout(timeout, site="httpd.stream")
    full_url, ctx = _dial(url)
    parsed = urllib.parse.urlsplit(full_url)
    target = parsed.path or "/"
    if parsed.query:
        target += "?" + parsed.query
    if parsed.scheme == "https":
        conn = http.client.HTTPSConnection(
            parsed.netloc, timeout=timeout, context=ctx)
    else:
        conn = http.client.HTTPConnection(parsed.netloc,
                                          timeout=timeout)
    up_headers = dict(_dl.stamp_headers(_auth_for(url, headers)))
    try:
        # manual chunk framing instead of http.client's encode_chunked:
        # that path CONCATENATES header+chunk+trailer into a fresh
        # buffer per window (one extra multi-MB copy per send on the
        # scatter hot path); three sends straight off the caller's
        # memoryview keep the loop copy-free (sendall releases the GIL)
        conn.putrequest(method, target, skip_accept_encoding=True)
        for hk, hv in up_headers.items():
            conn.putheader(hk, hv)
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        if conn.sock is not None:
            import socket as _socket
            # the per-chunk framing interleaves small sends (size
            # line, CRLF) with multi-MB payload sends — Nagle would
            # park the small ones behind delayed ACKs
            conn.sock.setsockopt(_socket.IPPROTO_TCP,
                                 _socket.TCP_NODELAY, 1)
        from ..faults import FaultInjected as _FaultInjected
        try:
            for chunk in chunks:
                directive = _fire_fault("httpd.stream.chunk",
                                        key=full_url)
                if directive == "truncate":
                    # end the chunked stream EARLY but CLEANLY: the
                    # receiver sees valid framing with fewer bytes
                    # than the producer meant — exactly the case the
                    # CRC/byte-count commit handshake must catch
                    break
                if directive == "drop":
                    conn.sock.close()
                    raise OSError(
                        f"stream to {url}: fault-injected drop")
                n = len(chunk)
                if not n:
                    continue
                conn.send(b"%X\r\n" % n)
                conn.send(chunk)
                conn.send(b"\r\n")
            conn.send(b"0\r\n\r\n")
        except _FaultInjected:
            # an armed `error` fault (here or in the producer) stands
            # in for the WIRE dying, not the receiver answering: skip
            # the receiver-verdict probe below — with both ends alive
            # it would block on a receiver that still wants chunks —
            # and let the finally tear the connection down mid-body
            raise
        except OSError:
            # the receiver may have REJECTED the upload mid-body
            # (4xx/5xx + close) — its verdict is the root cause the
            # caller needs, not this broken pipe; surface it if the
            # response is readable
            import http.client as _hc
            try:
                resp = conn.getresponse()
                return resp.status, resp.read()
            except (OSError, _hc.HTTPException):
                pass
            raise
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def http_upload(method: str, url: str, src_path: str,
                headers: dict | None = None, timeout: float = 60.0
                ) -> tuple[int, bytes, dict]:
    """Send a file as the request body WITHOUT buffering it in memory:
    Content-Length is set from the file size and http.client streams
    the file object in blocks (the worker's bulk shard push).
    `timeout` is a per-socket-operation stall bound (see
    http_download), deadline-derived when a budget is armed."""
    import os as _os
    from ..util import deadline as _dl
    timeout = _dl.io_timeout(timeout, site="httpd.upload")
    size = _os.path.getsize(src_path)
    headers = dict(_dl.stamp_headers(_auth_for(url, headers)))
    headers["Content-Length"] = str(size)
    full_url, ctx = _dial(url)
    with open(src_path, "rb") as f:
        req = urllib.request.Request(full_url, data=f, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout,
                                        context=ctx) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)


# --- pooled keep-alive client (the hot data-plane funnel) ----------------
#
# urllib.request opens a fresh TCP connection per call; at benchmark
# concurrency that is 3 syscall round-trips of pure setup per 1KB
# needle, and measured ~30x below the reference's `weed benchmark`
# req/s (README.md:555-605 — its Go http.Client pools keep-alive
# connections).  This pool is PER-THREAD (no cross-thread locking on
# the hot path; a ThreadPool worker reuses its sockets) keyed by
# scheme+netloc.  POSTs are retried once ONLY when a REUSED socket
# died before the request hit the wire (stale keep-alive), never on a
# fresh connection — the same idempotency rule Go's Transport applies.

_thread_pools = threading.local()


def _pool() -> dict:
    p = getattr(_thread_pools, "conns", None)
    if p is None:
        p = _thread_pools.conns = {}
    return p


def _one_pooled_request(method: str, full_url: str, body,
                        headers: dict, timeout: float, ctx):
    """One request over the thread's pooled connection for the url's
    (scheme, netloc); returns (status, data, headers, location)."""
    import http.client

    parsed = urllib.parse.urlsplit(full_url)
    target = parsed.path or "/"
    if parsed.query:
        target += "?" + parsed.query
    key = (parsed.scheme, parsed.netloc)
    # connection-churn counters (the pre-work for the persistent-
    # connection rework, ROADMAP item 1): a healthy funnel reuses ~all
    # of its sockets; opened ~= requests means every call pays the TCP
    # setup tax the pool exists to amortize
    from ..stats import PROCESS as _process_metrics
    for attempt in (0, 1):
        conn = _pool().get(key)
        reused = conn is not None
        if reused:
            _process_metrics.counter_add(
                "pool_connections_reused_total", 1.0,
                help_text="pooled requests served over a kept-alive "
                          "socket", peer=parsed.netloc)
        if conn is None:
            _fire_fault("httpd.pool.connect", key=parsed.netloc)
            _process_metrics.counter_add(
                "pool_connections_opened_total", 1.0,
                help_text="fresh sockets dialed by the pooled client",
                peer=parsed.netloc)
            if parsed.scheme == "https":
                conn = http.client.HTTPSConnection(
                    parsed.netloc, timeout=timeout, context=ctx)
            else:
                conn = http.client.HTTPConnection(
                    parsed.netloc, timeout=timeout)
            _pool()[key] = conn
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        try:
            _fire_fault("httpd.pool.request",
                        key=f"{parsed.netloc}{target}")
            conn.request(method, target, body=body, headers=headers)
        except (http.client.HTTPException, OSError) as e:
            # send failed: the request never executed — safe to retry
            # any method once on a stale reused socket
            conn.close()
            _pool().pop(key, None)
            if reused and attempt == 0:
                continue
            if isinstance(e, OSError):
                raise
            raise OSError(f"http request failed: {e!r}") from e
        try:
            resp = conn.getresponse()
            data = resp.read()
        except (http.client.HTTPException, OSError) as e:
            # request may have EXECUTED server-side (response lost):
            # transparently retrying a POST here would double-execute
            # non-idempotent operations (publish, delete counters), so
            # only idempotent work (RFC 9110 §9.2.2 methods, or a
            # caller-DECLARED X-Idempotent POST such as truncate-to-
            # size) re-issues — and only for the stale-keep-alive
            # race: a REUSED pooled socket that died with ZERO
            # response bytes is a connection-state artifact, not a
            # peer-health verdict, so it re-issues inline on a fresh
            # dial without feeding the breaker or spending retry
            # budget.  Every other failure (timeout on a hung peer,
            # mid-response reset, fresh-connection death) surfaces to
            # the ONE outer policy in _pooled_request (util/retry),
            # which re-issues idempotent work under backoff + budget —
            # keeping this inner loop from stacking multiplicatively
            # with the outer attempts.  Undeclared POSTs still surface
            # the executed-or-not ambiguity (Go Transport's rule —
            # blind replay would double-publish MQ messages).
            conn.close()
            _pool().pop(key, None)
            if attempt == 0 and reused and \
                    isinstance(e, http.client.RemoteDisconnected) and \
                    (method in ("GET", "HEAD", "PUT", "DELETE",
                                "OPTIONS")
                     or headers.get("X-Idempotent") == "1"):
                continue
            if isinstance(e, OSError):
                raise
            raise OSError(f"http response failed: {e!r}") from e
        if resp.will_close:
            conn.close()
            _pool().pop(key, None)
        return (resp.status, data, dict(resp.headers),
                resp.getheader("Location"))
    raise OSError("unreachable")  # pragma: no cover


def _pooled_request(method: str, url: str, body, headers: dict,
                    timeout: float, max_redirects: int = 3):
    # forward the active request id + trace parent on every internal
    # hop (util/request_id, tracing.py): the receiving server adopts
    # both, so one id traces gateway -> filer -> volume in the logs
    # and the receiver's server span hangs under this caller's span
    from .. import tracing
    from ..util.request_id import HEADER as _RID_HEADER
    from ..util.request_id import get_request_id
    rid = get_request_id()
    if rid and _RID_HEADER not in headers:
        headers = dict(headers)
        headers[_RID_HEADER] = rid
    tp = tracing.traceparent_header()
    if tp and tracing.HEADER not in headers:
        headers = dict(headers)
        headers[tracing.HEADER] = tp
    full_url, ctx = _dial(url)
    # unified failure policy (util/retry): consult the peer's circuit
    # breaker before dialing (a tripped peer fails fast instead of
    # burning a timeout), feed every transport outcome back into the
    # health map, and re-issue idempotent requests under the capped
    # jittered backoff + process retry budget.  POSTs keep exactly the
    # seed's semantics: only `_one_pooled_request`'s provably-never-
    # executed send-failed rule re-issues them.
    from ..util import deadline as _dl
    from ..util import retry as _retry
    for _hop in range(max_redirects):
        peer = urllib.parse.urlsplit(full_url).netloc
        idempotent = method in ("GET", "HEAD", "PUT", "DELETE",
                                "OPTIONS") or \
            headers.get("X-Idempotent") == "1"

        def _attempt(u=full_url):
            # deadline plane, per ATTEMPT: the socket timeout is
            # re-derived from the budget remaining NOW (a retry after
            # backoff has less), and the forwarded header carries the
            # fresh remaining ms so the receiver can never out-wait
            # this caller.  An already-spent budget raises before the
            # dial (DeadlineExceeded — retry_call refuses to re-issue
            # it).  Unarmed requests: two contextvar reads, the seed
            # timeout, no header.
            t = _dl.io_timeout(timeout, site="httpd.pool")
            return _one_pooled_request(method, u, body,
                                       _dl.stamp_headers(headers),
                                       t, ctx)

        status, data, rheaders, location = _retry.retry_call(
            _attempt, site="httpd.pool", peer=peer,
            idempotent=idempotent)
        if status in (301, 302, 307, 308) and location and \
                method in ("GET", "HEAD"):
            # urllib-parity redirect following for read paths
            full_url = urllib.parse.urljoin(full_url, location)
            continue
        return status, data, rheaders
    return status, data, rheaders


def http_bytes(method: str, url: str, body: bytes | None = None,
               headers: dict | None = None, timeout: float = 60.0
               ) -> tuple[int, bytes, dict]:
    return _pooled_request(method, url, body,
                           _auth_for(url, headers), timeout)
