"""Filer HTTP server (weed/server/filer_server.go + handlers).

Public API mirrors the reference's filer HTTP surface:
  POST/PUT /path/to/file     upload (auto-chunked)
  GET      /path/to/file     ranged read
  GET      /path/to/dir/     JSON listing (?limit=&lastFileName=&prefix=)
  DELETE   /path             (?recursive=true for directories)
  HEAD     /path             existence/size probe
plus JSON-over-HTTP mirrors of key filer.proto RPCs:
  GET  /__meta__/lookup?path=         <- filer.proto LookupDirectoryEntry
  POST /__meta__/rename               <- filer.proto AtomicRenameEntry
  GET  /__meta__/events?sinceNs=      <- SubscribeMetadata (poll form)
"""

from __future__ import annotations

from ..filer import Entry, Filer
from ..filer.filer_store import SqliteStore
from .httpd import HttpServer, Request


class FilerServer:
    def __init__(self, master: str, host: str = "127.0.0.1",
                 port: int = 0, store_path: str = ":memory:",
                 collection: str = "", replication: str = "",
                 meta_log_dir: str | None = None):
        if meta_log_dir is None and store_path != ":memory:":
            # persist the metadata log beside the store by default —
            # subscribers must survive a filer restart
            # (filer_notify_append.go)
            meta_log_dir = store_path + ".metalog"
        self.filer = Filer(master, SqliteStore(store_path),
                           collection=collection,
                           replication=replication,
                           meta_log_dir=meta_log_dir)
        self.http = HttpServer(host, port)
        self.http.route("GET", "/__meta__/lookup", self._meta_lookup)
        self.http.route("POST", "/__meta__/rename", self._meta_rename)
        self.http.route("POST", "/__meta__/set_attrs",
                        self._meta_set_attrs)
        self.http.route("GET", "/__meta__/events", self._meta_events)
        from .debug import install_debug_routes
        install_debug_routes(self.http)  # util/grace/pprof.go analog
        self.http.guard = self._guard
        self.http.fallback = self._dispatch

    def _guard(self, req: Request):
        """Admin-plane gate (guard.go): the filer's /debug plane must
        honor the same admin JWT as every other role."""
        from .. import security
        from .httpd import is_admin_path
        if is_admin_path(req.path):
            err = security.current().check_admin(
                req.query, req.headers, req.remote_ip)
            if err:
                return 401, {"error": err}
        return None

    def start(self):
        self.http.start()
        return self

    def stop(self):
        self.http.stop()
        self.filer.store.close()
        self.filer.meta_log.close()

    @property
    def url(self) -> str:
        return self.http.url

    # -- dispatch ---------------------------------------------------------

    def _dispatch(self, req: Request):
        path = req.path
        if req.method in ("POST", "PUT"):
            return self._put(req, path)
        if req.method in ("GET", "HEAD"):
            return self._get(req, path)
        if req.method == "DELETE":
            return self._delete(req, path)
        return 405, {"error": "method not allowed"}

    def _put(self, req: Request, path: str):
        if path.endswith("/"):
            # mkdir (filer_server_handlers_write.go mkdir on trailing /)
            e = Entry(path.rstrip("/") or "/", is_directory=True)
            self.filer.create_entry(e)
            return 201, {"name": e.name}
        mime = req.headers.get("Content-Type", "")
        if mime == "application/x-www-form-urlencoded":
            mime = ""
        entry = self.filer.write_file(path, req.body, mime=mime)
        return 201, {"name": entry.name, "size": entry.total_size()}

    def _get(self, req: Request, path: str):
        if path.endswith("/") or path == "":
            return self._list(req, path or "/")
        entry = self.filer.find_entry(path)
        if entry is None:
            return 404, {"error": f"{path} not found"}
        if entry.is_directory:
            return self._list(req, path)
        rng = req.headers.get("Range", "")
        offset, size = 0, None
        file_size = entry.total_size()
        try:
            if rng.startswith("bytes="):
                lo, _, hi = rng[6:].partition("-")
                if lo:
                    offset = int(lo)
                    if hi:
                        size = int(hi) - offset + 1
                elif hi:
                    size = min(int(hi), file_size)  # suffix: last N
                    offset = file_size - size
                else:
                    raise ValueError(rng)
        except ValueError:
            rng = ""  # malformed Range: serve the full body (RFC 9110)
            offset, size = 0, None
        data = self.filer.read_file(path, offset, size)
        mime = entry.attributes.mime or "application/octet-stream"
        if rng:
            end = offset + len(data) - 1
            return 206, (data, {
                "Content-Type": mime,
                "Content-Range": f"bytes {offset}-{end}/{file_size}"})
        return 200, (data, mime)

    def _list(self, req: Request, path: str):
        limit = int(req.query.get("limit", 1000))
        last = req.query.get("lastFileName", "")
        prefix = req.query.get("prefix", "")
        entries = self.filer.list_directory(
            path.rstrip("/") or "/", start_file=last, limit=limit,
            prefix=prefix)
        return 200, {
            "path": path,
            "entries": [e.to_json() for e in entries],
            "lastFileName": entries[-1].name if entries else "",
            "shouldDisplayLoadMore": len(entries) >= limit,
        }

    def _delete(self, req: Request, path: str):
        recursive = req.query.get("recursive", "") == "true"
        try:
            self.filer.delete_entry(path.rstrip("/") or "/",
                                    recursive=recursive)
        except IsADirectoryError as e:
            return 409, {"error": str(e)}
        return 204, b""

    # -- meta RPC mirrors -------------------------------------------------

    def _meta_lookup(self, req: Request):
        entry = self.filer.find_entry(req.query["path"])
        if entry is None:
            return 404, {"error": "not found"}
        return 200, entry.to_json()

    def _meta_rename(self, req: Request):
        b = req.json()
        try:
            self.filer.rename(b["oldPath"], b["newPath"])
        except FileNotFoundError as e:
            return 404, {"error": str(e)}
        return 200, {}

    def _meta_set_attrs(self, req: Request):
        """Attribute-only update (filer.proto UpdateEntry with unchanged
        chunks) — filer.sync uses this to propagate mode/uid/gid/mtime
        that the content PUT cannot carry."""
        b = req.json()
        entry = self.filer.find_entry(b["path"])
        if entry is None:
            return 404, {"error": "not found"}
        from ..filer.entry import Attributes
        entry.attributes = Attributes.from_json(b.get("attributes", {}))
        self.filer.create_entry(entry, create_parents=False)
        return 200, {}

    def _meta_events(self, req: Request):
        since = int(req.query.get("sinceNs", 0))
        limit = int(req.query.get("limit", 0))
        return 200, {"events": self.filer.events_since(since, limit)}
